"""Trusted light-block store.

Reference: light/store/store.go (interface) + light/store/db/db.go (the
only implementation: size-tracked, pruning, first/last scans). Backed by
the same KVStore abstraction as every other store in the framework
(store/db.py: MemDB / SQLite), keyed lb/<height:020d>.
"""

from __future__ import annotations

from typing import Optional

from cometbft_tpu.store.db import KVStore
from cometbft_tpu.types.light import LightBlock

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + b"%020d" % height


class LightStore:
    """light/store/db/db.go:24-214."""

    def __init__(self, db: KVStore):
        self.db = db
        self._heights: list[int] = sorted(
            int(k[len(_PREFIX):])
            for k, _ in db.iterate(_PREFIX, _PREFIX + b"\xff")
        )

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("lightBlock.Height <= 0")
        self.db.set(_key(lb.height), lb.to_proto())
        if not self._heights or lb.height != self._heights[-1]:
            import bisect

            i = bisect.bisect_left(self._heights, lb.height)
            if i >= len(self._heights) or self._heights[i] != lb.height:
                self._heights.insert(i, lb.height)

    def light_block(self, height: int) -> Optional[LightBlock]:
        data = self.db.get(_key(height))
        return LightBlock.from_proto(data) if data is not None else None

    def latest_light_block(self) -> Optional[LightBlock]:
        return self.light_block(self._heights[-1]) if self._heights else None

    def first_light_block(self) -> Optional[LightBlock]:
        return self.light_block(self._heights[0]) if self._heights else None

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        """db.go:170-189 LightBlockBefore: greatest stored height < height."""
        import bisect

        i = bisect.bisect_left(self._heights, height)
        return self.light_block(self._heights[i - 1]) if i > 0 else None

    def light_block_by_hash(self, want: bytes) -> Optional[LightBlock]:
        """Linear scan over trusted blocks (proxy header_by_hash; the store
        is bounded by prune())."""
        for h in self._heights:
            lb = self.light_block(h)
            if lb is not None and lb.signed_header.header.hash() == want:
                return lb
        return None

    def delete_light_block(self, height: int) -> None:
        self.db.delete(_key(height))
        try:
            self._heights.remove(height)
        except ValueError:
            pass

    def prune(self, size: int) -> None:
        """db.go:129-160: keep the newest `size` blocks."""
        while len(self._heights) > size:
            self.delete_light_block(self._heights[0])

    def prune_expired(self, trusting_period_ns: int, now) -> int:
        """Drop every block whose trusting period has lapsed at `now` —
        an expired header can no longer anchor any verification, so
        keeping it only wastes the size budget. Returns the count pruned.
        (The serving plane's checkpoint cache applies the same rule
        in-memory; this is the persistent-store twin.)"""
        pruned = 0
        for h in list(self._heights):
            lb = self.light_block(h)
            if lb is None:
                continue
            if lb.time.unix_ns() + trusting_period_ns <= now.unix_ns():
                self.delete_light_block(h)
                pruned += 1
            else:
                break  # heights ascend and so do header times
        return pruned

    def size(self) -> int:
        return len(self._heights)
