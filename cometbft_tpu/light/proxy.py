"""Light-client proxy: a local RPC endpoint whose answers are VERIFIED.

Reference: light/proxy/proxy.go:20-80 + light/rpc/client.go — the
`cometbft light <chainID> --primary --witness ...` daemon. Every block-ish
route answers from (or is cross-checked against) a light-client-verified
header chain:

  - light_block / header / header_by_hash / commit / validators answer
    straight from verified light blocks (bisection against the primary,
    divergence cross-check against witnesses — light/client.py);
  - block / block_by_hash fetch the raw block from the primary, then prove
    the payload against the VERIFIED header: the tx set must hash to the
    verified data_hash, and the served header IS the verified one — a lying
    primary cannot alter a single byte of what this proxy returns;
  - broadcast_tx_* / abci_query / status pass through to the primary,
    marked unverified (abci_query proof-op verification is app-specific;
    the reference's KeyPathFn hook is likewise opt-in).

A primary caught lying fails verification (wrong commit signatures over a
forged header → ErrVerification; conflicting-but-valid headers → witness
divergence handling with attack evidence, light/client.py:298-380); the
proxy surfaces the error instead of the forged data.

Serving plumbing reuses rpc/server.RPCServer with this module's route
table (no node behind it). Websocket subscriptions are RELAYED to the
primary's /websocket endpoint (reference: light/proxy/proxy.go wires the
node's event routes through light/rpc.Client): subscribe/unsubscribe and
the resulting event stream pass through UNVERIFIED — like the reference,
event payloads carry no commit proof; verified state always comes from
the block-ish routes above.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import os
import urllib.parse
import urllib.request

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.light.rpc_provider import normalize_rpc_url
from cometbft_tpu.rpc.core import RPCError, _b64, _hex, header_dict
from cometbft_tpu.rpc.server import RPCServer
from cometbft_tpu.types.block import Data


class _PrimaryRPC:
    """Raw JSON-RPC calls to the primary node (unverified plane). Uses
    POST with a JSON-RPC body so params keep their exact JSON types — a
    GET re-encode would strip quoting and retype base64/bool params on the
    primary's URI handler."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = normalize_rpc_url(base_url)
        self.timeout = timeout

    async def call(self, route: str, params: dict | None = None) -> dict:
        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": route,
            "params": params or {},
        }).encode()

        def _post():
            req = urllib.request.Request(
                self.base_url + "/", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.load(r)

        doc = await asyncio.to_thread(_post)
        if "error" in doc:
            e = doc["error"]
            raise RPCError(e.get("code", -32603), f"primary: {e.get('message', '')}")
        return doc["result"]


class _UpstreamWS:
    """Minimal RFC 6455 client to the primary's /websocket endpoint
    (client->server frames masked, as the RFC requires)."""

    def __init__(self, base_url: str):
        u = urllib.parse.urlparse(normalize_rpc_url(base_url))
        if u.scheme in ("https", "wss"):
            # this client speaks plaintext only; silently opening a clear
            # socket to an https primary would leak the relay's traffic
            raise ValueError(
                f"light proxy: TLS primaries are not supported for the "
                f"websocket relay (got {base_url!r}); use an http:// "
                "primary or terminate TLS in front of the proxy")
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None

    async def connect(self, timeout: float = 10.0) -> None:
        self.reader, self.writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.writer.write(
            (f"GET /websocket HTTP/1.1\r\nHost: {self.host}:{self.port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
             "\r\n").encode())
        await self.writer.drain()
        status = await self.reader.readline()
        if b"101" not in status:
            raise ConnectionError(f"ws upgrade rejected: {status!r}")
        from cometbft_tpu.rpc.server import WS_GUID

        want = base64.b64encode(
            hashlib.sha1((key + WS_GUID).encode()).digest()).decode()
        accept = ""
        while True:
            line = await self.reader.readline()
            if line in (b"\r\n", b""):
                break
            name, _, val = line.decode().partition(":")
            if name.strip().lower() == "sec-websocket-accept":
                accept = val.strip()
        if accept != want:
            raise ConnectionError("ws upgrade: bad Sec-WebSocket-Accept")

    async def send_json(self, payload: dict) -> None:
        data = json.dumps(payload).encode()
        mask = os.urandom(4)
        ln = len(data)
        head = b"\x81"  # FIN + text
        if ln < 126:
            head += bytes([0x80 | ln])
        elif ln < (1 << 16):
            head += bytes([0x80 | 126]) + ln.to_bytes(2, "big")
        else:
            head += bytes([0x80 | 127]) + ln.to_bytes(8, "big")
        body = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        self.writer.write(head + mask + body)
        await self.writer.drain()

    async def recv_json(self) -> dict | None:
        """Next data message as JSON; None on close. Server frames are
        unmasked; rpc/server._ws_recv handles either."""
        from cometbft_tpu.rpc.server import _ws_recv

        while True:
            opcode, data, _controls = await _ws_recv(self.reader)
            if opcode == 0x8:
                return None
            if opcode in (0x1, 0x2):
                return json.loads(data)

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class ProxyEnv:
    """Route environment for the verified proxy (mirrors rpc/core
    Environment's handler signature: async fn(params) -> result dict)."""

    def __init__(self, client, primary_url: str):
        self.client = client  # light.Client
        self.primary = _PrimaryRPC(primary_url)
        self.primary_url = primary_url
        self._upstreams: dict[str, _UpstreamWS] = {}
        # fail at construction, not inside some client's first ws
        # subscribe: the websocket relay cannot speak TLS (_UpstreamWS
        # raises the same error as defense in depth)
        if urllib.parse.urlparse(
                normalize_rpc_url(primary_url)).scheme in ("https", "wss"):
            raise ValueError(
                f"light proxy: TLS primaries are not supported for the "
                f"websocket relay (got {primary_url!r}); use an http:// "
                "primary or terminate TLS in front of the proxy")

    async def _verified(self, params: dict):
        h = params.get("height")
        if h in (None, ""):
            lb = await self.client.update()
            if lb is None:
                lb = self.client.store.latest_light_block()
            if lb is None:
                raise RPCError(-32603, "no trusted light block yet")
            return lb
        return await self.client.verify_light_block_at_height(int(h))

    # ------------------------------------------------------ verified plane

    async def light_block(self, params: dict) -> dict:
        lb = await self._verified(params)
        return {"height": str(lb.height), "light_block": _b64(lb.to_proto())}

    async def header(self, params: dict) -> dict:
        lb = await self._verified(params)
        return {"header": header_dict(lb.signed_header.header)}

    async def header_by_hash(self, params: dict) -> dict:
        want = bytes.fromhex(params["hash"])
        lb = self.client.store.light_block_by_hash(want)
        if lb is None:
            raise RPCError(-32603, "header not found among trusted light blocks")
        return {"header": header_dict(lb.signed_header.header)}

    @staticmethod
    def _commit_dict(c) -> dict:
        return {
            "height": str(c.height),
            "round": c.round_,
            "block_id": {
                "hash": _hex(c.block_id.hash),
                "parts": {
                    "total": c.block_id.part_set_header.total,
                    "hash": _hex(c.block_id.part_set_header.hash)},
            },
            "signatures": [
                {
                    "block_id_flag": int(cs.block_id_flag),
                    "validator_address": _hex(cs.validator_address),
                    "timestamp": str(cs.timestamp),
                    "signature": _b64(cs.signature) if cs.signature else None,
                }
                for cs in c.signatures
            ],
        }

    async def commit(self, params: dict) -> dict:
        lb = await self._verified(params)
        return {
            "canonical": True,
            "signed_header": {
                "header": header_dict(lb.signed_header.header),
                "commit": self._commit_dict(lb.signed_header.commit),
            },
        }

    async def validators(self, params: dict) -> dict:
        lb = await self._verified(params)
        return {
            "block_height": str(lb.height),
            "validators": [
                {
                    "address": _hex(v.address),
                    "pub_key": {"type": v.pub_key.type_(),
                                "value": _b64(v.pub_key.bytes_())},
                    "voting_power": str(v.voting_power),
                    "proposer_priority": str(v.proposer_priority),
                }
                for v in lb.validator_set.validators
            ],
            "count": str(len(lb.validator_set.validators)),
            "total": str(len(lb.validator_set.validators)),
        }

    async def block(self, params: dict) -> dict:
        """Raw block from the primary, proven against the verified header:
        served header = verified header; primary txs must hash to its
        data_hash; last_commit is the VERIFIED commit for height-1 (block
        h's last_commit IS the canonical commit for h-1, which the light
        chain already carries), cross-checked against last_commit_hash.
        Evidence entries pass through as unverified summaries (they are
        summaries on the node RPC too; the evidence_hash in the verified
        header is the authoritative statement)."""
        lb = await self._verified(params)
        raw = await self.primary.call("block", {"height": str(lb.height)})
        txs = [base64.b64decode(t) for t in raw["block"]["data"]["txs"]]
        got = Data(txs=txs).hash()
        want = lb.signed_header.header.data_hash
        if got != want:
            raise RPCError(
                -32603,
                f"primary returned txs not matching the verified data_hash "
                f"at height {lb.height} (got {got.hex()}, want {want.hex()})")
        last_commit = None
        if lb.height > 1:
            prev = await self.client.verify_light_block_at_height(lb.height - 1)
            c = prev.signed_header.commit
            if c.hash() != lb.signed_header.header.last_commit_hash:
                raise RPCError(
                    -32603,
                    f"verified commit for {lb.height - 1} does not hash to "
                    f"the verified header's last_commit_hash")
            last_commit = self._commit_dict(c)
        return {
            "block_id": {"hash": _hex(lb.signed_header.header.hash())},
            "block": {
                "header": header_dict(lb.signed_header.header),
                "data": {"txs": [_b64(t) for t in txs]},
                "evidence": raw["block"].get("evidence", {"evidence": []}),
                "last_commit": last_commit,
            },
        }

    # ---------------------------------------------------- unverified plane

    async def health(self, _params: dict) -> dict:
        return {}

    async def status(self, _params: dict) -> dict:
        res = await self.primary.call("status")
        res["light_client_info"] = {
            "primary": self.client.primary.id_(),
            "witnesses": [w.id_() for w in self.client.witnesses],
            "first_trusted_height": str(self.client.first_trusted_height()),
            "last_trusted_height": str(self.client.last_trusted_height()),
        }
        return res

    async def abci_query(self, params: dict) -> dict:
        return await self.primary.call("abci_query", params)

    async def abci_info(self, _params: dict) -> dict:
        return await self.primary.call("abci_info")

    async def broadcast_tx_sync(self, params: dict) -> dict:
        return await self.primary.call("broadcast_tx_sync", params)

    async def broadcast_tx_async(self, params: dict) -> dict:
        return await self.primary.call("broadcast_tx_async", params)

    async def broadcast_tx_commit(self, params: dict) -> dict:
        return await self.primary.call("broadcast_tx_commit", params)

    # ------------------------------------------- websocket passthrough

    async def ws_passthrough(self, req: dict, client_id: str, tasks,
                             send_json) -> None:
        """Relay subscribe/unsubscribe to the primary's /websocket and pump
        its event stream back to the local client — UNVERIFIED, as in the
        reference's light proxy (events carry no commit proof either way)."""
        up = self._upstreams.get(client_id)
        if up is None:
            up = _UpstreamWS(self.primary_url)
            try:
                await up.connect()
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                await send_json({
                    "jsonrpc": "2.0", "id": req.get("id", -1),
                    "error": {"code": -32603,
                              "message": f"primary ws unavailable: {e}"}})
                return
            self._upstreams[client_id] = up

            async def pump():
                try:
                    while True:
                        msg = await up.recv_json()
                        if msg is None:
                            return
                        await send_json(msg)
                except (ConnectionError, asyncio.IncompleteReadError, OSError):
                    pass

            tasks.spawn(pump(), name=f"ws-upstream-{client_id}")
        await up.send_json(req)

    async def ws_client_closed(self, client_id: str) -> None:
        up = self._upstreams.pop(client_id, None)
        if up is not None:
            up.close()

    def routes(self) -> dict:
        return {
            "health": self.health,
            "status": self.status,
            "light_block": self.light_block,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "commit": self.commit,
            "validators": self.validators,
            "block": self.block,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_commit": self.broadcast_tx_commit,
        }


class LightProxy(BaseService):
    """The daemon: a light.Client plus an RPCServer serving ProxyEnv."""

    def __init__(self, client, primary_url: str, listen_addr: str,
                 logger: cmtlog.Logger | None = None):
        super().__init__("LightProxy", logger or cmtlog.default().with_fields(
            module="light-proxy"))
        self.client = client
        self.env = ProxyEnv(client, primary_url)

        class _Cfg:
            laddr = listen_addr

        self.server = RPCServer(
            None, _Cfg(), logger=self.logger, env=self.env)

    @property
    def bound_addr(self) -> str:
        return self.server.bound_addr

    async def on_start(self) -> None:
        await self.client.initialize()
        await self.server.start()

    async def on_stop(self) -> None:
        await self.server.stop()
