"""Light-client error taxonomy (reference: light/errors.go)."""

from __future__ import annotations


class LightClientError(Exception):
    """Base for all light-client failures."""


class ErrOldHeaderExpired(LightClientError):
    """light/errors.go:15 — trusted header is outside the trusting period."""

    def __init__(self, expired_at, now):
        super().__init__(f"old header has expired at {expired_at} (now: {now})")
        self.expired_at = expired_at
        self.now = now


class ErrNewValSetCantBeTrusted(LightClientError):
    """light/errors.go:26 — less than trust-level of the trusted valset
    signed the new header; bisection should try a closer header."""

    def __init__(self, cause):
        super().__init__(f"can't trust new val set: {cause}")
        self.cause = cause


class ErrInvalidHeader(LightClientError):
    """light/errors.go:36 — the new header is outright invalid (the provider
    is faulty or lying; drop it)."""

    def __init__(self, cause):
        super().__init__(f"invalid header: {cause}")
        self.cause = cause


class ErrVerificationFailed(LightClientError):
    """light/errors.go:44 — verification failed at some intermediate height
    during bisection."""

    def __init__(self, from_height: int, to_height: int, cause: Exception):
        super().__init__(
            f"verify from #{from_height} to #{to_height} failed: {cause}"
        )
        self.from_height = from_height
        self.to_height = to_height
        self.cause = cause


class ErrLightClientAttack(LightClientError):
    """light/errors.go:60 — a witness disagreed with the primary and the
    divergence was confirmed: someone is lying."""


class ErrFailedHeaderCrossReferencing(LightClientError):
    """light/errors.go:55 — every witness failed to provide a comparison
    header; can't establish divergence."""


class ErrNoWitnesses(LightClientError):
    """light/errors.go:69 — no witnesses connected; cross-checking is off."""


class ErrLightBlockNotFound(LightClientError):
    """light/provider/errors.go:12 — provider has no block at that height."""


class ErrHeightTooHigh(LightClientError):
    """light/provider/errors.go:16 — height above the provider's head."""


class ErrBadLightBlock(LightClientError):
    """light/provider/errors.go:20 — provider returned a malformed block."""
