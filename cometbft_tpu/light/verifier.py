"""Stateless light-client header verification.

Reference: light/verifier.go:32-245. Three entry points:

  verify_adjacent      — height X → X+1: the new valset hash must equal the
                         trusted header's next_validators_hash, then +2/3 of
                         the new set must have signed.
  verify_non_adjacent  — height X → Y > X+1: trust-level (default 1/3) of the
                         TRUSTED valset must appear in the new commit, then
                         +2/3 of the new set must have signed.
  verify               — dispatch on adjacency.

Both commit checks ride the batch-first crypto boundary
(types/validation.py): on the TPU backend every signature row of a commit is
one device batch — for the 500-validator BASELINE config-4 chains that is
the whole workload, so bisection hops verify at device batch throughput
rather than per-signature host speed.
"""

from __future__ import annotations

from cometbft_tpu.types.light import LightBlock, SignedHeader
from cometbft_tpu.types.validation import (
    ErrNotEnoughVotingPowerSigned,
    Fraction,
    prefetch_staged,
    stage_verify_commit_light,
    stage_verify_commit_light_trusting,
    verify_commit_light,
)
from cometbft_tpu.types.validator import ValidatorSet
from cometbft_tpu.utils import cmttime

from cometbft_tpu.light.errors import (
    ErrInvalidHeader,
    ErrNewValSetCantBeTrusted,
    ErrOldHeaderExpired,
)

# light/verifier.go:16 — one correct validator is enough to trust a new header
DEFAULT_TRUST_LEVEL = Fraction(1, 3)


def validate_trust_level(lvl: Fraction) -> None:
    """light/verifier.go:197-205: trust level must be in [1/3, 1]."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(f"trustLevel must be within [1/3, 1], given {lvl}")


def header_expired(h: SignedHeader, trusting_period_ns: int, now: cmttime.Timestamp) -> bool:
    """light/verifier.go:208-211."""
    expiration_ns = h.time.unix_ns() + trusting_period_ns
    return expiration_ns <= now.unix_ns()


def _verify_new_header_and_vals(
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now: cmttime.Timestamp,
    max_clock_drift_ns: int,
) -> None:
    """light/verifier.go:153-193."""
    try:
        untrusted_header.validate_basic(trusted_header.chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"untrusted header invalid: {e}") from e
    if untrusted_header.height <= trusted_header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted_header.height} to be greater "
            f"than trusted header height {trusted_header.height}"
        )
    if untrusted_header.time.unix_ns() <= trusted_header.time.unix_ns():
        raise ErrInvalidHeader(
            f"expected new header time {untrusted_header.time} to be after "
            f"old header time {trusted_header.time}"
        )
    if untrusted_header.time.unix_ns() >= now.unix_ns() + max_clock_drift_ns:
        raise ErrInvalidHeader(
            f"new header has a time from the future {untrusted_header.time} "
            f"(now: {now}; max clock drift: {max_clock_drift_ns}ns)"
        )
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"expected new header validators ({untrusted_header.header.validators_hash.hex()}) "
            f"to match those supplied ({untrusted_vals.hash().hex()}) "
            f"at height {untrusted_header.height}"
        )


def verify_adjacent(
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: cmttime.Timestamp,
    max_clock_drift_ns: int,
) -> None:
    """light/verifier.go:93-135."""
    if untrusted_header.height != trusted_header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted_header.time.add_ns(trusting_period_ns), now
        )
    _verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift_ns
    )
    if untrusted_header.header.validators_hash != trusted_header.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted_header.header.next_validators_hash.hex()}) to match "
            f"those from new header ({untrusted_header.header.validators_hash.hex()})"
        )
    try:
        # sync class by default: a light hop must not preempt consensus
        # flushes in the global verify scheduler. The fleet service
        # (light/fleet.py) sets the ambient LIGHT class around its
        # bisections — external serving traffic yields to a catching-up
        # node's own sync windows too — and that choice is respected here.
        from cometbft_tpu import sched

        klass = sched.LIGHT if sched.current_class() == sched.LIGHT else sched.SYNC
        with sched.work_class(klass):
            verify_commit_light(
                trusted_header.chain_id,
                untrusted_vals,
                untrusted_header.commit.block_id,
                untrusted_header.height,
                untrusted_header.commit,
            )
    except Exception as e:  # noqa: BLE001 — uniform ErrInvalidHeader wrapping
        raise ErrInvalidHeader(e) from e


def verify_non_adjacent(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: cmttime.Timestamp,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:32-90."""
    if untrusted_header.height == trusted_header.height + 1:
        raise ValueError("headers must be non adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted_header.time.add_ns(trusting_period_ns), now
        )
    _verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift_ns
    )
    # Both signature checks of a bisection hop — trust-level of the OLD set
    # and +2/3 of the NEW set over the same commit — are staged on the
    # device together and resolved with ONE fetch (the sync path paid two
    # sequential round trips per hop; over a high-RTT link that dominated
    # bisection wall time). With the reduced-fetch protocol that one fetch
    # is 8 bytes/batch of headers on the happy path — the per-lane masks
    # cross the tunnel only when a commit actually fails. Power thresholds
    # still raise synchronously at staging, with the reference's error
    # mapping preserved.
    #
    # DoS guard (verifier.go:69-72 ordering): untrusted_vals is attacker-
    # chosen, so the coalesced form only runs when the new set is within a
    # small factor of the trusted one (honest valsets churn gradually); a
    # suspiciously large new set pays the trusted-set check IN FULL before
    # any work proportional to its own size.
    coalesce = len(untrusted_vals.validators) <= 4 * max(
        len(trusted_vals.validators), 1)
    try:
        staged_trust = stage_verify_commit_light_trusting(
            trusted_header.chain_id, trusted_vals, untrusted_header.commit, trust_level
        )
        if not coalesce:
            staged_trust.finish()
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(e) from e
    try:
        staged_new = stage_verify_commit_light(
            trusted_header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.height,
            untrusted_header.commit,
        )
    except Exception as e:  # noqa: BLE001 - verifier.go:69-72 wrapping
        raise ErrInvalidHeader(e) from e
    from cometbft_tpu import sched as _sched

    prefetch_staged([staged_trust, staged_new],
                    klass=_sched.LIGHT
                    if _sched.current_class() == _sched.LIGHT else "sync")
    try:
        staged_trust.finish()
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(e) from e
    try:
        staged_new.finish()
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidHeader(e) from e


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: cmttime.Timestamp,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """light/verifier.go:138-151."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period_ns, now, max_clock_drift_ns, trust_level,
        )
    else:
        verify_adjacent(
            trusted_header, untrusted_header, untrusted_vals,
            trusting_period_ns, now, max_clock_drift_ns,
        )


def verify_with_certificate(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: cmttime.Timestamp,
    max_clock_drift_ns: int,
    trust_level: Fraction,
    cert,
) -> bool:
    """A bisection hop decided by a commit certificate (cert/): the
    non-crypto header checks run EXACTLY as the classic path runs them
    (and raise identically), then the certificate stands in for the
    per-vote commit checks — a >2/3 bitmap tally plus ONE pairing —
    when it attests this header's served commit byte-for-byte
    (attests_commit pins the signer set, timestamps AND the signature
    sum, making cert-accept equivalent to the aggregate-first per-vote
    path on this exact commit).

    Returns True when the hop is decided (accepted). Returns False when
    the certificate is unusable here — mismatched, forged, failing its
    pairing, or (non-adjacent) not carrying trust-level power of the
    OLD set — and the caller MUST run the classic path, which then
    produces the canonical verdict or error. Accept-only: a certificate
    can decide a hop positively or get out of the way; it can never
    reject one. ErrInvalidKey (BLS set with the backend off) propagates
    — misconfiguration stays loud on this path too."""
    from cometbft_tpu.cert.certificate import (
        ErrCertInvalid,
        attests_commit,
        verify_certificate,
    )

    adjacent = untrusted_header.height == trusted_header.height + 1
    if header_expired(trusted_header, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted_header.time.add_ns(trusting_period_ns), now
        )
    _verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift_ns
    )
    if adjacent and (untrusted_header.header.validators_hash
                     != trusted_header.header.next_validators_hash):
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted_header.header.next_validators_hash.hex()}) to match "
            f"those from new header ({untrusted_header.header.validators_hash.hex()})"
        )
    commit = untrusted_header.commit
    if not attests_commit(cert, commit):
        return False
    if not adjacent:
        # trust-level tally of the OLD set over the certified signers —
        # the same address-keyed sum the classic trusting check runs
        # (signature validity is covered by the certificate's aggregate)
        tallied = 0
        from cometbft_tpu.types.basic import BlockIDFlag as _Flag

        for cs in commit.signatures:
            if cs.block_id_flag != _Flag.COMMIT:
                continue
            _, val = trusted_vals.get_by_address(cs.validator_address)
            if val is not None:
                tallied += val.voting_power
        needed = (trusted_vals.total_voting_power()
                  * trust_level.numerator // trust_level.denominator)
        if tallied <= needed:
            return False
    try:
        verify_certificate(cert, trusted_header.chain_id, untrusted_vals)
    except ErrCertInvalid:
        return False
    return True


def verify_backwards(untrusted_header, trusted_header) -> None:
    """light/verifier.go:214-245 — headers, not signed headers: walk the
    LastBlockID hash chain one step down."""
    try:
        untrusted_header.validate_basic()
    except ValueError as e:
        raise ErrInvalidHeader(e) from e
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if untrusted_header.time.unix_ns() >= trusted_header.time.unix_ns():
        raise ErrInvalidHeader(
            f"expected older header time {untrusted_header.time} to be before "
            f"new header time {trusted_header.time}"
        )
    if untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.hash().hex()} does not match "
            f"trusted header's last block {trusted_header.last_block_id.hash.hex()}"
        )
