"""The light client: trusted-state bootstrap, sequential + skipping
(bisection) verification, fork detection, attack evidence.

Reference: light/client.go (Client, verifySequential:613, verifySkipping:706,
backwards:933) and light/detector.go (detectDivergence:28,
examineConflictingHeaderAgainstTrace:290, newLightClientAttackEvidence:408).

TPU-first shape: every hop of a bisection lands in verify_commit_light /
verify_commit_light_trusting (types/validation.py), which coalesce a
commit's whole signature set into one device batch — a 500-validator
BASELINE-config-4 hop is a single MXU-batched kernel launch, so the
dominant cost of a 100k-height bisection (~log2 pivots × 2 commit checks)
is a handful of device batches rather than ~10⁵ host verifies. The client
logic itself is asyncio (providers are network-bound), single-task like
the rest of the framework — no locks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.light import LightBlock
from cometbft_tpu.types.validation import Fraction
from cometbft_tpu.utils import cmttime

from cometbft_tpu.light import verifier
from cometbft_tpu.light.errors import (
    ErrFailedHeaderCrossReferencing,
    ErrHeightTooHigh,
    ErrInvalidHeader,
    ErrLightBlockNotFound,
    ErrLightClientAttack,
    ErrNewValSetCantBeTrusted,
    ErrNoWitnesses,
    ErrVerificationFailed,
    LightClientError,
)
from cometbft_tpu.light.provider import Provider
from cometbft_tpu.light.store import LightStore

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

# client.go:36-44 defaults
DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000
# pivot = trusted + (new-trusted) * 1/2 (client.go:52-56)
_PIVOT_NUM, _PIVOT_DEN = 1, 2


@dataclass
class TrustOptions:
    """light/trust_options.go: subjective-initialization root of trust."""

    period_ns: int
    height: int
    hash_: bytes

    def validate_basic(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("negative or zero trusting period")
        if self.height <= 0:
            raise ValueError("negative or zero trusted height")
        if len(self.hash_) != 32:
            raise ValueError("expected 32-byte trusted header hash")


class Client:
    """light/client.go:147."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: list[Provider],
        trusted_store: LightStore,
        *,
        verification_mode: str = SKIPPING,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        logger: cmtlog.Logger | None = None,
    ):
        trust_options.validate_basic()
        verifier.validate_trust_level(trust_level)
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.trusting_period_ns = trust_options.period_ns
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = trusted_store
        self.verification_mode = verification_mode
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.pruning_size = pruning_size
        self.logger = logger or cmtlog.nop()
        self.latest_trusted: Optional[LightBlock] = trusted_store.latest_light_block()
        # in-flight dedup (libs/singleflight.py — the mempool CheckTx
        # pattern, extracted): concurrent verify_light_block_at_height/
        # update calls for the same height share ONE bisection — the
        # first caller runs it, the rest await its future (height 0 keys
        # the update() latest-head flight)
        from cometbft_tpu.libs.singleflight import SingleFlight

        self._flights = SingleFlight()
        # optional shared-checkpoint source (light/fleet.py points this at
        # the fleet's skip-list cache): height -> trusted LightBlock at
        # the greatest cached height <= the requested one, or None. The
        # default consults this client's own store, so even a plain
        # client's bisection fast-forwards through heights it has already
        # verified instead of re-verifying hops above them.
        self.checkpoint_source: Callable[[int], Optional[LightBlock]] = (
            lambda h: self.store.light_block_before(h + 1))
        # certificate short-circuit (cert/): a primary that can serve
        # commit certificates lets a hop decide on a >2/3 bitmap tally
        # plus ONE pairing instead of per-vote commit verification —
        # accept-only, so any unusable certificate (absent, mismatched,
        # forged) falls through to the classic path bit-identically
        self.cert_source = getattr(primary, "commit_certificate", None)
        self.cert_hits = 0       # hops decided by a certificate
        self.cert_misses = 0     # no certificate at the hop height
        self.cert_fallbacks = 0  # held a certificate, ran classic anyway

    # ----------------------------------------------------------- bootstrap

    async def initialize(self, now: cmttime.Timestamp | None = None) -> None:
        """client.go:303-402: restore from the store or fetch the trust-
        options header from the primary, cross-check it with every witness,
        and persist it as the root of trust."""
        now = now or cmttime.now()
        if self.latest_trusted is not None:
            # checkTrustedHeaderUsingOptions (client.go:303)
            if self.latest_trusted.height < self.trust_options.height:
                opt_block = await self._light_block_from_primary(self.trust_options.height)
                if opt_block.hash() != self.trust_options.hash_:
                    raise LightClientError(
                        "trusted option header hash does not match the primary's"
                    )
            return
        lb = await self._light_block_from_primary(self.trust_options.height)
        if lb.hash() != self.trust_options.hash_:
            raise LightClientError(
                f"expected header's hash {self.trust_options.hash_.hex()}, "
                f"but got {lb.hash().hex()}"
            )
        lb.validate_basic(self.chain_id)
        # +2/3 of its own valset signed it (client.go:388-395)
        from cometbft_tpu.types.validation import verify_commit_light

        verify_commit_light(
            self.chain_id, lb.validator_set, lb.commit.block_id, lb.height, lb.commit
        )
        await self._compare_first_header_with_witnesses(lb)
        self._update_trusted(lb)

    async def _compare_first_header_with_witnesses(self, lb: LightBlock) -> None:
        """client.go:1131: during subjective init every witness must agree
        — a divergent witness at the root of trust is simply dropped."""
        bad: list[int] = []
        for i, w in enumerate(self.witnesses):
            try:
                other = await w.light_block(lb.height)
            except LightClientError:
                continue
            if other.hash() != lb.hash():
                self.logger.error(
                    "witness disagrees with primary at the root of trust; removing",
                    witness=w.id_(),
                )
                bad.append(i)
        self._remove_witnesses(bad)

    # -------------------------------------------------------------- verify

    async def verify_light_block_at_height(
        self, height: int, now: cmttime.Timestamp | None = None
    ) -> LightBlock:
        """client.go:474-523 — plus in-flight dedup: concurrent calls for
        the same height share the FIRST caller's bisection instead of each
        running their own (the store check alone cannot catch this — the
        store only fills once a flight completes)."""
        if height <= 0:
            raise ValueError("negative or zero height")
        now = now or cmttime.now()
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        _, lb = await self._flights.do(
            height, lambda: self._verify_at_height(height, now))
        return lb

    async def _verify_at_height(self, height: int, now) -> LightBlock:
        lb = await self._light_block_from_primary(height)
        await self._verify_light_block(lb, now)
        return lb

    async def update(self, now: cmttime.Timestamp | None = None) -> Optional[LightBlock]:
        """client.go:436-470: fetch + verify the primary's latest header if
        newer than the last trusted one. Concurrent update() calls share
        one flight (dedup key 0)."""
        now = now or cmttime.now()
        if self.latest_trusted is None:
            raise LightClientError("no headers exist yet")
        _, lb = await self._flights.do(0, lambda: self._update_flight(now))
        return lb

    async def _update_flight(self, now) -> Optional[LightBlock]:
        last = self.latest_trusted
        latest = await self._light_block_from_primary(0)
        if last is not None and latest.height > last.height:
            await self._verify_light_block(latest, now)
            return latest
        return None

    async def _verify_light_block(self, new_lb: LightBlock, now: cmttime.Timestamp) -> None:
        """client.go:558-611: pick forward (sequential/skipping) or
        backwards verification relative to the trusted state."""
        if self.store.light_block(new_lb.height) is not None:
            return
        closest = self.store.light_block_before(new_lb.height)
        if closest is not None:
            if self.verification_mode == SEQUENTIAL:
                await self._verify_sequential(closest, new_lb, now)
            else:
                await self._verify_skipping_against_primary(closest, new_lb, now)
            return
        first = self.store.first_light_block()
        if first is None:
            raise LightClientError("no trusted state to verify against; initialize first")
        await self._backwards(first, new_lb, now)

    async def _try_certificate(
        self, trusted: LightBlock, target: LightBlock, now: cmttime.Timestamp
    ) -> bool:
        """Try to decide the hop trusted→target with a commit certificate.

        True means the hop is verified (one pairing); False means run the
        classic per-vote path. Certificates are accept-only: any miss,
        mismatch, forged signature, or sub-trust-level tally returns False
        and costs nothing but the attempt. Header-shape/expiry errors raise
        exactly as the classic verifiers would, so callers' except clauses
        behave identically either way."""
        if self.cert_source is None:
            return False
        cert = await self.cert_source(target.height)
        if cert is None:
            self.cert_misses += 1
            return False
        ok = verifier.verify_with_certificate(
            trusted.signed_header, trusted.validator_set,
            target.signed_header, target.validator_set,
            self.trusting_period_ns, now, self.max_clock_drift_ns,
            self.trust_level, cert,
        )
        if ok:
            self.cert_hits += 1
        else:
            self.cert_fallbacks += 1
        return ok

    async def _verify_sequential(
        self, trusted: LightBlock, new_lb: LightBlock, now: cmttime.Timestamp
    ) -> None:
        """client.go:613-697 — height-by-height VerifyAdjacent. The devices
        see one commit batch per height, streamed."""
        verified = trusted
        trace = [trusted]
        for height in range(trusted.height + 1, new_lb.height + 1):
            interim = (
                new_lb if height == new_lb.height
                else await self._light_block_from_primary(height)
            )
            try:
                if not await self._try_certificate(verified, interim, now):
                    verifier.verify_adjacent(
                        verified.signed_header, interim.signed_header,
                        interim.validator_set, self.trusting_period_ns, now,
                        self.max_clock_drift_ns,
                    )
            except LightClientError as e:
                raise ErrVerificationFailed(verified.height, interim.height, e) from e
            verified = interim
            trace.append(verified)
        await self._detect_divergence(trace, now)
        for lb in trace[1:]:
            self._update_trusted(lb)

    async def _verify_skipping(
        self,
        source: Provider,
        trusted: LightBlock,
        new_lb: LightBlock,
        now: cmttime.Timestamp,
    ) -> list[LightBlock]:
        """client.go:706-775 — bisection. Returns the verification trace
        (every block the client had to fully verify, in height order).

        Shared-cache fast-forward: before fetching a pivot from the
        provider, `checkpoint_source` is consulted for an already-trusted
        block in (verified, pivot] — a hit advances `verified` directly
        (no fetch, no signature work for the hops below it). The fleet
        service points this at its skip-list checkpoint cache, so a cold
        client's bisection restarts from the nearest cached checkpoint
        instead of walking all the way up from its own trust root."""
        block_cache = [new_lb]
        depth = 0
        verified = trusted
        trace = [trusted]
        while True:
            target = block_cache[depth]
            try:
                # certificate first: a usable certificate decides the hop
                # with one pairing; anything else (miss, mismatch, forged,
                # sub-trust-level) runs the unchanged classic path — the
                # canonical verdicts and errors below come from it
                if not await self._try_certificate(verified, target, now):
                    verifier.verify(
                        verified.signed_header, verified.validator_set,
                        target.signed_header, target.validator_set,
                        self.trusting_period_ns, now, self.max_clock_drift_ns,
                        self.trust_level,
                    )
            except ErrNewValSetCantBeTrusted:
                # jump too far: bisect [verified, target]
                if depth == len(block_cache) - 1:
                    pivot = (
                        verified.height
                        + (target.height - verified.height) * _PIVOT_NUM // _PIVOT_DEN
                    )
                    cached = self._trusted_checkpoint(pivot, verified, now)
                    if cached is not None:
                        verified = cached
                        trace.append(verified)
                        continue
                    interim = await source.light_block(pivot)
                    block_cache.append(interim)
                depth += 1
                continue
            except LightClientError as e:
                raise ErrVerificationFailed(verified.height, target.height, e) from e
            if depth == 0:
                trace.append(new_lb)
                return trace
            verified = target
            block_cache = block_cache[:depth]
            depth = 0
            trace.append(verified)

    async def _verify_skipping_against_primary(
        self, trusted: LightBlock, new_lb: LightBlock, now: cmttime.Timestamp
    ) -> None:
        """client.go:777-832: verifySkipping + witness cross-check."""
        trace = await self._verify_skipping(self.primary, trusted, new_lb, now)
        await self._detect_divergence(trace, now)
        for lb in trace[1:]:
            self._update_trusted(lb)

    async def _backwards(
        self, trusted: LightBlock, new_lb: LightBlock, now: cmttime.Timestamp
    ) -> None:
        """client.go:933-988: hash-chain walk below the first trusted
        header. No signature checks — pure header-link hashes (the trusted
        header transitively commits to every ancestor)."""
        if verifier.header_expired(trusted.signed_header, self.trusting_period_ns, now):
            raise ErrInvalidHeader("trusted header expired; can't verify backwards")
        verified = trusted.header
        height = trusted.height - 1
        while height >= new_lb.height:
            interim = (
                new_lb if height == new_lb.height
                else await self._light_block_from_primary(height)
            )
            verifier.verify_backwards(interim.header, verified)
            verified = interim.header
            self._update_trusted(interim)
            height -= 1

    # ------------------------------------------------------------ detector

    async def _detect_divergence(self, trace: list[LightBlock], now) -> None:
        """detector.go:28-107: ask every witness for the target header; any
        conflict is examined for attack evidence. At least one witness must
        agree (or be removed) for the header to stand."""
        if not trace or len(trace) < 2:
            raise LightClientError("nil or single block primary trace")
        if not self.witnesses:
            raise ErrNoWitnesses("no witnesses connected; unable to cross-check")
        last = trace[-1]
        header_matched = False
        to_remove: list[int] = []
        for i, witness in enumerate(self.witnesses):
            try:
                w_block = await self._get_target_block_or_latest(last.height, witness)
            except LightClientError:
                to_remove.append(i)
                continue
            if w_block is None:
                continue  # witness is still catching up — benign
            if w_block.hash() == last.hash():
                header_matched = True
                continue
            attack = await self._handle_conflicting_headers(trace, w_block, i, now)
            if attack:
                raise ErrLightClientAttack(
                    "conflicting headers confirmed: primary or witness is lying"
                )
            to_remove.append(i)
        self._remove_witnesses(to_remove)
        if not header_matched:
            raise ErrFailedHeaderCrossReferencing(
                "all witnesses failed to cross-reference the header"
            )

    async def _get_target_block_or_latest(
        self, height: int, witness: Provider
    ) -> Optional[LightBlock]:
        """detector.go:379-405: None when the witness is behind (benign)."""
        latest = await witness.light_block(0)
        if latest.height == height:
            return latest
        if latest.height > height:
            return await witness.light_block(height)
        return None

    async def _handle_conflicting_headers(
        self, primary_trace: list[LightBlock], challenging: LightBlock,
        witness_index: int, now,
    ) -> bool:
        """detector.go:217-287. Returns True when a real attack was
        confirmed (evidence generated + reported both ways)."""
        witness = self.witnesses[witness_index]
        try:
            witness_trace, primary_block = await self._examine_against_trace(
                primary_trace, challenging, witness, now
            )
        except LightClientError as e:
            self.logger.info(
                "error validating witness's divergent header", err=str(e),
                witness=witness.id_(),
            )
            return False
        # witness held as source of truth -> evidence against the primary
        common, trusted_blk = witness_trace[0], witness_trace[-1]
        ev_primary = make_attack_evidence(primary_block, trusted_blk, common)
        self.logger.error(
            "ATTEMPTED ATTACK DETECTED; sending evidence against primary",
            ev=ev_primary.string(), primary=self.primary.id_(),
        )
        await witness.report_evidence(ev_primary)
        # reverse: primary held as source of truth -> evidence against witness
        try:
            p_trace, witness_block = await self._examine_against_trace(
                witness_trace, primary_block, self.primary, now
            )
            common, trusted_blk = p_trace[0], p_trace[-1]
            ev_witness = make_attack_evidence(witness_block, trusted_blk, common)
            await self.primary.report_evidence(ev_witness)
        except LightClientError as e:
            self.logger.info("error validating primary's divergent header", err=str(e))
        return True

    async def _examine_against_trace(
        self, trace: list[LightBlock], target: LightBlock, source: Provider, now,
    ) -> tuple[list[LightBlock], LightBlock]:
        """detector.go:290-377: walk the trace, re-verifying each height
        against `source`, until the hashes diverge — that bifurcation point
        yields (source's trace, the divergent block from the trace owner)."""
        if target.height < trace[0].height:
            raise LightClientError(
                f"target block height below trusted height "
                f"({target.height} < {trace[0].height})"
            )
        prev: Optional[LightBlock] = None
        source_trace: list[LightBlock] = []
        for idx, trace_block in enumerate(trace):
            if trace_block.height > target.height:
                # forward lunatic: the block right after target diverges
                if trace_block.time.unix_ns() > target.time.unix_ns():
                    raise LightClientError(
                        "sanity: trace block time above target block time"
                    )
                if prev is not None and prev.height != target.height:
                    source_trace = await self._verify_skipping(source, prev, target, now)
                return source_trace, trace_block
            source_block = (
                target if trace_block.height == target.height
                else await source.light_block(trace_block.height)
            )
            if idx == 0:
                if source_block.hash() != trace_block.hash():
                    raise LightClientError(
                        "trusted block differs from the source's first block"
                    )
                prev = source_block
                continue
            source_trace = await self._verify_skipping(source, prev, source_block, now)
            if source_block.hash() != trace_block.hash():
                return source_trace, trace_block  # bifurcation point
            prev = source_block
        raise LightClientError("no divergence found in trace (contract violation)")

    # ----------------------------------------------------------- plumbing

    def _trusted_checkpoint(
        self, pivot: int, verified: LightBlock, now: cmttime.Timestamp
    ) -> Optional[LightBlock]:
        """An already-trusted block in (verified.height, pivot] from the
        shared checkpoint source, still within its trusting period —
        or None. Never raises: a broken cache degrades to a plain fetch."""
        try:
            cached = self.checkpoint_source(pivot)
        except Exception:  # noqa: BLE001 - cache trouble must not fail verify
            return None
        if (cached is None or cached.height <= verified.height
                or cached.height > pivot):
            return None
        if verifier.header_expired(
                cached.signed_header, self.trusting_period_ns, now):
            return None
        return cached

    async def _light_block_from_primary(self, height: int) -> LightBlock:
        """client.go:990-1017 (without the primary-replacement dance: a
        failing primary surfaces as the provider's error)."""
        lb = await self.primary.light_block(height)
        lb.validate_basic(self.chain_id)
        if height != 0 and lb.height != height:
            raise ErrLightBlockNotFound(
                f"primary returned height {lb.height}, want {height}"
            )
        return lb

    def _update_trusted(self, lb: LightBlock) -> None:
        """client.go:910-931."""
        self.store.save_light_block(lb)
        if self.latest_trusted is None or lb.height > self.latest_trusted.height:
            self.latest_trusted = lb
        self.store.prune(self.pruning_size)

    def _remove_witnesses(self, indexes: list[int]) -> None:
        """client.go:1019-1043."""
        for i in sorted(indexes, reverse=True):
            self.witnesses.pop(i)

    # ------------------------------------------------------------- queries

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    def last_trusted_height(self) -> int:
        lb = self.store.latest_light_block()
        return lb.height if lb else -1

    def first_trusted_height(self) -> int:
        lb = self.store.first_light_block()
        return lb.height if lb else -1


def make_attack_evidence(
    conflicted: LightBlock, trusted: LightBlock, common: LightBlock
) -> LightClientAttackEvidence:
    """detector.go:408-425 newLightClientAttackEvidence: classify the attack
    (lunatic vs equivocation/amnesia) and fill every field a full node needs
    to verify it."""
    ev = LightClientAttackEvidence(conflicting_block=conflicted, common_height=0)
    if ev.conflicting_header_is_invalid(trusted.header):
        ev.common_height = common.height
        ev.timestamp = common.time
        ev.total_voting_power = common.validator_set.total_voting_power()
    else:
        ev.common_height = trusted.height
        ev.timestamp = trusted.time
        ev.total_voting_power = trusted.validator_set.total_voting_power()
    ev.byzantine_validators = ev.get_byzantine_validators(
        common.validator_set, trusted.signed_header
    )
    return ev
