"""EvidencePool (reference: evidence/pool.go).

Verified-but-uncommitted Byzantine proofs, persisted under two keyspaces
(pending / committed-marker) exactly like the reference's prefix scheme
(pool.go:45-50). The pool:

  add_evidence     — dedupe + verify + persist + offer to gossip
  pending_evidence — proposer's pull, size-capped (pool.go:100-130)
  check_evidence   — validates evidence in a peer's proposed block
  update           — post-commit: mark committed, prune expired

The reference guards the pool with mutexes; here every call happens on the
consensus asyncio task (or blocksync's), so plain dicts suffice — same
single-writer discipline as the rest of the engine.
"""

from __future__ import annotations

from typing import Callable, Iterable

from cometbft_tpu.evidence.verify import ErrInvalidEvidence, verify_evidence
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.state.state import State
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.db import KVStore, MemDB
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    evidence_list_from_proto,
    evidence_list_to_proto,
)

_PENDING = b"\x00"
_COMMITTED = b"\x01"


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + ev.height().to_bytes(8, "big") + ev.hash()


class EvidencePool:
    def __init__(
        self,
        db: KVStore | None,
        state_store: StateStore,
        logger: cmtlog.Logger | None = None,
    ):
        self.db = db if db is not None else MemDB()
        self.state_store = state_store
        self.logger = logger or cmtlog.nop()
        self._pending: dict[bytes, Evidence] = {}
        self._committed: set[bytes] = set()
        self._state: State | None = state_store.load()
        # broadcast hook: the evidence reactor subscribes (reactor.go:32)
        self.on_evidence_added: Callable[[Evidence], None] | None = None
        self._load()

    # -------------------------------------------------------------- intake

    def add_evidence(self, ev: Evidence) -> bool:
        """pool.go:136-192 AddEvidence: idempotent; verifies before
        accepting. Returns True if newly added."""
        h = ev.hash()
        if h in self._committed or h in self._pending:
            return False
        state = self._state or self.state_store.load()
        if state is None:
            raise ErrInvalidEvidence("evidence pool has no state")
        verify_evidence(ev, state, self._validators_at)
        self._pending[h] = ev
        self.db.set(_key(_PENDING, ev), ev.bytes_())
        self.logger.info("verified new evidence of byzantine behavior", evidence=ev.string())
        if self.on_evidence_added is not None:
            self.on_evidence_added(ev)
        return True

    def check_evidence(self, evs: Iterable[Evidence]) -> None:
        """pool.go:194-235 CheckEvidence: every piece in a proposed block
        must be valid and not already committed; duplicates within the
        list are rejected."""
        seen: set[bytes] = set()
        for ev in evs:
            h = ev.hash()
            if h in seen:
                raise ErrInvalidEvidence(f"duplicate evidence {h.hex()} in block")
            seen.add(h)
            if h in self._committed:
                raise ErrInvalidEvidence(f"evidence {h.hex()} was already committed")
            if h not in self._pending:
                state = self._state or self.state_store.load()
                verify_evidence(ev, state, self._validators_at)

    # ------------------------------------------------------------- outflow

    def pending_evidence(self, max_bytes: int) -> tuple[list[Evidence], int]:
        """pool.go:100-130 PendingEvidence: oldest-first under a byte cap."""
        out: list[Evidence] = []
        size = 0
        for ev in sorted(self._pending.values(), key=lambda e: (e.height(), e.hash())):
            ev_size = len(ev.bytes_()) + 16  # proto wrapper overhead
            if max_bytes >= 0 and size + ev_size > max_bytes:
                break
            out.append(ev)
            size += ev_size
        return out, size

    def update(self, state: State, committed: list[Evidence]) -> None:
        """pool.go:80-98: called after every ApplyBlock with the evidence
        the block carried. Marks committed + prunes expired pending."""
        self._state = state
        for ev in committed:
            h = ev.hash()
            self._committed.add(h)
            self.db.set(_key(_COMMITTED, ev), b"\x01")
            if h in self._pending:
                del self._pending[h]
                self.db.delete(_key(_PENDING, ev))
        self._prune_expired(state)

    # ------------------------------------------------------------ internals

    def _prune_expired(self, state: State) -> None:
        params = state.consensus_params.evidence
        height = state.last_block_height
        now_ns = state.last_block_time.unix_ns()
        for h, ev in list(self._pending.items()):
            if (
                height - ev.height() > params.max_age_num_blocks
                and now_ns - ev.time().unix_ns() > params.max_age_duration_ns
            ):
                del self._pending[h]
                self.db.delete(_key(_PENDING, ev))

    def _validators_at(self, height: int):
        return self.state_store.load_validators(height)

    def _load(self) -> None:
        """Recover pending/committed sets from the DB on boot."""
        for k, v in self.db.iterate(_PENDING, _PENDING + b"\xff" * 40):
            if not k.startswith(_PENDING):
                continue
            ev = DuplicateVoteEvidence.from_proto(v)
            self._pending[ev.hash()] = ev
        for k, _ in self.db.iterate(_COMMITTED, _COMMITTED + b"\xff" * 40):
            if k.startswith(_COMMITTED):
                self._committed.add(k[-32:])

    def size(self) -> int:
        return len(self._pending)
