"""EvidencePool (reference: evidence/pool.go).

Verified-but-uncommitted Byzantine proofs, persisted under two keyspaces
(pending / committed-marker) exactly like the reference's prefix scheme
(pool.go:45-50). The pool:

  add_evidence     — dedupe + verify + persist + offer to gossip
  pending_evidence — proposer's pull, size-capped (pool.go:100-130)
  check_evidence   — validates evidence in a peer's proposed block
  update           — post-commit: mark committed, prune expired

The reference guards the pool with mutexes; here every call happens on the
consensus asyncio task (or blocksync's), so plain dicts suffice — same
single-writer discipline as the rest of the engine.
"""

from __future__ import annotations

from typing import Callable, Iterable

from cometbft_tpu.evidence.verify import ErrInvalidEvidence, verify_evidence
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.state.state import State
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.db import KVStore, MemDB
from cometbft_tpu.types.evidence import (
    DuplicateVoteEvidence,
    Evidence,
    evidence_list_from_proto,
    evidence_list_to_proto,
)

_PENDING = b"\x00"
_COMMITTED = b"\x01"


def _key(prefix: bytes, ev: Evidence) -> bytes:
    return prefix + ev.height().to_bytes(8, "big") + ev.hash()


class EvidencePool:
    def __init__(
        self,
        db: KVStore | None,
        state_store: StateStore,
        logger: cmtlog.Logger | None = None,
        block_store=None,
    ):
        self.db = db if db is not None else MemDB()
        self.state_store = state_store
        # historical signed headers for light-client-attack verification
        # (pool.go:66 blockStore); None -> LC evidence is rejected
        self.block_store = block_store
        self.logger = logger or cmtlog.nop()
        self._pending: dict[bytes, Evidence] = {}
        self._committed: set[bytes] = set()
        self._consensus_buffer: list[tuple] = []
        self._state: State | None = state_store.load()
        # broadcast hook: the evidence reactor subscribes (reactor.go:32)
        self.on_evidence_added: Callable[[Evidence], None] | None = None
        self.metrics = None  # libs.metrics.EvidenceMetrics | None (node wires it)
        self._load()

    # -------------------------------------------------------------- intake

    def add_evidence(self, ev: Evidence, from_consensus: bool = False) -> bool:
        """pool.go:136-192 AddEvidence: idempotent; verifies before
        accepting. Returns True if newly added. from_consensus marks
        evidence our own engine produced (pool.go:196
        AddEvidenceFromConsensus): its height has no committed header yet,
        so the block-time cross-check is skipped."""
        try:
            ev.validate_basic()  # before hash(): malformed wire evidence
        except ValueError as e:
            raise ErrInvalidEvidence(f"evidence failed basic validation: {e}") from e
        h = ev.hash()
        if h in self._committed or h in self._pending:
            return False
        state = self._state or self.state_store.load()
        if state is None:
            raise ErrInvalidEvidence("evidence pool has no state")
        verify_evidence(ev, state, self._validators_at, self.block_store,
                        from_consensus=from_consensus)
        self._pending[h] = ev
        # oneof-wrapped so the type survives reload (DuplicateVote vs LC attack)
        self.db.set(_key(_PENDING, ev), evidence_list_to_proto([ev]))
        self.logger.info("verified new evidence of byzantine behavior", evidence=ev.string())
        if self.on_evidence_added is not None:
            self.on_evidence_added(ev)
        return True

    def check_evidence(self, evs: Iterable[Evidence]) -> None:
        """pool.go:194-235 CheckEvidence: every piece in a proposed block
        must be valid and not already committed; duplicates within the
        list are rejected."""
        seen: set[bytes] = set()
        for ev in evs:
            try:
                ev.validate_basic()
            except ValueError as e:
                raise ErrInvalidEvidence(f"evidence failed basic validation: {e}") from e
            h = ev.hash()
            if h in seen:
                raise ErrInvalidEvidence(f"duplicate evidence {h.hex()} in block")
            seen.add(h)
            if h in self._committed:
                raise ErrInvalidEvidence(f"evidence {h.hex()} was already committed")
            if h not in self._pending:
                state = self._state or self.state_store.load()
                verify_evidence(ev, state, self._validators_at, self.block_store)

    # ------------------------------------------------------------- outflow

    def pending_evidence(self, max_bytes: int) -> tuple[list[Evidence], int]:
        """pool.go:100-130 PendingEvidence: oldest-first under a byte cap."""
        out: list[Evidence] = []
        size = 0
        for ev in sorted(self._pending.values(), key=lambda e: (e.height(), e.hash())):
            ev_size = len(ev.bytes_()) + 16  # proto wrapper overhead
            if max_bytes >= 0 and size + ev_size > max_bytes:
                break
            out.append(ev)
            size += ev_size
        return out, size

    def report_conflicting_votes(self, vote_a, vote_b) -> None:
        """pool.go:196 ReportConflictingVotes: buffer an equivocation seen
        by consensus. Evidence is materialized in update() once the header
        at that height is committed, so its timestamp can be the BLOCK time
        (the time cross-check other pools apply would reject anything
        else)."""
        self._consensus_buffer.append((vote_a, vote_b))

    def _process_consensus_buffer(self, state: State) -> None:
        """pool.go:459-520 processConsensusBuffer."""
        from cometbft_tpu.types.evidence import DuplicateVoteEvidence

        buf, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buf:
            try:
                if vote_a.height == state.last_block_height:
                    ev = DuplicateVoteEvidence.new(
                        vote_a, vote_b, state.last_block_time, state.last_validators
                    )
                elif vote_a.height < state.last_block_height:
                    val_set = self.state_store.load_validators(vote_a.height)
                    meta = (
                        self.block_store.load_block_meta(vote_a.height)
                        if self.block_store is not None else None
                    )
                    if val_set is None or meta is None:
                        self.logger.error(
                            "failed to load valset/header for conflicting votes",
                            height=vote_a.height,
                        )
                        continue
                    ev = DuplicateVoteEvidence.new(
                        vote_a, vote_b, meta.header.time, val_set
                    )
                else:
                    # votes above the committed height: retry next update
                    self._consensus_buffer.append((vote_a, vote_b))
                    continue
                self.add_evidence(ev, from_consensus=True)
            except Exception as e:  # noqa: BLE001 - never wedge the commit path
                self.logger.error("failed to convert conflicting votes", err=str(e))

    def update(self, state: State, committed: list[Evidence]) -> None:
        """pool.go:80-98: called after every ApplyBlock with the evidence
        the block carried. Marks committed + prunes expired pending,
        then materializes buffered consensus equivocations."""
        self._state = state
        for ev in committed:
            h = ev.hash()
            self._committed.add(h)
            self.db.set(_key(_COMMITTED, ev), b"\x01")
            if h in self._pending:
                del self._pending[h]
                self.db.delete(_key(_PENDING, ev))
        self._prune_expired(state)
        self._process_consensus_buffer(state)
        if self.metrics is not None:
            if committed:
                self.metrics.evidence_committed.inc(len(committed))
            self.metrics.evidence_pending.set(len(self._pending))

    # ------------------------------------------------------------ internals

    def _prune_expired(self, state: State) -> None:
        params = state.consensus_params.evidence
        height = state.last_block_height
        now_ns = state.last_block_time.unix_ns()
        for h, ev in list(self._pending.items()):
            if (
                height - ev.height() > params.max_age_num_blocks
                and now_ns - ev.time().unix_ns() > params.max_age_duration_ns
            ):
                del self._pending[h]
                self.db.delete(_key(_PENDING, ev))

    def _validators_at(self, height: int):
        return self.state_store.load_validators(height)

    def _load(self) -> None:
        """Recover pending/committed sets from the DB on boot."""
        for k, v in self.db.iterate(_PENDING, _PENDING + b"\xff" * 40):
            if not k.startswith(_PENDING):
                continue
            try:
                evs = evidence_list_from_proto(v)
            except Exception:  # noqa: BLE001 - pre-wrapper rows (bare proto)
                evs = [DuplicateVoteEvidence.from_proto(v)]
            for ev in evs:
                self._pending[ev.hash()] = ev
        for k, _ in self.db.iterate(_COMMITTED, _COMMITTED + b"\xff" * 40):
            if k.startswith(_COMMITTED):
                self._committed.add(k[-32:])

    def size(self) -> int:
        return len(self._pending)
