"""Evidence reactor: gossips byzantine-behavior proofs.

Reference: evidence/reactor.go:32 — one channel (0x38), a per-peer
broadcast routine walking the pool's pending list, and Receive that adds
(and thereby verifies) evidence from peers. Invalid evidence from a peer
is a protocol violation — the switch bans the sender (reactor.go:99).

Wire: EvidenceList {1: repeated Evidence envelope} via
types.evidence_list_to_proto.
"""

from __future__ import annotations

import asyncio

from cometbft_tpu.evidence.pool import EvidencePool
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.types.evidence import (
    evidence_list_from_proto,
    evidence_list_to_proto,
)

EVIDENCE_CHANNEL = 0x38
_BROADCAST_BATCH_BYTES = 1 << 20


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool, logger: cmtlog.Logger | None = None):
        super().__init__("Evidence", logger)
        self.pool = pool
        self._peer_tasks: dict[object, asyncio.Task] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=EVIDENCE_CHANNEL, priority=6,
                                  recv_message_capacity=1 << 22)]

    async def add_peer(self, peer) -> None:
        self._peer_tasks[peer] = asyncio.get_running_loop().create_task(
            self._broadcast_routine(peer)
        )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer, None)
        if t is not None:
            t.cancel()

    async def receive(self, e: Envelope) -> None:
        """reactor.go:84-120: add (verifies); raising here lets the switch
        stop the peer for invalid evidence."""
        for ev in evidence_list_from_proto(e.message):
            self.pool.add_evidence(ev)

    async def _broadcast_routine(self, peer) -> None:
        """reactor.go:67 broadcastEvidenceRoutine: resend the pending list
        until it drains; new evidence is picked up on the next lap."""
        sent: set[bytes] = set()
        try:
            while peer.is_running:
                evs, _ = self.pool.pending_evidence(_BROADCAST_BATCH_BYTES)
                fresh = [ev for ev in evs if ev.hash() not in sent]
                if fresh and await peer.send(
                    EVIDENCE_CHANNEL, evidence_list_to_proto(fresh)
                ):
                    # only delivered evidence is marked; failed sends retry
                    sent.update(ev.hash() for ev in fresh)
                await asyncio.sleep(0.1)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001
            self.logger.error("evidence broadcast routine failed",
                              peer=peer.id[:10], err=str(err))
