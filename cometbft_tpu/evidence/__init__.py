"""Evidence subsystem: detection -> pool -> block inclusion -> ABCI report.

Reference: evidence/ (pool.go, verify.go, reactor.go). The pool stores
verified-but-uncommitted Byzantine proofs, offers them to proposers,
validates evidence in peers' proposed blocks, and expires what has aged
out. The gossip reactor lives in cometbft_tpu/reactors/.
"""

from cometbft_tpu.evidence.pool import EvidencePool  # noqa: F401
from cometbft_tpu.evidence.verify import verify_evidence, verify_duplicate_vote  # noqa: F401
