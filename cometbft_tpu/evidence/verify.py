"""Evidence verification (reference: evidence/verify.go).

verify_evidence: age/expiry checks against consensus params + dispatch by
type (verify.go:19-108). verify_duplicate_vote: the equivocation proof
check (verify.go:166-232) — both votes must be valid signatures from the
same validator over the same height/round/type but different block IDs.
Signature checks ride the batch verifier (two sigs per evidence coalesce
with anything else in flight on the device).
"""

from __future__ import annotations

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.state.state import State
from cometbft_tpu.types.evidence import DuplicateVoteEvidence, Evidence, LightClientAttackEvidence
from cometbft_tpu.types.validator import ValidatorSet


class ErrInvalidEvidence(Exception):
    pass


def verify_evidence(ev: Evidence, state: State, get_validators,
                    block_store=None, from_consensus: bool = False) -> None:
    """verify.go:19-108:
    - structural validity (validate_basic)
    - the recorded time must equal the block time at the evidence height
      (verify.go:28-35; an attacker-chosen time would defeat time-based
      expiry) — skipped for evidence our own consensus produced
      (from_consensus, ref AddEvidenceFromConsensus), whose height has no
      committed header yet
    - the evidence must not be expired (height AND time window)
    - the evidence height's validator set must contain the culprit(s)
    get_validators(height) -> ValidatorSet | None (historical lookup);
    block_store supplies historical headers (None -> LC evidence rejected
    for lack of a header source; time check skipped)."""
    # structural validity is the pool's intake contract (add/check call
    # validate_basic before hashing); only the semantic checks live here
    ev_time = ev.time()
    if not from_consensus and block_store is not None:
        meta = block_store.load_block_meta(ev.height())
        if meta is None:
            raise ErrInvalidEvidence(f"no header at evidence height {ev.height()}")
        if ev_time.unix_ns() != meta.header.time.unix_ns():
            raise ErrInvalidEvidence(
                f"evidence time ({ev_time}) differs from the block time at its "
                f"height ({meta.header.time})"
            )
        ev_time = meta.header.time
    ev_params = state.consensus_params.evidence
    height = state.last_block_height
    age_num_blocks = height - ev.height()
    age_ns = state.last_block_time.unix_ns() - ev_time.unix_ns()
    if (
        age_num_blocks > ev_params.max_age_num_blocks
        and age_ns > ev_params.max_age_duration_ns
    ):
        raise ErrInvalidEvidence(
            f"evidence from height {ev.height()} is too old; "
            f"min height is {height - ev_params.max_age_num_blocks}"
        )
    val_set = get_validators(ev.height())
    if val_set is None:
        raise ErrInvalidEvidence(f"no validator set at evidence height {ev.height()}")

    # sync class: evidence intake must not preempt consensus-critical
    # flushes in the global verify scheduler; its tiny batches (2 sigs
    # for an equivocation) coalesce with whatever else is in flight
    from cometbft_tpu import sched

    with sched.work_class(sched.SYNC):
        if isinstance(ev, DuplicateVoteEvidence):
            verify_duplicate_vote(ev, state.chain_id, val_set)
        elif isinstance(ev, LightClientAttackEvidence):
            verify_light_client_attack(ev, state, val_set, block_store)
        else:
            raise ErrInvalidEvidence(f"unknown evidence type {type(ev).__name__}")


def verify_duplicate_vote(
    ev: DuplicateVoteEvidence, chain_id: str, val_set: ValidatorSet
) -> None:
    """verify.go:166-232."""
    a, b = ev.vote_a, ev.vote_b
    if a.height != b.height or a.round_ != b.round_ or a.type_ != b.type_:
        raise ErrInvalidEvidence(
            f"h/r/s mismatch: {a.height}/{a.round_}/{a.type_} vs {b.height}/{b.round_}/{b.type_}"
        )
    if a.block_id.key() == b.block_id.key():
        raise ErrInvalidEvidence("block IDs are the same; not an equivocation")
    if a.validator_address != b.validator_address:
        raise ErrInvalidEvidence(
            f"validator addresses differ: {a.validator_address.hex()} vs {b.validator_address.hex()}"
        )
    if a.validator_index != b.validator_index:
        raise ErrInvalidEvidence("validator indices differ")
    _, val = val_set.get_by_address(a.validator_address)
    if val is None:
        raise ErrInvalidEvidence(
            f"address {a.validator_address.hex()} was not a validator at height {a.height}"
        )
    # powers recorded in the evidence must match the historical set
    if ev.validator_power != val.voting_power:
        raise ErrInvalidEvidence(
            f"validator power mismatch: evidence {ev.validator_power}, valset {val.voting_power}"
        )
    if ev.total_voting_power != val_set.total_voting_power():
        raise ErrInvalidEvidence(
            f"total voting power mismatch: evidence {ev.total_voting_power}, "
            f"valset {val_set.total_voting_power()}"
        )
    # both signatures must verify under the culprit's key (batched: 2 sigs)
    bv = crypto_batch.create_batch_verifier(val.pub_key)
    bv.add(val.pub_key, a.sign_bytes(chain_id), a.signature)
    bv.add(val.pub_key, b.sign_bytes(chain_id), b.signature)
    ok, mask = bv.verify()
    if not ok:
        which = "A" if not mask[0] else "B"
        raise ErrInvalidEvidence(f"invalid signature on vote {which}")


def _signed_header_at(block_store, height: int):
    """verify.go:266-279 getSignedHeader."""
    from cometbft_tpu.types.light import SignedHeader

    meta = block_store.load_block_meta(height)
    if meta is None:
        return None
    commit = block_store.load_block_commit(height)
    if commit is None:
        return None
    return SignedHeader(header=meta.header, commit=commit)


def verify_light_client_attack(
    ev: LightClientAttackEvidence, state: State, common_vals: ValidatorSet,
    block_store,
) -> None:
    """verify.go:101-164 VerifyLightClientAttack against full-node state:
    - lunatic (common height != conflicting height): 1/3+ of the common
      valset must have signed the conflicting commit (one skipping jump);
      equivocation/amnesia: the conflicting header must be correctly derived
    - +2/3 of the conflicting valset signed the conflicting block (device-
      batched: the whole commit is one batch through verify_commit_light)
    - the node's own header at that height must differ from the conflict
    - recorded total voting power and byzantine validators must match."""
    from cometbft_tpu.light.verifier import DEFAULT_TRUST_LEVEL
    from cometbft_tpu.types.validation import (
        verify_commit_light,
        verify_commit_light_trusting,
    )

    if block_store is None:
        raise ErrInvalidEvidence(
            "light-client attack evidence requires a block store for header lookups"
        )
    # the conflicting block must be internally consistent: its valset hashes
    # to ITS header's validators_hash and its commit signs ITS header
    # (types/evidence.go ValidateBasic -> ConflictingBlock.ValidateBasic);
    # without this a forged valset could satisfy every later check
    try:
        ev.conflicting_block.validate_basic(state.chain_id)
    except ValueError as e:
        raise ErrInvalidEvidence(f"invalid conflicting light block: {e}") from e
    common_header = _signed_header_at(block_store, ev.height())
    if common_header is None:
        raise ErrInvalidEvidence(f"no header at evidence height {ev.height()}")
    trusted_header = common_header
    conflicting = ev.conflicting_block
    if ev.height() != conflicting.height:
        trusted_header = _signed_header_at(block_store, conflicting.height)
        if trusted_header is None:
            # forward lunatic: conflicting height above our head — compare
            # against the latest header we do have (verify.go:70-85)
            latest = block_store.height()
            trusted_header = _signed_header_at(block_store, latest)
            if trusted_header is None:
                raise ErrInvalidEvidence(f"no header at latest height {latest}")
            if trusted_header.time.unix_ns() < conflicting.time.unix_ns():
                raise ErrInvalidEvidence(
                    "latest block time is before conflicting block time"
                )

    if common_header.height != conflicting.height:
        # lunatic: one skipping verification from the common ancestor
        try:
            verify_commit_light_trusting(
                state.chain_id, common_vals, conflicting.commit, DEFAULT_TRUST_LEVEL
            )
        except Exception as e:  # noqa: BLE001
            raise ErrInvalidEvidence(
                f"skipping verification of conflicting block failed: {e}"
            ) from e
    elif ev.conflicting_header_is_invalid(trusted_header.header):
        raise ErrInvalidEvidence(
            "common height equals conflicting height, so the conflicting "
            "block must be correctly derived, yet it wasn't"
        )

    try:
        verify_commit_light(
            state.chain_id,
            conflicting.validator_set,
            conflicting.commit.block_id,
            conflicting.height,
            conflicting.commit,
        )
    except Exception as e:  # noqa: BLE001
        raise ErrInvalidEvidence(f"invalid commit from conflicting block: {e}") from e

    if ev.total_voting_power != common_vals.total_voting_power():
        raise ErrInvalidEvidence(
            f"total voting power mismatch: evidence {ev.total_voting_power}, "
            f"common valset {common_vals.total_voting_power()}"
        )

    if conflicting.height > trusted_header.height:
        # forward lunatic must violate monotonic time to be an infraction
        if conflicting.time.unix_ns() > trusted_header.time.unix_ns():
            raise ErrInvalidEvidence(
                "conflicting block doesn't violate monotonically increasing time"
            )
    elif trusted_header.hash() == conflicting.hash():
        raise ErrInvalidEvidence(
            "trusted header hash matches the evidence's conflicting header hash"
        )

    # ABCI component: byzantine validators recorded = derived (verify.go:220-262)
    expected = ev.get_byzantine_validators(common_vals, trusted_header)
    got = ev.byzantine_validators
    if len(expected) != len(got):
        raise ErrInvalidEvidence(
            f"byzantine validator count mismatch: evidence {len(got)}, derived {len(expected)}"
        )
    for e_val, g_val in zip(expected, got):
        if e_val.address != g_val.address or e_val.voting_power != g_val.voting_power:
            raise ErrInvalidEvidence("byzantine validator mismatch")
