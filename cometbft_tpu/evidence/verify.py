"""Evidence verification (reference: evidence/verify.go).

verify_evidence: age/expiry checks against consensus params + dispatch by
type (verify.go:19-108). verify_duplicate_vote: the equivocation proof
check (verify.go:166-232) — both votes must be valid signatures from the
same validator over the same height/round/type but different block IDs.
Signature checks ride the batch verifier (two sigs per evidence coalesce
with anything else in flight on the device).
"""

from __future__ import annotations

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.state.state import State
from cometbft_tpu.types.evidence import DuplicateVoteEvidence, Evidence, LightClientAttackEvidence
from cometbft_tpu.types.validator import ValidatorSet


class ErrInvalidEvidence(Exception):
    pass


def verify_evidence(ev: Evidence, state: State, get_validators) -> None:
    """verify.go:19-108 minus the light-client branch plumbing:
    - the evidence must not be expired (height AND time window)
    - the evidence height's validator set must contain the culprit(s)
    get_validators(height) -> ValidatorSet | None (historical lookup)."""
    ev_params = state.consensus_params.evidence
    height = state.last_block_height
    age_num_blocks = height - ev.height()
    age_ns = state.last_block_time.unix_ns() - ev.time().unix_ns()
    if (
        age_num_blocks > ev_params.max_age_num_blocks
        and age_ns > ev_params.max_age_duration_ns
    ):
        raise ErrInvalidEvidence(
            f"evidence from height {ev.height()} is too old; "
            f"min height is {height - ev_params.max_age_num_blocks}"
        )
    val_set = get_validators(ev.height())
    if val_set is None:
        raise ErrInvalidEvidence(f"no validator set at evidence height {ev.height()}")

    if isinstance(ev, DuplicateVoteEvidence):
        verify_duplicate_vote(ev, state.chain_id, val_set)
    elif isinstance(ev, LightClientAttackEvidence):
        _verify_light_client_attack(ev, state, val_set)
    else:
        raise ErrInvalidEvidence(f"unknown evidence type {type(ev).__name__}")


def verify_duplicate_vote(
    ev: DuplicateVoteEvidence, chain_id: str, val_set: ValidatorSet
) -> None:
    """verify.go:166-232."""
    a, b = ev.vote_a, ev.vote_b
    if a.height != b.height or a.round_ != b.round_ or a.type_ != b.type_:
        raise ErrInvalidEvidence(
            f"h/r/s mismatch: {a.height}/{a.round_}/{a.type_} vs {b.height}/{b.round_}/{b.type_}"
        )
    if a.block_id.key() == b.block_id.key():
        raise ErrInvalidEvidence("block IDs are the same; not an equivocation")
    if a.validator_address != b.validator_address:
        raise ErrInvalidEvidence(
            f"validator addresses differ: {a.validator_address.hex()} vs {b.validator_address.hex()}"
        )
    if a.validator_index != b.validator_index:
        raise ErrInvalidEvidence("validator indices differ")
    _, val = val_set.get_by_address(a.validator_address)
    if val is None:
        raise ErrInvalidEvidence(
            f"address {a.validator_address.hex()} was not a validator at height {a.height}"
        )
    # powers recorded in the evidence must match the historical set
    if ev.validator_power != val.voting_power:
        raise ErrInvalidEvidence(
            f"validator power mismatch: evidence {ev.validator_power}, valset {val.voting_power}"
        )
    if ev.total_voting_power != val_set.total_voting_power():
        raise ErrInvalidEvidence(
            f"total voting power mismatch: evidence {ev.total_voting_power}, "
            f"valset {val_set.total_voting_power()}"
        )
    # both signatures must verify under the culprit's key (batched: 2 sigs)
    bv = crypto_batch.create_batch_verifier(val.pub_key)
    bv.add(val.pub_key, a.sign_bytes(chain_id), a.signature)
    bv.add(val.pub_key, b.sign_bytes(chain_id), b.signature)
    ok, mask = bv.verify()
    if not ok:
        which = "A" if not mask[0] else "B"
        raise ErrInvalidEvidence(f"invalid signature on vote {which}")


def _verify_light_client_attack(
    ev: LightClientAttackEvidence, state: State, common_vals: ValidatorSet
) -> None:
    """verify.go:110-164 shape: validated once the light client lands
    (conflicting header must be signed by 1/3+ of the common valset). The
    pool rejects LC evidence until then rather than accepting it
    unverified."""
    raise ErrInvalidEvidence(
        "light-client attack evidence requires the light client (not yet wired)"
    )
