"""Inspect mode (reference: inspect/inspect.go).

Serves the data-backed subset of the RPC over a STOPPED node's stores:
status, block/blockchain/commit/validators, tx + block search. No
consensus, no p2p, no app — a crashed or halted node's disk can be
examined (and a light client can even use it as a primary) without
running the node.
"""

from __future__ import annotations

import asyncio
import os
import signal

from cometbft_tpu.config import Config
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.rpc.server import RPCServer
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.state.txindex import BlockIndexer, NullTxIndexer, TxIndexer
from cometbft_tpu.store import BlockStore
from cometbft_tpu.store.db import open_db
from cometbft_tpu.types.event_bus import EventBus
from cometbft_tpu.version import CMTSemVer as VERSION


class InspectNode:
    """The read-only stand-in for Node that the RPC Environment needs
    (inspect/rpc/rpc.go Routes — the data-backed subset)."""

    def __init__(self, config: Config, logger: cmtlog.Logger):
        self.config = config
        self.logger = logger
        backend = config.base.db_backend
        # honor the node's CRC-guard knob: the data was WRITTEN through
        # the wrapper, so reading it raw would misparse every record
        self.block_store = BlockStore(open_db(
            backend, config.db_path("blockstore"),
            checksum=config.storage.checksum))
        self.state_store = StateStore(open_db(
            backend, config.db_path("state"),
            checksum=config.storage.checksum))
        self.node_key = NodeKey.load_or_gen(config.node_key_path())
        with open(config.genesis_path()) as f:
            from cometbft_tpu.types.genesis import GenesisDoc

            self.genesis_doc = GenesisDoc.from_json(f.read())
        self.node_info = NodeInfo(
            node_id=self.node_key.id(),
            network=self.genesis_doc.chain_id,
            version=VERSION,
            moniker=config.base.moniker + " (inspect)",
            rpc_address=config.rpc.laddr,
        )
        if config.tx_index.indexer == "kv":
            db = open_db(backend, config.db_path("tx_index"))
            self.tx_indexer = TxIndexer(db)
            self.block_indexer = BlockIndexer(db)
        else:
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = None
        self.event_bus = EventBus()
        self.metrics_registry = None
        # RPC routes that need these return empty/error in inspect mode
        self.priv_validator = None
        self.mempool = _NoMempool()
        self.consensus_state = None
        self.consensus_reactor = _NoReactor()
        self.evidence_pool = _NoEvidence()
        self.switch = _NoSwitch()
        self.proxy_app = None

    @property
    def state(self):
        return self.state_store.load()


class _NoMempool:
    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0

    def reap_max_txs(self, n: int) -> list:
        return []


class _NoEvidence:
    def add_evidence(self, ev):
        raise RuntimeError("inspect mode: evidence intake disabled")

    def pending_evidence(self, max_bytes: int):
        return [], 0


class _NoReactor:
    wait_sync = False


class _NoSwitch:
    peers: dict = {}

    def n_peers(self) -> int:
        return 0


async def run_inspect(config: Config) -> None:
    """Serve until SIGINT/SIGTERM (inspect.go Run)."""
    # CBFT_LOG_FORMAT overlays base.log_format, same as Node.__init__ —
    # otherwise the main logger and default()-built library loggers
    # would disagree on format under the env override
    log_fmt = (os.environ.get("CBFT_LOG_FORMAT", "").strip().lower()
               or config.base.log_format)
    cmtlog.set_default_format(log_fmt)
    logger = cmtlog.Logger(level=cmtlog.parse_level(config.base.log_level),
                           fmt=log_fmt)
    node = InspectNode(config, logger)
    server = RPCServer(node, config.rpc, logger=logger.with_fields(module="rpc"))
    await server.start()
    logger.info("inspect RPC serving", addr=server.bound_addr,
                height=node.block_store.height())
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()
