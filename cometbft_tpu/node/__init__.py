from cometbft_tpu.node.node import Node, init_files

__all__ = ["Node", "init_files"]
