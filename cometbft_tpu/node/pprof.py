"""Live profiler endpoint — the `pprof_laddr` analog.

Reference: node/node.go:868-882 wires net/http/pprof onto
config.RPC.PprofListenAddress. Python equivalents, served as plain HTTP on
the same config field:

  GET /debug/pprof/profile?seconds=N[&format=text]
      cProfile of the node's MAIN thread (the asyncio event loop — where
      all consensus/p2p/rpc Python work runs) for N seconds (default 5,
      max 120). Default response is the marshalled pstats dump (load with
      pstats.Stats(file)); format=text returns a cumulative-time table.
  GET /debug/pprof/heap[?format=text]
      tracemalloc snapshot. Tracing starts on the FIRST heap request (the
      reference's heap profile is likewise since-start-of-tracking);
      responses report top allocation sites since then.
  GET /debug/pprof/stacks
      every thread's current Python stack (the goroutine-dump analog; also
      available as SIGUSR1 on the process, cmd.py).

Profiling is on-demand and idle-cost-free except tracemalloc once /heap
has been requested (documented overhead, as with the reference's
mutex/block profiles).
"""

from __future__ import annotations

import asyncio
import cProfile
import io
import marshal
import pstats
import sys
import traceback
import urllib.parse

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService

MAX_PROFILE_SECONDS = 120


def _all_stacks_text() -> str:
    import threading

    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {ident} ({names.get(ident, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


class PprofServer(BaseService):
    """Plain-HTTP profiler plane, separate from the RPC listener (like the
    reference's pprof mux)."""

    def __init__(self, laddr: str, logger: cmtlog.Logger | None = None):
        super().__init__("Pprof", logger or cmtlog.default().with_fields(
            module="pprof"))
        self.laddr = laddr
        self.bound_addr = ""
        self._server: asyncio.Server | None = None
        self._profiling = False
        self._started_tracemalloc = False

    async def on_start(self) -> None:
        addr = self.laddr.removeprefix("tcp://").removeprefix("http://")
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle, host or "127.0.0.1", int(port))
        sock = self._server.sockets[0].getsockname()
        self.bound_addr = f"{sock[0]}:{sock[1]}"
        self.logger.info("pprof listening", addr=self.bound_addr)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._started_tracemalloc:
            # tracemalloc taxes every allocation in the whole process;
            # never leave it running past the profiler's lifetime
            import tracemalloc

            tracemalloc.stop()
            self._started_tracemalloc = False

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await reader.readline()
            while True:  # drain headers
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            parts = line.decode("latin1").split()
            if len(parts) < 2 or parts[0] != "GET":
                await self._respond(writer, 405, b"method not allowed\n")
                return
            path, _, query = parts[1].partition("?")
            params = {k: v[0] for k, v in
                      urllib.parse.parse_qs(query).items()}
            if path == "/debug/pprof/profile":
                await self._profile(writer, params)
            elif path == "/debug/pprof/heap":
                await self._heap(writer, params)
            elif path == "/debug/pprof/stacks":
                await self._respond(writer, 200, _all_stacks_text().encode())
            elif path in ("/", "/debug/pprof", "/debug/pprof/"):
                await self._respond(
                    writer, 200,
                    b"pprof endpoints: /debug/pprof/profile?seconds=N"
                    b"[&format=text], /debug/pprof/heap[?format=text], "
                    b"/debug/pprof/stacks\n")
            else:
                await self._respond(writer, 404, b"unknown pprof route\n")
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _profile(self, writer, params: dict) -> None:
        import math

        try:
            s = float(params.get("seconds", "5"))
        except ValueError:
            await self._respond(writer, 400, b"bad seconds\n")
            return
        if not math.isfinite(s):  # nan/inf must never reach asyncio.sleep
            await self._respond(writer, 400, b"bad seconds\n")
            return
        seconds = min(MAX_PROFILE_SECONDS, max(0.0, s))
        if self._profiling:
            await self._respond(writer, 409, b"profile already running\n")
            return
        self._profiling = True
        try:
            prof = cProfile.Profile()
            prof.enable()
            try:
                await asyncio.sleep(seconds)
            finally:
                prof.disable()
            prof.create_stats()
            if params.get("format") == "text":
                buf = io.StringIO()
                pstats.Stats(prof, stream=buf).sort_stats(
                    "cumulative").print_stats(60)
                await self._respond(writer, 200, buf.getvalue().encode())
            else:
                await self._respond(
                    writer, 200, marshal.dumps(prof.stats),
                    ctype="application/octet-stream")
        finally:
            self._profiling = False

    async def _heap(self, writer, params: dict) -> None:
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start(12)
            self._started_tracemalloc = True
            await self._respond(
                writer, 200,
                b"tracemalloc started; request /debug/pprof/heap again for "
                b"allocations since now\n")
            return
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")
        lines = [f"heap: {len(stats)} allocation sites, "
                 f"{sum(s.size for s in stats)} bytes tracked"]
        lines += [str(s) for s in stats[:80]]
        await self._respond(writer, 200, ("\n".join(lines) + "\n").encode())

    @staticmethod
    async def _respond(writer, status: int, body: bytes,
                       ctype: str = "text/plain") -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 409: "Conflict"}.get(status, "")
        writer.write(
            (f"HTTP/1.1 {status} {reason}\r\nContent-Type: {ctype}\r\n"
             f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
             ).encode() + body)
        await writer.drain()
