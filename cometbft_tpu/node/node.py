"""Node assembly: the dependency-injection root.

Reference: node/node.go:263-524 NewNode + OnStart (node.go:527). Boot
order mirrors the reference call stack (SURVEY §3.1):

  init DBs -> load state (db or genesis) -> start proxy app conns ->
  event switch -> privval -> [handshake replay] -> mempool -> evidence ->
  block executor -> consensus -> reactors -> transport/switch -> dial
  persistent peers -> RPC

`init_files` is the `cometbft init` analog (cmd/cometbft/commands/init.go):
write genesis + node key + privval key under the home dir.
"""

from __future__ import annotations

import os

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.blocksync import BlocksyncReactor
from cometbft_tpu.config import Config
from cometbft_tpu.consensus import ConsensusState
from cometbft_tpu.consensus import timeline as cmttimeline
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.evidence import EvidencePool
from cometbft_tpu.evidence.reactor import EvidenceReactor
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs import trace as cmttrace
from cometbft_tpu.libs.events import EventSwitch
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.mempool.mempool import CListMempool
from cometbft_tpu.mempool.reactor import MempoolReactor
from cometbft_tpu.p2p.conn.connection import MConnConfig
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.p2p.transport import Transport
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.proxy import (
    AppConns,
    grpc_client_creator,
    local_client_creator,
    socket_client_creator,
)
from cometbft_tpu.state import BlockExecutor, State, StateStore
from cometbft_tpu.state.txindex import (
    BlockIndexer,
    IndexerService,
    NullTxIndexer,
    TxIndexer,
)
from cometbft_tpu.store import BlockStore
from cometbft_tpu.store.db import open_db
from cometbft_tpu.types.event_bus import EventBus
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.utils import cmttime
from cometbft_tpu.version import CMTSemVer as VERSION


def _strip_tcp(addr: str) -> str:
    return addr.removeprefix("tcp://")


def init_files(home: str, chain_id: str = "", moniker: str = "node") -> Config:
    """`init` command (cmd/cometbft/commands/init.go): write config.toml,
    genesis.json (single validator = this node), node key, privval key."""
    cfg = Config(home=home)
    cfg.base.moniker = moniker
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    cfg.save()

    pv = FilePV.load_or_generate(
        cfg.priv_validator_key_path(), cfg.priv_validator_state_path()
    )
    NodeKey.load_or_gen(cfg.node_key_path())

    gpath = cfg.genesis_path()
    if not os.path.exists(gpath):
        gdoc = GenesisDoc(
            genesis_time=cmttime.canonical_now_ms(),
            chain_id=chain_id or f"test-chain-{os.urandom(3).hex()}",
            validators=[
                GenesisValidator(
                    address=pv.get_pub_key().address(),
                    pub_key=pv.get_pub_key(),
                    power=10,
                    name=moniker,
                )
            ],
        )
        gdoc.validate_and_complete()
        with open(gpath, "w") as f:
            f.write(gdoc.to_json())
    return cfg


class Node(BaseService):
    """node/node.go:234 Node: owns every subsystem."""

    def __init__(self, config: Config, logger: cmtlog.Logger | None = None,
                 app=None, genesis_doc: GenesisDoc | None = None):
        # CBFT_LOG_FORMAT overlays base.log_format (the CBFT_TRACE
        # pattern: env wins at boot, config is the durable knob);
        # normalized so CBFT_LOG_FORMAT=JSON means json, and an unknown
        # value fails loudly in set_default_format below
        log_fmt = (os.environ.get("CBFT_LOG_FORMAT", "").strip().lower()
                   or config.base.log_format)
        if logger is None:
            logger = cmtlog.Logger(
                level=cmtlog.parse_level(config.base.log_level),
                fmt=log_fmt,
            )
        super().__init__("Node", logger)
        self.config = config
        config.validate_basic()

        # process-wide default log format: deep library log sites
        # (kernels, scheduler, supervisors) follow the node's choice, and
        # JSON records carry trace/span ids for slow-batch correlation
        cmtlog.set_default_format(log_fmt)
        # flight recorder (libs/trace.py): ARM-only — a node booting with
        # tracing off never disarms a tracer a test/bench armed directly.
        # CBFT_TRACE overlays the config knob (the CBFT_CHAOS pattern).
        inst = config.instrumentation
        env_trace = os.environ.get("CBFT_TRACE")
        tracing = (env_trace.strip().lower() not in ("", "0", "false",
                                                     "off", "no")
                   if env_trace is not None else inst.tracing)
        if tracing:
            cmttrace.configure(
                enabled=True, capacity=inst.trace_buffer_spans,
                slow_ms=inst.trace_slow_ms,
                slow_captures=inst.trace_slow_captures)
        # consensus heightline (consensus/timeline.py): ARM-only, the
        # same overlay pattern — CBFT_TIMELINE wins over the config knob
        env_tl = os.environ.get("CBFT_TIMELINE")
        timeline_on = (env_tl.strip().lower() not in ("", "0", "false",
                                                      "off", "no")
                       if env_tl is not None else inst.timeline)
        if timeline_on:
            cmttimeline.configure(
                enabled=True, heights=inst.timeline_heights,
                slow_ms=inst.height_slow_ms,
                postmortems=inst.postmortem_captures)

        # crypto backend selection + device-fault supervision knobs
        # (BASELINE: --crypto.backend flag; ops/dispatch.py supervisor)
        crypto_batch.configure(config.crypto)

        # device backends: arm the persistent XLA compilation cache so a
        # node (re)start loads compiled verify executables instead of
        # re-tracing them — on a multi-chip mesh EVERY chip instantiates
        # its own executable, and paying a cold compile per chip inside
        # live consensus rounds would eat the liveness budget
        if config.crypto.backend != "cpu":
            try:
                import jax

                repo_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                jax.config.update("jax_compilation_cache_dir",
                                  os.path.join(repo_root, ".jax_cache"))
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 2)
            except Exception:  # noqa: BLE001 - cache is an optimization
                pass

        # network-fault schedule (p2p/netchaos.py; CBFT_NET_CHAOS overlays)
        if config.p2p.chaos:
            from cometbft_tpu.p2p import netchaos

            netchaos.arm_spec(config.p2p.chaos)

        # disk-fault schedule (libs/diskchaos.py; CBFT_DISK_CHAOS overlays)
        if config.storage.chaos:
            from cometbft_tpu.libs import diskchaos

            diskchaos.arm_spec(config.storage.chaos)

        # ---- genesis + identity (node.go:274-300)
        if genesis_doc is None:
            with open(config.genesis_path()) as f:
                genesis_doc = GenesisDoc.from_json(f.read())
        self.genesis_doc = genesis_doc
        self.node_key = NodeKey.load_or_gen(config.node_key_path())

        # ---- storage (node/setup.go:127 initDBs)
        backend = config.base.db_backend
        sync_mode = config.storage.synchronous
        # CRC-guard exactly the stores a rotted bit can turn into an
        # accepted-but-wrong block: block records and state records
        self.block_store = BlockStore(open_db(
            backend, config.db_path("blockstore"),
            synchronous=sync_mode, checksum=config.storage.checksum))
        self.state_store = StateStore(open_db(
            backend, config.db_path("state"),
            synchronous=sync_mode, checksum=config.storage.checksum))
        state = self.state_store.load()
        if state is None:
            state = State.from_genesis(genesis_doc)
            self.state_store.bootstrap(state)

        # ---- application (node.go:302 createAndStartProxyAppConns)
        if app is not None:
            creator = local_client_creator(app)
        elif config.base.proxy_app == "kvstore":
            app = KVStoreApplication()
            creator = local_client_creator(app)
        elif config.base.proxy_app.startswith("grpc://"):
            creator = grpc_client_creator(config.base.proxy_app)
        elif config.base.proxy_app.startswith("tcp://") or config.base.proxy_app.startswith("unix://"):
            creator = socket_client_creator(config.base.proxy_app)
        else:
            raise ValueError(f"unknown proxy_app {config.base.proxy_app!r}")
        self.app = app
        self.proxy_app = AppConns(creator)

        # ---- privval (node.go:324)
        self.priv_validator = FilePV.load_or_generate(
            config.priv_validator_key_path(), config.priv_validator_state_path()
        )

        # ---- mempool + evidence (node.go:369-388)
        self.mempool = CListMempool(config.mempool, None)  # app conn wired on start
        self._evidence_db = open_db(backend, config.db_path("evidence"),
                                    synchronous=sync_mode)
        self.evidence_pool = EvidencePool(self._evidence_db, self.state_store,
                                          block_store=self.block_store)
        self.event_switch = EventSwitch()
        self.event_bus = EventBus()

        # ---- indexers (node.go:311-320 createAndStartIndexerService)
        self._sql_sink = None
        if config.tx_index.indexer == "kv":
            self._indexer_db = open_db(backend, config.db_path("tx_index"),
                                       synchronous=sync_mode)
            self.tx_indexer = TxIndexer(self._indexer_db)
            self.block_indexer = BlockIndexer(self._indexer_db)
        elif config.tx_index.indexer == "sql":
            # psql-sink analog on sqlite: write-only relational sink, no
            # RPC search (state/indexer/sink/psql contract)
            from cometbft_tpu.state.indexer_sql import SQLEventSink

            self._indexer_db = None
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = None
            self._sql_sink = SQLEventSink(
                config.db_path("tx_events"), self.genesis_doc.chain_id)
        else:
            self._indexer_db = None
            self.tx_indexer = NullTxIndexer()
            self.block_indexer = None
        self.indexer_service = IndexerService(
            self.tx_indexer, self.block_indexer, self.event_bus,
            logger=self.logger.with_fields(module="txindex"),
            sql_sink=self._sql_sink,
        ) if (self._indexer_db is not None or self._sql_sink is not None) else None

        # ---- execution + consensus (node.go:391-425)
        # ---- metrics (node.go:300 DefaultMetricsProvider; per-node registry
        # so in-process multi-node tests don't cross-count)
        from cometbft_tpu.libs import metrics as cmtmetrics

        self.metrics_registry = cmtmetrics.Registry()
        # cometbft_build_info: constant-1 gauge whose labels carry the
        # build — fleet scrapes correlate behavior with version/backend
        # (the node_exporter build_info convention)
        from cometbft_tpu import version as _version

        schemes = ["ed25519", "secp256k1", "sr25519"]
        if getattr(config.crypto, "bls_enabled", False):
            schemes.append("bls12381")
        self.metrics_registry.gauge(
            "build", "info", "Build/version information (value is always 1).",
            labels=("version", "abci", "block_protocol", "p2p_protocol",
                    "tpu_crypto_backend", "backend", "schemes"),
        ).labels(
            _version.CMTSemVer, _version.ABCIVersion,
            str(_version.BlockProtocol), str(_version.P2PProtocol),
            str(_version.TPUCryptoBackend), config.crypto.backend,
            ",".join(schemes),
        ).set(1)
        self.consensus_metrics = cmtmetrics.ConsensusMetrics(self.metrics_registry)
        self.mempool_metrics = cmtmetrics.MempoolMetrics(self.metrics_registry)
        self.p2p_metrics = cmtmetrics.P2PMetrics(
            self.metrics_registry, peer_cap=config.p2p.metrics_peer_cap)
        self.evidence_metrics = cmtmetrics.EvidenceMetrics(self.metrics_registry)
        self.mempool.metrics = self.mempool_metrics
        self.evidence_pool.metrics = self.evidence_metrics

        # ---- overload plane (libs/overload.py, no reference analog):
        # one per-node pressure registry every plane grades itself
        # against. Signals registered here read state that already
        # exists; the RPC server adds its own on start.
        from cometbft_tpu.libs.overload import OverloadRegistry

        self.overload = OverloadRegistry()
        self.mempool.attach_overload(self.overload)
        from cometbft_tpu import sched as _sched_mod

        self.overload.register(
            "sched",
            lambda: (sum(_sched_mod.get()._depth.values())
                     / max(1, _sched_mod.get().queue_limit)))
        self.overload.register(
            "events", self.event_bus.server.max_lag_fraction)

        # ---- commit-certificate plane (cert/, no reference analog):
        # succinct finality certificates — produced at commit finalize
        # off the event bus, stored CRC-guarded beside the block store,
        # served over RPC and the negotiated blocksync channel
        self.cert_plane = None
        self.cert_metrics = None
        self._cert_db = None
        if config.cert.enabled:
            from cometbft_tpu.cert import CertPlane, CertStore

            self._cert_db = open_db(
                backend, config.db_path("certs"),
                synchronous=sync_mode, checksum=config.storage.checksum)
            self.cert_metrics = cmtmetrics.CertMetrics(self.metrics_registry)
            self.cert_plane = CertPlane(
                CertStore(self._cert_db), self.block_store, self.state_store,
                genesis_doc.chain_id, event_bus=self.event_bus,
                backfill=config.cert.backfill,
                backfill_batch=config.cert.backfill_batch,
                poll_interval=config.cert.poll_interval,
                metrics=self.cert_metrics,
                logger=self.logger.with_fields(module="cert"),
            )

        # background pruning honoring app/companion retain heights
        # (node.go:263-524 createPruner; state/pruner.go)
        from cometbft_tpu.state.pruner import Pruner

        self.pruner = Pruner(
            self.state_store, self.block_store,
            tx_indexer=self.tx_indexer, block_indexer=self.block_indexer,
            # retain-height advances drop certificates with their blocks
            cert_store=self.cert_plane.store if self.cert_plane else None,
            # a configured privileged gRPC listener means a data companion
            # may set retain heights — the pruner must then honor them
            companion_enabled=bool(config.grpc.privileged_laddr),
            logger=self.logger.with_fields(module="pruner"),
        )

        self.block_exec = BlockExecutor(
            self.state_store, None, self.mempool, evidence_pool=self.evidence_pool,
            event_bus=self.event_bus, pruner=self.pruner,
        )
        wal = WAL(os.path.join(config.wal_path(), "wal"))
        self.consensus_state = ConsensusState(
            config=config.consensus,
            state=state,
            block_exec=self.block_exec,
            block_store=self.block_store,
            wal=wal,
            priv_validator=self.priv_validator,
            event_switch=self.event_switch,
            logger=self.logger.with_fields(module="consensus"),
            metrics=self.consensus_metrics,
        )
        # blocksync runs when enabled and we are not the sole validator
        # (node.go onlyValidatorIsUs — nothing to sync from ourselves)
        self.blocksync_active = config.block_sync.enable and not _only_validator_is_us(
            state, self.priv_validator.get_pub_key()
        )
        # statesync bootstrap: only a node with no committed state
        # (node.go:559 stateSync && state height == 0)
        self.statesync_active = (
            config.state_sync.enable and state.last_block_height == 0
        )
        # heightline recorder identity + slow-height postmortem collector:
        # the recorder exists either way (disabled marks are near-free);
        # the collector only fires on a slow height
        tlr = self.consensus_state.timeline
        tlr.node = self.node_key.id()
        tlr.slow_ms = config.instrumentation.height_slow_ms
        tlr.collector = self._postmortem_context
        self._postmortem_wire_prev: dict = {}
        self.consensus_reactor = ConsensusReactor(
            self.consensus_state,
            wait_sync=self.blocksync_active or self.statesync_active,
            logger=self.logger.with_fields(module="cons-reactor"),
        )
        self.blocksync_reactor = BlocksyncReactor(
            self.block_exec,
            self.block_store,
            # with statesync the pool must start at the restored height:
            # blocksync activates in the statesync handoff instead of boot
            active=self.blocksync_active and not self.statesync_active,
            consensus_reactor=self.consensus_reactor,
            cert_plane=self.cert_plane,
            cert_serve=config.cert.serve if self.cert_plane else False,
            logger=self.logger.with_fields(module="blocksync"),
        )
        # Every node SERVES snapshots on the statesync channels (reference:
        # the reactor always registers, node.go:374); only a fresh node with
        # statesync.enable also SYNCS (state provider + syncer attached).
        from cometbft_tpu.statesync import LightClientStateProvider, StatesyncReactor

        state_provider = None
        if config.state_sync.enable and self.statesync_active:
            from cometbft_tpu.light import Client as LightClient
            from cometbft_tpu.light import TrustOptions
            from cometbft_tpu.light.rpc_provider import RPCProvider
            from cometbft_tpu.light.store import LightStore
            from cometbft_tpu.store.db import MemDB

            ss = config.state_sync
            providers = [
                RPCProvider(genesis_doc.chain_id, url) for url in ss.rpc_servers
            ]
            # fold statesync onto the fleet's shared checkpoint cache
            # (PR 11 residual): bisections start/fast-forward from any
            # checkpoint the serving plane already verified, and every
            # statesync-verified block seeds the cache for the fleet
            from cometbft_tpu.light.fleet import shared_cache

            ckpt_cache = shared_cache(
                genesis_doc.chain_id,
                capacity=config.light.fleet_cache_capacity,
                trust_period_ns=int(ss.trust_period * 1e9),
                skip_base=config.light.fleet_skip_base,
            )

            class _TeeingLightStore(LightStore):
                """Statesync trust store that tees every verified block
                into the shared checkpoint cache."""

                def save_light_block(self, lb):  # noqa: D102
                    super().save_light_block(lb)
                    try:
                        ckpt_cache.put(lb)
                    except Exception:  # noqa: BLE001 - cache is a bonus
                        pass

            lc = LightClient(
                genesis_doc.chain_id,
                TrustOptions(
                    period_ns=int(ss.trust_period * 1e9),
                    height=ss.trust_height,
                    hash_=bytes.fromhex(ss.trust_hash),
                ),
                providers[0], providers[1:], _TeeingLightStore(MemDB()),
                logger=self.logger.with_fields(module="light"),
            )
            _own_source = lc.checkpoint_source

            def _cached_source(h, _own=_own_source, _c=ckpt_cache):
                hit = _c.nearest_at_or_below(h)
                return hit if hit is not None else _own(h)

            lc.checkpoint_source = _cached_source
            self._statesync_light_client = lc
            state_provider = LightClientStateProvider(
                lc, initial_height=state.initial_height,
                consensus_params=state.consensus_params,
            )
        self.statesync_reactor = StatesyncReactor(
            None,  # snapshot conn wired at start (proxy conns live then)
            state_provider=state_provider,
            logger=self.logger.with_fields(module="statesync"),
            chunk_timeout=config.state_sync.chunk_request_timeout,
        )
        self.mempool_reactor = MempoolReactor(
            self.mempool, logger=self.logger.with_fields(module="mempool"))
        self.evidence_reactor = EvidenceReactor(
            self.evidence_pool, logger=self.logger.with_fields(module="evidence"))

        # ---- p2p (node.go:443-482)
        self.node_info = NodeInfo(
            node_id=self.node_key.id(),
            network=genesis_doc.chain_id,
            version=VERSION,
            moniker=config.base.moniker,
            rpc_address=config.rpc.laddr,
        )
        fuzz_cfg = None
        if config.p2p.test_fuzz:
            from cometbft_tpu.p2p.fuzz import FuzzConnConfig

            fuzz_cfg = FuzzConnConfig(
                mode=config.p2p.test_fuzz_mode,
                prob_drop_rw=config.p2p.test_fuzz_prob_drop_rw,
                prob_drop_conn=config.p2p.test_fuzz_prob_drop_conn,
                prob_sleep=config.p2p.test_fuzz_prob_sleep,
                max_delay=config.p2p.test_fuzz_max_delay,
            )
        self.transport = Transport(
            self.node_key, self.node_info,
            logger=self.logger.with_fields(module="p2p"),
            fuzz_config=fuzz_cfg,
        )
        from cometbft_tpu.p2p.switch import PeerScorer

        self.switch = Switch(
            self.transport,
            mconn_config=MConnConfig(
                send_rate=config.p2p.send_rate,
                recv_rate=config.p2p.recv_rate,
                max_packet_msg_payload_size=config.p2p.max_packet_msg_payload_size,
                flush_throttle=config.p2p.flush_throttle_timeout,
            ),
            logger=self.logger.with_fields(module="p2p"),
            scorer=PeerScorer(
                ban_threshold=config.p2p.ban_score_threshold,
                ban_base=config.p2p.ban_duration,
                ban_max=config.p2p.ban_max_duration,
                half_life=config.p2p.ban_score_half_life,
            ),
        )
        self.switch.metrics = self.p2p_metrics
        # consensus-detected offenses (forged vote signatures) feed the
        # same ban ledger as transport-level errors
        self.consensus_state.misbehavior_hook = self.switch.report_misbehavior
        self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
        self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)
        self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
        self.switch.add_reactor("EVIDENCE", self.evidence_reactor)
        self.switch.add_reactor("STATESYNC", self.statesync_reactor)

        # ---- pex (node.go:498 createPEXReactorAndAddToSwitch)
        self.addr_book = None
        self.pex_reactor = None
        if config.p2p.pex:
            import random as _random

            from cometbft_tpu.p2p.pex import AddrBook, NetAddress, PEXReactor

            self.addr_book = AddrBook(
                os.path.join(config.home, config.p2p.addr_book_file),
                our_id=self.node_key.id(),
            )
            self.addr_book.metrics = self.p2p_metrics
            if self.addr_book.load_error:
                self.logger.error(
                    "address book corrupt; quarantined and booting empty",
                    err=self.addr_book.load_error,
                    quarantined=self.addr_book.quarantined_path,
                )
            for seed in config.p2p.seed_list():
                self.addr_book.add_address(NetAddress.parse(seed))
            # persistent peers are operator intent: pinned in the book,
            # exempt from eviction and the per-group outbound cap
            for pp in config.p2p.persistent_peer_list():
                try:
                    ppa = NetAddress.parse(pp)
                except (ValueError, TypeError):
                    continue
                self.addr_book.add_address(ppa)
                self.addr_book.mark_protected(ppa.node_id)
            self.pex_reactor = PEXReactor(
                self.addr_book,
                max_outbound=config.p2p.max_num_outbound_peers,
                seed_mode=config.p2p.seed_mode,
                ensure_interval=config.p2p.pex_ensure_interval,
                max_group_outbound=config.p2p.max_outbound_per_group,
                rng=_random.Random(self.node_key.id()),
                logger=self.logger.with_fields(module="pex"),
            )
            self.switch.add_reactor("PEX", self.pex_reactor)
            # a switch ban also marks the address book so PEX neither
            # offers nor dials the peer until the ban decays
            self.switch.on_ban = self.addr_book.mark_bad

        # TEST/E2E ONLY: adversarial validator mode (consensus/byzantine.py)
        self._byzantine = None
        if config.consensus.byzantine:
            from cometbft_tpu.consensus.byzantine import (
                make_byzantine,
                switch_vote_sender,
            )

            self._byzantine = make_byzantine(
                self.consensus_state, config.consensus.byzantine,
                send=switch_vote_sender(self.switch),
            )
            self.logger.info("BYZANTINE MODE ARMED",
                             behavior=config.consensus.byzantine)

        self.rpc_server = None  # attached on start when rpc.laddr set
        self.pprof_server = None
        self.grpc_server = None
        self.grpc_priv_server = None

    # ------------------------------------------------- slow-height bundles

    def _postmortem_context(self, height: int) -> dict:
        """Bounded node context captured into a slow-height postmortem
        bundle (consensus/timeline.py Recorder): the matching slow span
        capture from the flight recorder, the gossip-accounting snapshot,
        wire-counter deltas since the previous capture, and scheduler /
        verify-mesh health. Every section degrades to None independently
        — a broken subsystem must not cost the bundle."""
        ctx: dict = {}
        try:
            caps = cmttrace.slow_captures()
            # prefer the capture of THIS height's span tree; else newest
            pick = None
            for c in reversed(caps):
                if (c.get("root") == "consensus.height"
                        and c.get("attrs", {}).get("height") == height):
                    pick = c
                    break
            if pick is None and caps:
                pick = caps[-1]
            if pick is not None:
                ctx["span_capture"] = {
                    "root": pick.get("root"),
                    "dur_ms": pick.get("dur_ms"),
                    "attrs": pick.get("attrs"),
                    "spans": pick.get("spans", [])[:200],
                }
        except Exception:  # noqa: BLE001
            ctx["span_capture"] = None
        try:
            ctx["gossip"] = self.consensus_reactor.gossip_accounting()
        except Exception:  # noqa: BLE001
            ctx["gossip"] = None
        try:
            tele = self.switch.net_telemetry()
            totals = dict(tele.get("totals") or {})
            prev = self._postmortem_wire_prev
            ctx["wire_totals"] = totals
            ctx["wire_deltas"] = {
                k: round(v - prev.get(k, 0), 3) if isinstance(v, float)
                else v - prev.get(k, 0)
                for k, v in totals.items() if isinstance(v, (int, float))}
            self._postmortem_wire_prev = totals
            ctx["channels"] = tele.get("channels")
        except Exception:  # noqa: BLE001
            ctx["wire_totals"] = ctx["wire_deltas"] = None
        try:
            from cometbft_tpu import sched

            ctx["scheduler"] = sched.health_snapshot()
        except Exception:  # noqa: BLE001
            ctx["scheduler"] = None
        try:
            from cometbft_tpu.ops import dispatch

            ctx["crypto_backend"] = dispatch.health_snapshot()
        except Exception:  # noqa: BLE001
            ctx["crypto_backend"] = None
        return ctx

    # ------------------------------------------------------------ lifecycle

    async def on_start(self) -> None:
        """node.go:527 OnStart."""
        if self.indexer_service is not None:
            await self.indexer_service.start()
        await self.pruner.start()
        if self.cert_plane is not None:
            await self.cert_plane.start()

        # bridge the consensus fast-path EventSwitch into the async EventBus
        # so RPC subscribers see round transitions (state.go:129-131 dual
        # event plane)
        from cometbft_tpu.types import event_bus as eb

        def _rs_bridge(rs) -> None:
            self.event_bus.server.publish(
                eb.EventDataRoundState(rs.height, rs.round_, str(rs.step)),
                {eb.EVENT_TYPE_KEY: [eb.EVENT_NEW_ROUND_STEP]},
            )

        self.event_switch.add_listener("node-bus", "NewRoundStep", _rs_bridge)

        await self.proxy_app.start()
        # wire the live app conns (created only at proxy start)
        self.mempool.app_conn = self.proxy_app.mempool
        self.block_exec.app_conn = self.proxy_app.consensus

        # ABCI handshake: replay blocks the app missed (replay.go:241)
        from cometbft_tpu.consensus.replay import Handshaker

        hs = Handshaker(
            self.state_store, self.block_store, self.genesis_doc,
            logger=self.logger.with_fields(module="handshake"),
        )
        state = await hs.handshake(self.proxy_app)
        self.consensus_state.sync_to_state(state)
        self.blocksync_reactor.set_state(self.consensus_state.state)

        # the statesync reactor needs the live snapshot connection
        self.statesync_reactor.conn = self.proxy_app.snapshot
        if self.statesync_reactor.syncer is not None:
            self.statesync_reactor.syncer.conn = self.proxy_app.snapshot

        # pre-trace the verify scheduler's bucket ladder so the first
        # real consensus flush doesn't pay a cold device compile
        # mid-round (no-op off the TPU backend)
        if self.config.crypto.sched_warmup:
            from cometbft_tpu import sched as _sched

            import asyncio as _aio

            cap = self.config.crypto.sched_warmup_max_lanes
            traced = await _aio.get_running_loop().run_in_executor(
                None, lambda: _sched.get().warmup(cap))
            if traced:
                self.logger.info("verify scheduler warmup", shapes=str(traced))

        addr = await self.transport.listen(_strip_tcp(self.config.p2p.laddr))
        self.node_info.listen_addr = addr
        await self.switch.start()
        if self._byzantine is not None:
            await self._byzantine.start()
        peers = self.config.p2p.persistent_peer_list()
        if peers:
            await self.switch.dial_peers_async(peers, persistent=True)

        # statesync bootstrap (node.go:559 startStateSync): restore a
        # snapshot anchored in light-client-verified headers, then hand off
        # to blocksync starting at the restored height + 1
        if self.statesync_active and self.statesync_reactor.syncer is not None:
            import asyncio as _asyncio

            self._statesync_task = _asyncio.create_task(self._run_statesync())

        if self.config.rpc.laddr:
            from cometbft_tpu.rpc.server import RPCServer

            self.rpc_server = RPCServer(self, self.config.rpc)
            await self.rpc_server.start()

        # live profiler plane (node.go:868-882 pprof mux analog)
        if self.config.rpc.pprof_laddr:
            from cometbft_tpu.node.pprof import PprofServer

            self.pprof_server = PprofServer(self.config.rpc.pprof_laddr)
            await self.pprof_server.start()

        # gRPC service surface (node.go:527 + rpc/grpc/server; disabled
        # unless configured)
        if self.config.grpc.laddr:
            from cometbft_tpu.rpc import grpc_services as gs

            self.grpc_server, self.grpc_bound = gs.serve(
                [gs.VersionService(), gs.BlockService(self.block_store),
                 gs.BlockResultsService(self.state_store, self.block_store)],
                self.config.grpc.laddr)
            self.logger.info("gRPC services listening", addr=self.grpc_bound)
        if self.config.grpc.privileged_laddr:
            from cometbft_tpu.rpc import grpc_services as gs

            self.grpc_priv_server, self.grpc_priv_bound = gs.serve(
                [gs.PruningService(self.pruner)],
                self.config.grpc.privileged_laddr)
            self.logger.info("privileged gRPC listening",
                             addr=self.grpc_priv_bound)

    async def _run_statesync(self) -> None:
        """node.go startStateSync: sync, persist, hand off to blocksync."""
        try:
            state, commit = await self.statesync_reactor.sync(
                discovery_time=self.config.state_sync.discovery_time)
            self.state_store.bootstrap(state)
            # the light-client-verified commit seeds LastCommit
            # reconstruction (node.go startStateSync SaveSeenCommit)
            self.block_store.save_seen_commit(state.last_block_height, commit)
            self.consensus_state.sync_to_state(state)
            self.logger.info("state sync complete; switching to block sync",
                             height=state.last_block_height,
                             app_hash=state.app_hash.hex()[:12])
            await self.blocksync_reactor.activate(state)
        except Exception as e:  # noqa: BLE001 - bootstrap failed: stay put
            import traceback

            self.logger.error("state sync failed", err=str(e),
                              tb=traceback.format_exc(limit=5).replace("\n", " | "))
        finally:
            # stop soliciting snapshots: the sync ran once (ref clears the
            # syncer when the sync ends); serving continues
            self.statesync_reactor.syncer = None

    async def on_stop(self) -> None:
        if getattr(self, "_statesync_task", None) is not None:
            self._statesync_task.cancel()
        if self.rpc_server is not None:
            await self.rpc_server.stop()
        if self.pprof_server is not None:
            await self.pprof_server.stop()
        for srv in (self.grpc_server, self.grpc_priv_server):
            if srv is not None:
                from cometbft_tpu.rpc.grpc_services import wait_closed

                await wait_closed(srv, grace=0.5)
        if self._byzantine is not None:
            await self._byzantine.stop()
        await self.switch.stop()
        await self.proxy_app.stop()
        if self.cert_plane is not None and self.cert_plane.is_running:
            await self.cert_plane.stop()
        if self.pruner.is_running:
            await self.pruner.stop()
        if self.indexer_service is not None and self.indexer_service.is_running:
            await self.indexer_service.stop()
        if self._sql_sink is not None:
            try:
                self._sql_sink.close()
            except Exception:  # noqa: BLE001
                pass
        for db in (self.block_store.db, self.state_store.db, self._evidence_db,
                   self._indexer_db, self._cert_db):
            try:
                db.close()
            except Exception:  # noqa: BLE001
                pass


def _only_validator_is_us(state, pub_key) -> bool:
    """node.go onlyValidatorIsUs."""
    if state.validators is None or len(state.validators) != 1:
        return False
    return state.validators.validators[0].address == pub_key.address()
