"""Version constants.

Mirrors the reference's version package (version/version.go:1-21): a semver
core version plus protocol versions for the block and p2p wire formats and the
ABCI application interface.
"""

# Framework semver.
CMTSemVer = "0.1.0-tpu"

# ABCI application-protocol semver (reference: version/version.go ABCIVersion).
ABCIVersion = "2.0.0"

# Block protocol version (reference: version/version.go BlockProtocol = 11).
BlockProtocol = 11

# P2P protocol version (reference: version/version.go P2PProtocol = 8).
P2PProtocol = 8

# TPU crypto-backend version (new in this framework).
TPUCryptoBackend = 1
