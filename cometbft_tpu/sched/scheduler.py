"""VerifyScheduler — node-wide continuous batching of signature work.

Before this subsystem the TPU only ever saw whatever one caller had on
hand: each VoteSet flushed its own staged batch, blocksync and the light
client formed their own windows, evidence checks dispatched two-row
batches, and mempool admission had no batch path at all. Under real
traffic the device ran many small, shape-diverse batches instead of a few
full ones — and batch size is the dominant term in committee verification
cost (arXiv:2302.00418); the FPGA verification-engine work
(arXiv:2112.02229) gets its throughput from exactly one shared,
always-full hardware verification queue fed by all protocol components.

This module is that queue, built the way an inference server does
continuous batching:

  producers  consensus vote flushes, blocksync/light commit windows,
             evidence checks, mempool admission — all submit rows of
             (pub_key, msg, sig) instead of owning device dispatch.
  classes    CONSENSUS > SYNC > MEMPOOL. A consensus (or sync) caller
             uses verify_now()/verify_many(): the batch drains
             IMMEDIATELY, inline on the calling thread, and coalesces
             whatever compatible queued work fits the bucket as filler.
             Mempool-class work uses submit(): per-item futures, flushed
             by the next inline drain riding along, or by the deadline
             worker when no higher-priority flush arrives in time.
  bucketing  every dispatched batch is padded (by the kernel) to the
             shared bucket ladder (ops/ed25519_kernel.bucket_size):
             powers of two to 2048, then multiples of 2048 — so XLA/
             Pallas compiles a handful of shapes once instead of once
             per unique batch size. warmup() pre-traces the ladder.
  fairness   bounded per-class queues; mempool admission is REJECTED
             (SchedulerSaturated) while consensus/sync backlog already
             fills buckets without it; a starvation guard promotes any
             group overdue past `starvation_limit` into the next batch
             regardless of class order.
  seams      dispatch rides the existing crypto/batch + ops/dispatch
             ladder unchanged: backend resolution consults the circuit
             breaker, device batches run under the DeviceSupervisor with
             the ed25519.*/sr25519.*/pallas.trace/mixed.resolve chaos
             sites armed, and every failure degrades to the CPU oracle.
             The scheduler adds its own chaos site ("sched.flush"): an
             injected scheduler fault falls back to per-group fragmented
             dispatch — verification survives the scheduler dying.

Thread model: the core is lock-guarded and asyncio-free. Inline drains
run on the caller's thread (consensus event loop, blocksync executor).
One lazy daemon worker thread serves deadline flushes; it parks on a
condition variable and only exists once something queues with a deadline.
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

import numpy as np

from cometbft_tpu.libs import trace

# priority classes, highest first (the wire values appear in metrics
# labels and the crypto_health snapshot — keep in sync with README).
# LIGHT is the serving plane's class (light/fleet.py): fleet bisections
# ride below node-critical sync (a catching-up node beats external
# clients) but above mempool filler — and unlike mempool they are never
# rejected at admission (the fleet applies its own saturation gate).
CONSENSUS = "consensus"
SYNC = "sync"
LIGHT = "light"
MEMPOOL = "mempool"
CLASSES = (CONSENSUS, SYNC, LIGHT, MEMPOOL)

# grace beyond a group's deadline before its flush counts as a miss (the
# worker wakes AT the deadline; only contention pushes past this)
_MISS_SLACK = 0.005


class SchedulerSaturated(Exception):
    """Mempool-class admission rejected: the queues already hold more
    work than the next buckets can absorb. Callers shed load (mempool
    turns this into ErrMempoolIsFull) instead of queuing unboundedly."""


# --------------------------------------------------------------- work class
#
# Ambient class for call sites that reach the scheduler through the
# crypto/batch verifier seam (create_batch_verifier has no class
# parameter — its callers predate the scheduler). Consensus-critical is
# the safe default: unlabeled paths (LastCommit reconstruction on
# restart, RPC-triggered verifies) must never be starved behind filler.
#
# A ContextVar, NOT threading.local: the fleet service holds
# work_class(LIGHT) across awaits (provider fetches suspend mid-extent),
# and a thread-local would leak the class to every other coroutine
# interleaving on the loop thread — worse, two overlapping extents
# exiting non-LIFO would poison the ambient class permanently.
# ContextVars are per-task under asyncio and per-thread otherwise, and
# token-based reset is exact under any interleaving.

_ambient: contextvars.ContextVar = contextvars.ContextVar(
    "verify_work_class", default=None)


def current_class() -> str:
    return _ambient.get() or CONSENSUS


@contextmanager
def work_class(klass: str):
    """Set the ambient priority class for verifiers created in this
    dynamic extent — per-task under asyncio, per-thread otherwise
    (blocksync/light/evidence label their verification SYNC through
    this; the fleet labels its bisections LIGHT)."""
    if klass not in CLASSES:
        raise ValueError(f"unknown verify class {klass!r} (classes: {CLASSES})")
    token = _ambient.set(klass)
    try:
        yield
    finally:
        _ambient.reset(token)


# ------------------------------------------------------------------- groups


@dataclass(eq=False)  # identity semantics: groups are queue entries
class _Group:
    """One producer group: rows verified together, one recheck budget (a
    commit's rows must not spend a window-mate's oracle-recheck allowance
    — see ops/ed25519_kernel.apply_recheck). `unit` identifies the
    producer SUBMISSION the group arrived in (a verify_many window is one
    unit of several groups): the fragmented-baseline accounting pads each
    unit to its own bucket, which is exactly what the pre-scheduler
    architecture dispatched — one device batch per producer call."""

    klass: str
    rows: list  # [(crypto.PubKey, bytes msg, bytes sig)]
    submitted_at: float
    unit: int = 0
    deadline: float | None = None  # monotonic; None = inline-only
    futures: list[concurrent.futures.Future] | None = None
    mask: np.ndarray | None = None

    def resolve(self, mask: np.ndarray) -> None:
        self.mask = mask
        if self.futures is not None:
            for fut, ok in zip(self.futures, mask):
                if not fut.done():
                    fut.set_result(bool(ok))

    def fail(self, exc: BaseException) -> None:
        if self.futures is not None:
            for fut in self.futures:
                if not fut.done():
                    fut.set_exception(exc)


class VerifyScheduler:
    """The node-wide verify queue. One instance per process (module-level
    get() in cometbft_tpu/sched/__init__.py) — the device is a
    process-global resource, so its scheduler is too."""

    def __init__(
        self,
        max_lanes: int = 16384,
        sync_deadline: float = 0.002,
        light_deadline: float = 0.004,
        mempool_deadline: float = 0.010,
        queue_limit: int = 16384,
        starvation_limit: float = 0.25,
        clock=time.monotonic,
    ):
        self.max_lanes = max_lanes
        self.class_deadline = {
            CONSENSUS: 0.0, SYNC: sync_deadline, LIGHT: light_deadline,
            MEMPOOL: mempool_deadline,
        }
        self.queue_limit = queue_limit
        self.starvation_limit = starvation_limit
        self._clock = clock
        self._cond = threading.Condition()
        self._queues: dict[str, list[_Group]] = {k: [] for k in CLASSES}
        # running row counts per class (kept in lockstep with _queues so
        # the admission hot path never scans the backlog)
        self._depth: dict[str, int] = {k: 0 for k in CLASSES}
        self._worker: threading.Thread | None = None
        self._stop = False
        # ---- stats (lock: self._cond's lock via _stat calls under lock,
        # or the GIL for single int/float bumps)
        self.batches = 0
        self.rows_total = 0
        self.lanes_total = 0
        # what the SAME groups would have cost dispatched fragment-by-
        # fragment (each producer its own padded batch) — the pre-
        # scheduler architecture, measured on live traffic so fill-ratio
        # gains are asserted against real load, not synthetic replays
        self.frag_lanes_total = 0
        self.deadline_misses = 0
        # per-class attribution: the overload soak asserts consensus
        # flushes miss ZERO deadlines while mempool-class work sheds
        self.deadline_miss_by_class = {k: 0 for k in CLASSES}
        self.rejected = 0
        self.chaos_fallbacks = 0
        self.worker_flushes = 0
        self._shapes: set[int] = set()
        self._class_rows = {k: 0 for k in CLASSES}
        self._unit_seq = 0
        # bounded submit->dispatch latency samples per class (bench/test
        # percentile source; the histogram metric is the scrape surface)
        self._lat: dict[str, list[float]] = {k: [] for k in CLASSES}
        # warm the kernel import chain (jax + ops, ~2s cold) at
        # construction: the first flush must never pay module imports
        # inside its span — they would dominate its latency budget (a
        # phantom slow-batch capture) and sink per-batch span coverage
        from cometbft_tpu.ops import bls_kernel  # noqa: F401
        from cometbft_tpu.ops import ed25519_kernel  # noqa: F401
        from cometbft_tpu.ops import sr25519_kernel  # noqa: F401

    # ------------------------------------------------------------ metrics

    @staticmethod
    def _metrics():
        try:
            from cometbft_tpu.libs import metrics as m

            return m.sched_metrics()
        except Exception:  # noqa: BLE001 - metrics must never break verify
            return None

    def _publish_depth(self) -> None:
        m = self._metrics()
        if m is None:
            return
        try:
            for k in CLASSES:
                m.queue_depth.labels(k).set(self._depth[k])
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------- bucket

    @staticmethod
    def bucket_lanes(n: int) -> int:
        """The padded lane count a batch of n rows dispatches at — the
        single source of truth is the kernel's bucket ladder."""
        from cometbft_tpu.ops import ed25519_kernel

        return ed25519_kernel.bucket_size(max(n, 1))

    def bucket_ladder(self, cap: int | None = None) -> list[int]:
        """Every distinct device shape batches can dispatch at, up to
        cap lanes. len() of this bounds compiled-program count."""
        from cometbft_tpu.ops import ed25519_kernel as EK

        cap = cap or self.max_lanes
        out: list[int] = []
        b = EK.MIN_BUCKET
        while b <= cap and b < EK._POW2_CAP:
            out.append(b)
            b *= 2
        m = EK._POW2_CAP
        while m <= cap:
            out.append(m)
            m += EK._POW2_CAP
        return out

    def _next_unit(self) -> int:
        with self._cond:
            self._unit_seq += 1
            return self._unit_seq

    # ------------------------------------------------------------- submit

    def submit(self, rows, klass: str = MEMPOOL,
               deadline: float | None = None) -> list[concurrent.futures.Future]:
        """Queue rows for the next batch; returns one Future[bool] per
        row. The work rides the next inline drain as filler, or the
        deadline worker flushes it within the class deadline. Raises
        SchedulerSaturated for mempool-class work when the queues are
        already full (backpressure — shed at admission, not at dispatch).
        """
        if klass not in CLASSES:
            raise ValueError(f"unknown verify class {klass!r}")
        if not rows:
            return []
        now = self._clock()
        if deadline is None:
            deadline = now + self.class_deadline[klass]
        grp = _Group(klass=klass, rows=list(rows), submitted_at=now,
                     unit=self._next_unit(), deadline=deadline,
                     futures=[concurrent.futures.Future() for _ in rows])
        trace.event("sched.submit", cat="sched", klass=klass,
                    rows=len(grp.rows))
        with self._cond:
            depth = self._depth[klass]
            if klass == MEMPOOL:
                # reject when this class is full OR when higher-priority
                # backlog already fills the next buckets without filler
                higher = (self._depth[CONSENSUS] + self._depth[SYNC]
                          + self._depth[LIGHT])
                if depth + len(rows) > self.queue_limit or higher >= self.queue_limit:
                    self.rejected += 1
                    raise SchedulerSaturated(
                        f"mempool verify queue at {depth} rows "
                        f"(limit {self.queue_limit}, higher-class backlog {higher})")
            elif depth + len(rows) > 4 * self.queue_limit:
                # consensus/sync never reject (liveness) but a runaway
                # producer must surface loudly, not OOM silently
                try:
                    from cometbft_tpu.libs import log as _log

                    _log.default().error(
                        "verify scheduler queue overflow",
                        klass=klass, depth=str(depth))
                except Exception:  # noqa: BLE001
                    pass
            self._queues[klass].append(grp)
            self._depth[klass] += len(grp.rows)
            self._ensure_worker_locked()
            self._publish_depth()
            self._cond.notify_all()
        return grp.futures

    # ------------------------------------------------------- inline drain

    def verify_now(self, rows, klass: str = CONSENSUS) -> np.ndarray:
        """Verify rows NOW: one inline device batch on the calling
        thread, coalescing queued filler up to the bucket. Returns the
        (N,) bool mask for the caller's rows."""
        return self.verify_many([rows], klass)[0]

    def verify_many(self, rowlists, klass: str = CONSENSUS) -> list[np.ndarray]:
        """verify_now for a window of groups (blocksync stages a window
        of commits; each keeps its own recheck budget) — one coalesced
        dispatch, one mask per group."""
        unit = self._next_unit()
        own = [
            _Group(klass=klass, rows=list(rows), submitted_at=self._clock(),
                   unit=unit)
            for rows in rowlists
        ]
        n_own = sum(len(g.rows) for g in own)
        if n_own == 0:
            for g in own:
                g.resolve(np.zeros(0, dtype=bool))
            return [g.mask for g in own]
        # root span: one inline drain == one batch lifecycle; a drain
        # slower than the latency budget keeps its full tree (slow-batch
        # capture ring)
        with trace.span("sched.verify", cat="sched", klass=klass,
                        rows=n_own, groups=len(own)) as sp:
            riders = self._take_riders(n_own)
            if riders:
                sp.set(rider_rows=sum(len(g.rows) for g in riders))
            self._dispatch(own + riders)
        return [g.mask for g in own]

    def flush(self) -> int:
        """Drain everything queued right now (tests, shutdown, bench).
        Returns the number of rows dispatched."""
        with self._cond:
            groups = [g for k in CLASSES for g in self._queues[k]]
            for k in CLASSES:
                self._queues[k].clear()
                self._depth[k] = 0
            self._publish_depth()
        if not groups:
            return 0
        self._dispatch(groups)
        return sum(len(g.rows) for g in groups)

    @staticmethod
    def _mesh(build: bool = False):
        """The active multi-chip verify mesh, or None (disabled, too few
        devices, not yet built, or the parallel plane failed to import).
        Only the dispatch path builds (build=True); telemetry and
        rider-budget math peek, so a health poll never registers
        per-chip supervisors. Never raises — the scheduler must dispatch
        with the mesh module broken."""
        try:
            from cometbft_tpu.parallel import mesh as _mesh_mod

            return (_mesh_mod.active() if build
                    else _mesh_mod.peek_active())
        except Exception:  # noqa: BLE001
            return None

    def _effective_max_lanes(self) -> int:
        """The lane budget one flush may coalesce: per-chip max_lanes
        times the LIVE mesh size — the scheduler fills per-chip lanes
        against the current topology, so an 8-chip mesh absorbs 8x the
        filler and a shrunken mesh stops over-coalescing into its
        survivors. Single-chip (mesh off) keeps the classic budget."""
        mesh = self._mesh()
        if mesh is None:
            return self.max_lanes
        from cometbft_tpu.ops import ed25519_kernel as EK

        return min(self.max_lanes * max(1, mesh.live_size_hint()),
                   1 << EK.MAX_BUCKET_LOG2)

    def _take_riders(self, n_own: int) -> list[_Group]:
        """Pop queued groups to fill the bucket the inline batch will
        dispatch at anyway. Starvation guard first: any group overdue
        past starvation_limit rides along regardless of class order."""
        with self._cond:
            queued = sum(self._depth.values())
            if queued == 0:
                return []
            target = self.bucket_lanes(
                min(n_own + queued, self._effective_max_lanes()))
            space = target - n_own
            out: list[_Group] = []
            now = self._clock()
            # overdue first (oldest first), then strict class priority
            overdue = sorted(
                (g for k in CLASSES for g in self._queues[k]
                 if now - g.submitted_at > self.starvation_limit),
                key=lambda g: g.submitted_at)
            seen = set(map(id, overdue))
            candidates = overdue + [
                g for k in CLASSES for g in self._queues[k]
                if id(g) not in seen
            ]
            for g in candidates:
                if len(g.rows) > space:
                    continue
                out.append(g)
                space -= len(g.rows)
            for g in out:
                self._queues[g.klass].remove(g)
                self._depth[g.klass] -= len(g.rows)
            self._publish_depth()
            return out

    # ----------------------------------------------------------- dispatch

    def _dispatch(self, groups: list[_Group]) -> None:
        """Form and run device batches for the groups (chunked at
        max_lanes, groups never split), resolve every mask/future. The
        scheduler's own chaos site fires here: an injected scheduler
        fault degrades to per-group fragmented dispatch — the pre-PR
        architecture — so verification survives scheduler failure."""
        if not groups:
            return
        try:
            from cometbft_tpu.libs import chaos

            chaos.fire("sched.flush")
        except Exception as exc:  # noqa: BLE001 - scheduler fault injected
            self.chaos_fallbacks += 1
            try:
                from cometbft_tpu.libs import log as _log

                _log.default().error(
                    "verify scheduler flush fault; dispatching fragmented",
                    err=str(exc))
            except Exception:  # noqa: BLE001
                pass
            for g in groups:
                try:
                    self._dispatch_core([g])
                except Exception:  # noqa: BLE001 - group's futures failed;
                    pass           # later groups must still dispatch
            return
        # chunk: groups are never split; a chunk holds up to the
        # effective lane budget (per-chip max_lanes x live mesh size)
        # unless a single group alone exceeds it (a 10k mega-commit
        # dispatches alone — the kernel's lane cap is far above it).
        # A failing chunk fails ITS futures (in _dispatch_core) and must
        # not strand the remaining chunks' futures — a hung future would
        # wedge a mempool admission await forever.
        lane_budget = self._effective_max_lanes()
        chunks: list[list[_Group]] = []
        chunk: list[_Group] = []
        chunk_rows = 0
        for g in groups:
            if chunk and chunk_rows + len(g.rows) > lane_budget:
                chunks.append(chunk)
                chunk, chunk_rows = [], 0
            chunk.append(g)
            chunk_rows += len(g.rows)
        if chunk:
            chunks.append(chunk)
        first_exc: Exception | None = None
        for c in chunks:
            try:
                self._dispatch_core(c)
            except Exception as exc:  # noqa: BLE001
                first_exc = first_exc or exc
        if first_exc is not None:
            raise first_exc

    def _dispatch_core(self, groups: list[_Group]) -> None:
        """One device batch: group rows by scheme, dispatch each scheme's
        sub-batch through the existing ladder (TPU kernels under the
        supervisor/breaker, else the registry CPU verifier), resolve all
        device thunks with ONE fetch, slice masks back per group."""
        n_rows = sum(len(g.rows) for g in groups)
        if trace.enabled():
            # queue attribution: each group's submit->dispatch wait (an
            # interval on the group, not a span on any one thread).
            # Inline-drain own groups contribute only their ~µs of
            # residence, so the queue share stays dominated by groups
            # that genuinely sat in the queue.
            t_flush = self._clock()
            for g in groups:
                wait = t_flush - g.submitted_at
                if wait > 0:
                    trace.account("queue", wait)
        lanes = self.bucket_lanes(n_rows)
        flush_sp = trace.span("sched.flush", cat="sched", rows=n_rows,
                              groups=len(groups), lanes=lanes,
                              classes=",".join(sorted(
                                  {g.klass for g in groups})))
        try:
            with flush_sp:
                masks = self._run_batch(groups)
        except Exception as exc:  # noqa: BLE001 - must not lose futures
            for g in groups:
                g.fail(exc)
            raise
        now = self._clock()
        # ---- stats (under the lock: worker and inline drains dispatch
        # concurrently) + metrics
        misses = 0
        with self._cond:
            self.batches += 1
            self.rows_total += n_rows
            self.lanes_total += lanes
            self._shapes.add(lanes)
            unit_rows: dict[int, int] = {}
            for g in groups:
                unit_rows[g.unit] = unit_rows.get(g.unit, 0) + len(g.rows)
                self._class_rows[g.klass] += len(g.rows)
            for nr in unit_rows.values():
                self.frag_lanes_total += self.bucket_lanes(nr)
            for g in groups:
                buf = self._lat[g.klass]
                buf.append(now - g.submitted_at)
                if len(buf) > 4096:
                    del buf[:2048]
                if g.deadline is not None and now > g.deadline + _MISS_SLACK:
                    misses += 1
                    self.deadline_miss_by_class[g.klass] += 1
            self.deadline_misses += misses
        m = self._metrics()
        if m is not None:
            try:
                m.batch_lanes.observe(lanes)
                m.fill_ratio.observe(n_rows / lanes)
                if misses:
                    m.flush_deadline_misses.inc(misses)
                for g in groups:
                    m.flush_latency.labels(g.klass).observe(
                        now - g.submitted_at)
            except Exception:  # noqa: BLE001
                pass
        for g, mask in zip(groups, masks):
            g.resolve(mask)

    def _run_batch(self, groups: list[_Group]) -> list[np.ndarray]:
        """The scheme-grouped verification core. Device thunks for every
        scheme resolve together (one device->host fetch); per-group row
        boundaries become the kernel's recheck groups so each producer
        keeps its own host-oracle recheck budget.

        Topology routing: on the tpu backend with an active multi-chip
        mesh (parallel/mesh.py), each scheme's sub-batch is sharded over
        the live mesh with class-aware placement — the batch's highest
        priority class decides (consensus pins to the least-loaded chip
        for latency; sync/mempool spread for throughput). A chip dying
        mid-flush re-shards inside the mesh; only an all-chips-dead mesh
        degrades to the single-chip ladder this method otherwise uses."""
        from cometbft_tpu.crypto import batch as crypto_batch
        from cometbft_tpu.libs.prefixrows import PrefixedMsg
        from cometbft_tpu.ops import ed25519_kernel

        # scheme -> (pubs, msgs, sigs, bounds, [(group_idx, row_idx)])
        per: dict[str, dict] = {}
        # batch preparation is all "stage": backend selection plus the
        # scheme grouping/bounds pass (the span starts before
        # resolve_backend so flush glue stays inside the coverage model)
        with trace.span("sched.group_rows", cat="stage",
                        rows=sum(len(g.rows) for g in groups)):
            backend = crypto_batch.resolve_backend()
            mesh = self._mesh(build=True) if backend == "tpu" else None
            klasses = {g.klass for g in groups}
            # the batch's placement class: its highest-priority member
            batch_klass = next(k for k in CLASSES if k in klasses)
            for gi, g in enumerate(groups):
                for ri, (pub, msg, sig) in enumerate(g.rows):
                    scheme = pub.type_()
                    d = per.setdefault(scheme, {
                        "pubs": [], "msgs": [], "sigs": [], "where": [],
                        "bounds": [], "open": None,
                    })
                    if d["open"] != gi:
                        if d["open"] is not None:
                            d["bounds"].append((d["_b0"], len(d["sigs"])))
                        d["open"] = gi
                        d["_b0"] = len(d["sigs"])
                    d["pubs"].append(pub)
                    # shared-prefix rows stay FACTORED through the
                    # scheduler (the kernel staging fast path broadcasts
                    # each run's prefix once — libs/prefixrows.py)
                    d["msgs"].append(msg if isinstance(msg, PrefixedMsg)
                                     else bytes(msg))
                    d["sigs"].append(bytes(sig))
                    d["where"].append((gi, ri))
            for d in per.values():
                if d["open"] is not None:
                    d["bounds"].append((d["_b0"], len(d["sigs"])))
        thunks: list = []
        thunk_schemes: list[str] = []
        host_masks: dict[str, np.ndarray] = {}
        # the whole dispatch-and-resolve phase sits inside one counted
        # span so per-scheme loop glue, thunk construction, and the
        # resolve call are covered flush time; nested counted children
        # (host_verify here, the kernels' stage/transfer/fetch spans on
        # the device path) subtract from its self time, leaving only the
        # true glue attributed as compute
        mesh_thunks: list[tuple[str, object]] = []
        with trace.span("sched.dispatch", cat="compute",
                        schemes=len(per)):
            for scheme, d in per.items():
                if mesh is not None and scheme in (
                        "ed25519", "sr25519", "bls12381"):
                    # mesh shards dispatch eagerly inside verify_async;
                    # both schemes' shards are in flight before any join
                    mesh_thunks.append((scheme, mesh.verify_async(
                        scheme, [p.bytes_() for p in d["pubs"]],
                        d["msgs"], d["sigs"], klass=batch_klass,
                        recheck_groups=d["bounds"])))
                elif backend == "tpu" and scheme == "ed25519":
                    thunks.append(ed25519_kernel.verify_batch_async(
                        [p.bytes_() for p in d["pubs"]], d["msgs"],
                        d["sigs"], recheck_groups=d["bounds"]))
                    thunk_schemes.append(scheme)
                elif backend == "tpu" and scheme == "sr25519":
                    from cometbft_tpu.ops import sr25519_kernel

                    thunks.append(sr25519_kernel.verify_batch_async(
                        [p.bytes_() for p in d["pubs"]], d["msgs"],
                        d["sigs"]))
                    thunk_schemes.append(scheme)
                elif backend == "tpu" and scheme == "bls12381":
                    from cometbft_tpu.ops import bls_kernel

                    thunks.append(bls_kernel.verify_batch_async(
                        [p.bytes_() for p in d["pubs"]], d["msgs"],
                        d["sigs"], recheck_groups=d["bounds"]))
                    thunk_schemes.append(scheme)
                else:
                    # sig_rows marks THE counting site for these rows
                    # (rolling attribution row totals; every other span
                    # annotates informational `rows` only)
                    with trace.span("sched.host_verify", cat="compute",
                                    scheme=scheme,
                                    sig_rows=len(d["sigs"])):
                        host_masks[scheme] = self._host_mask(scheme, d)
            if thunks:
                resolved = ed25519_kernel.resolve_batches(thunks)
                for scheme, mask in zip(thunk_schemes, resolved):
                    host_masks[scheme] = np.asarray(mask, dtype=bool)
            # every mesh thunk must be JOINED even if an earlier one
            # raises — a skipped join would strand its shards' inflight
            # accounting and skew placement for the process lifetime
            mesh_err: Exception | None = None
            for scheme, thunk in mesh_thunks:
                try:
                    host_masks[scheme] = np.asarray(thunk(), dtype=bool)
                except Exception as exc:  # noqa: BLE001
                    mesh_err = mesh_err or exc
            if mesh_err is not None:
                raise mesh_err
        with trace.span("sched.slice_masks", cat="resolve"):
            out = [np.zeros(len(g.rows), dtype=bool) for g in groups]
            for scheme, d in per.items():
                mask = host_masks[scheme]
                for (gi, ri), ok in zip(d["where"], mask):
                    out[gi][ri] = bool(ok)
        return out

    @staticmethod
    def _host_mask(scheme: str, d: dict) -> np.ndarray:
        """CPU rung for one scheme's rows: the registry batch verifier
        when the scheme has one, else a serial host loop (an unbatchable
        key type — secp256k1 — must still verify, not crash the batch).
        A structurally-bad row fails alone instead of raising."""
        from cometbft_tpu.crypto import batch as crypto_batch
        from cometbft_tpu.libs.prefixrows import as_bytes

        n = len(d["sigs"])
        backends = crypto_batch._REGISTRY.get(scheme)
        if backends is not None:
            bv = backends["cpu"]()
            staged: list[int] = []
            mask = np.zeros(n, dtype=bool)
            for i in range(n):
                try:
                    bv.add(d["pubs"][i], as_bytes(d["msgs"][i]),
                           d["sigs"][i])
                    staged.append(i)
                except Exception:  # noqa: BLE001 - structural reject
                    pass
            if staged:
                _, sub = bv.verify()
                for i, ok in zip(staged, sub):
                    mask[i] = bool(ok)
            return mask
        mask = np.zeros(n, dtype=bool)
        for i in range(n):
            try:
                mask[i] = bool(d["pubs"][i].verify_signature(
                    as_bytes(d["msgs"][i]), d["sigs"][i]))
            except Exception:  # noqa: BLE001
                mask[i] = False
        return mask

    # ------------------------------------------------------ deadline worker

    def _ensure_worker_locked(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._stop = False
        self._worker = threading.Thread(
            target=self._worker_loop, name="verify-sched", daemon=True)
        self._worker.start()

    def _worker_loop(self) -> None:
        """Flush queued groups when their deadlines come due and no
        inline drain picked them up as filler first."""
        while True:
            with self._cond:
                if self._stop:
                    return
                deadlines = [
                    g.deadline for k in CLASSES for g in self._queues[k]
                    if g.deadline is not None
                ]
                now = self._clock()
                if not deadlines:
                    self._cond.wait(timeout=0.25)
                    continue
                dl = min(deadlines)
                if dl > now:
                    self._cond.wait(timeout=min(dl - now, 0.25))
                    continue
                groups = [g for k in CLASSES for g in self._queues[k]]
                for k in CLASSES:
                    self._queues[k].clear()
                    self._depth[k] = 0
                self._publish_depth()
            if groups:
                self.worker_flushes += 1
                try:
                    self._dispatch(groups)
                except Exception:  # noqa: BLE001 - futures already failed
                    pass

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=2.0)
            self._worker = None

    # -------------------------------------------------------------- warmup

    def warmup(self, max_lanes: int | None = None) -> list[int]:
        """Pre-trace the bucket ladder on the device so the first real
        consensus flush doesn't pay a cold compile mid-round. No-op off
        the TPU backend (CPU programs compile in milliseconds and tests
        pin the CPU backend). Returns the lane counts traced."""
        from cometbft_tpu.crypto import batch as crypto_batch

        if crypto_batch.resolve_backend() != "tpu":
            return []
        from cometbft_tpu.ops import ed25519_kernel as EK
        from cometbft_tpu.ops import limbs as _limbs

        traced: list[int] = []
        for b in self.bucket_ladder(max_lanes or 2048):
            # double-buffer pair per rung: the first real flushes must
            # not allocate staging blocks on the hot path
            _limbs.POOL.warm(b)
            try:
                from cometbft_tpu.ops import challenge as _challenge

                if _challenge.enabled():
                    # worst-case flat wire block for the device-challenge
                    # path (smaller vars warm organically on first use)
                    _limbs.POOL.warm_flat(
                        _challenge.block_words(b, _challenge.MAX_VAR))
            except Exception:  # noqa: BLE001 - warmup is best-effort
                pass
            # identity-point rows: pub = the identity encoding, s = 0 —
            # structurally valid, decompress trivially, verify cheap
            pubs = [EK._ID_ENC32] * b
            msgs = [b"sched-warmup"] * b
            sigs = [EK._ID_ENC32 + b"\x00" * 32] * b
            try:
                EK.resolve_batches([EK.verify_batch_async(pubs, msgs, sigs)])
                traced.append(b)
            except Exception:  # noqa: BLE001 - device trouble: supervisor owns it
                break
        return traced

    # ------------------------------------------------------------ snapshot

    def latency_quantiles(self) -> dict:
        """Per-class submit->dispatch latency p50/p99 in ms from the
        bounded sample buffers (None for classes with no traffic)."""
        out = {}
        for k in CLASSES:
            buf = sorted(self._lat[k])
            if not buf:
                out[k] = None
                continue
            out[k] = {
                "n": len(buf),
                "p50_ms": round(buf[len(buf) // 2] * 1e3, 3),
                "p99_ms": round(buf[min(len(buf) - 1,
                                        int(len(buf) * 0.99))] * 1e3, 3),
            }
        return out

    def health(self) -> dict:
        """The crypto_health `verify_sched` section (rpc/core.py) and the
        assertion surface for tests/bench."""
        with self._cond:
            depth = dict(self._depth)
        fill = self.rows_total / self.lanes_total if self.lanes_total else None
        frag = (self.rows_total / self.frag_lanes_total
                if self.frag_lanes_total else None)
        return {
            "batches": self.batches,
            "rows_total": self.rows_total,
            "lanes_total": self.lanes_total,
            "fill_ratio_mean": round(fill, 4) if fill is not None else None,
            "fragmented_fill_ratio_mean":
                round(frag, 4) if frag is not None else None,
            "dispatch_shapes": sorted(self._shapes),
            "bucket_ladder_len": len(self.bucket_ladder()),
            "queue_depth": depth,
            "class_rows": dict(self._class_rows),
            "deadline_misses": self.deadline_misses,
            "deadline_miss_by_class": dict(self.deadline_miss_by_class),
            "rejected": self.rejected,
            "chaos_fallbacks": self.chaos_fallbacks,
            "worker_flushes": self.worker_flushes,
            "worker_alive": bool(self._worker and self._worker.is_alive()),
            "max_lanes": self.max_lanes,
            "effective_max_lanes": self._effective_max_lanes(),
            "mesh": self._mesh_view(),
            "deadlines": dict(self.class_deadline),
            "link": self._link_view(),
        }

    def _mesh_view(self) -> dict:
        """The scheduler's live view of the multi-chip topology it fills
        lanes against (never raises — telemetry)."""
        mesh = self._mesh()
        if mesh is None:
            return {"active": False}
        try:
            return {
                "active": True,
                "devices": len(mesh.chips),
                "live": mesh.live_size(),
                "placement": mesh.placement,
            }
        except Exception:  # noqa: BLE001
            return {"active": True}

    @staticmethod
    def planning_bytes_per_sig() -> float:
        """The live wire cost of one signature used for flush planning:
        the reduced-send accounting's measured rate (ops/residency.py —
        the number PR 6's trace attribution also records; with device
        challenge derivation on, the measured steady state is ~66-82
        B/sig because the k plane never crosses the wire), falling back
        to the rolling attribution model, then to the pre-reduced-send
        96 B/sig constant only when the process has not sent a single
        batch yet."""
        try:
            from cometbft_tpu.ops import residency

            measured = residency.measured_bytes_per_sig()
            if measured:
                return float(measured)
        except Exception:  # noqa: BLE001 - planning must never raise
            pass
        try:
            from cometbft_tpu.libs import trace as _trace

            attr = _trace.attribution()
            bps = attr.get("bytes_per_sig_tx")
            if bps:
                return float(bps)
        except Exception:  # noqa: BLE001
            pass
        return 96.0

    def _link_view(self) -> dict:
        """The scheduler's live view of the host<->device link
        (libs/linkmodel.py, fed by the kernels' measured transfers):
        estimated bandwidth/RTT plus the predicted wall cost of a
        full-lane flush at the MEASURED bytes-per-sig (reduced-send
        accounting; the hardcoded 96 B/sig planning constant is gone —
        it is only the cold-start fallback before any batch has been
        sent). Never raises (telemetry)."""
        try:
            from cometbft_tpu.libs import linkmodel

            tun = linkmodel.tunnel()
            out = tun.snapshot()
            bps = self.planning_bytes_per_sig()
            out["planning_bytes_per_sig"] = round(bps, 2)
            # current wire cost of one maximally-coalesced flush
            est = tun.transfer_seconds(int(bps * self.max_lanes))
            out["full_flush_wire_ms_at_measured_bytes_per_sig"] = (
                round(est * 1e3, 2) if est is not None else None)
            return out
        except Exception:  # noqa: BLE001
            return {}
