"""Global verify scheduler — continuous batching of all signature work.

Public surface: the process-global VerifyScheduler singleton (get()),
the priority-class constants and the ambient-class context manager
(work_class), plus configure()/enabled()/reset() for node boot and tests.
See cometbft_tpu/sched/scheduler.py for the design.
"""

from __future__ import annotations

import threading

from cometbft_tpu.sched.scheduler import (  # noqa: F401 - public re-exports
    CLASSES,
    CONSENSUS,
    LIGHT,
    MEMPOOL,
    SYNC,
    SchedulerSaturated,
    VerifyScheduler,
    current_class,
    work_class,
)

_lock = threading.Lock()
_sched: VerifyScheduler | None = None
_enabled = True

# constructor kwargs applied at (re)creation — configure() records them so
# a get() after reset() rebuilds with the node's knobs, not the defaults
_kwargs: dict = {}


def enabled() -> bool:
    """Is scheduler routing on? When off, crypto/batch falls back to the
    pre-scheduler fragmented dispatch (each producer its own batch)."""
    return _enabled


def get() -> VerifyScheduler:
    global _sched
    if _sched is None:
        with _lock:
            if _sched is None:
                _sched = VerifyScheduler(**_kwargs)
    return _sched


def configure(enabled: bool | None = None, **kwargs) -> None:
    """Apply config.crypto scheduler knobs (node boot; tests poke
    directly). Unknown knobs raise. Live instance updated in place so a
    reconfig doesn't orphan queued work."""
    global _enabled
    allowed = {"max_lanes", "sync_deadline", "light_deadline",
               "mempool_deadline", "queue_limit", "starvation_limit"}
    bad = set(kwargs) - allowed
    if bad:
        raise ValueError(f"unknown scheduler knob(s) {sorted(bad)}")
    with _lock:
        if enabled is not None:
            _enabled = enabled
        _kwargs.update(kwargs)
        if _sched is not None:
            if "max_lanes" in kwargs:
                _sched.max_lanes = kwargs["max_lanes"]
            if "sync_deadline" in kwargs:
                _sched.class_deadline[SYNC] = kwargs["sync_deadline"]
            if "light_deadline" in kwargs:
                _sched.class_deadline[LIGHT] = kwargs["light_deadline"]
            if "mempool_deadline" in kwargs:
                _sched.class_deadline[MEMPOOL] = kwargs["mempool_deadline"]
            if "queue_limit" in kwargs:
                _sched.queue_limit = kwargs["queue_limit"]
            if "starvation_limit" in kwargs:
                _sched.starvation_limit = kwargs["starvation_limit"]


def reset() -> None:
    """Stop the worker and forget all state (tests; fresh process
    semantics). Queued futures are failed, not leaked."""
    global _sched
    with _lock:
        sched, _sched = _sched, None
    if sched is not None:
        try:
            sched.flush()
        except Exception:  # noqa: BLE001 - draining is best-effort
            pass
        sched.stop()


def health_snapshot() -> dict:
    """The crypto_health `verify_sched` section. Never creates the
    singleton implicitly beyond what get() would."""
    snap = get().health()
    snap["enabled"] = _enabled
    return snap
