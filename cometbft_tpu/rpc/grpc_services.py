"""gRPC RPC services: version / block / block-results / pruning.

Reference: rpc/grpc/server/services/{versionservice,blockservice,
blockresultservice,pruningservice} — a gRPC surface beside the JSON-RPC
server, with the pruning (data-companion) service on a separate
PRIVILEGED listener (config.go:520-543 GRPCConfig/GRPCPrivilegedConfig).

Transport follows abci/grpc.py: unary methods on grpc's generic-handler
API. Every service is served TWICE on the same listener — on the
reference's proto paths (tendermint.services.{version,block,block_results,
pruning}.v1.*, raw protobuf bodies per the .proto shapes, so the
data-companion ecosystem's generated stubs connect unmodified) and on the
framework-native JSON paths below. GetLatestHeight is a server stream, as
in the reference (blockservice/service.go:98): it yields a height whenever
the store head advances.

Framework-native service names (JSON bodies):
  cometbft_tpu.rpc.VersionService / GetVersion
  cometbft_tpu.rpc.BlockService   / GetByHeight, GetLatest,
                                    GetLatestHeight (stream)
  cometbft_tpu.rpc.BlockResultsService / GetBlockResults
  cometbft_tpu.rpc.PruningService (privileged) /
      SetBlockRetainHeight, GetBlockRetainHeight,
      SetBlockResultsRetainHeight, GetBlockResultsRetainHeight,
      SetTxIndexerRetainHeight, GetTxIndexerRetainHeight,
      SetBlockIndexerRetainHeight, GetBlockIndexerRetainHeight
"""

from __future__ import annotations

import asyncio
import json
import time
from concurrent import futures

import grpc

from cometbft_tpu import version as ver
from cometbft_tpu.state import pruner as pruner_mod


def _ident(b: bytes) -> bytes:
    return b


# long-lived streams each hold one thread-pool worker; bound them so idle
# subscribers can never starve the unary RPCs sharing the executor
import threading

_MAX_STREAMS = 4
_stream_slots = threading.BoundedSemaphore(_MAX_STREAMS)


class _JsonServicer:
    """Maps /<service>/<Method> onto self.<snake_case Method>(dict)->dict.
    Only methods listed in rpc_methods / stream_methods are reachable —
    never arbitrary attributes (untrusted input picks the method name).

    When proto_service_name is set, the same methods are ALSO served on the
    reference's service path (tendermint.services.*.v1.*) with raw protobuf
    request/response bodies via proto_codecs — the data-companion ecosystem
    connects with its generated stubs, no configuration."""

    service_name = ""
    proto_service_name = ""
    rpc_methods: frozenset[str] = frozenset()
    stream_methods: frozenset[str] = frozenset()
    # Method -> (decode_request(bytes) -> dict,
    #            encode_response(self, dict) -> bytes)
    proto_codecs: dict = {}
    # Method -> alternate handler attr for the proto path (when the JSON
    # handler's dict would be built only to be thrown away)
    proto_method_overrides: dict = {}

    def service(self, handler_call_details):
        path = handler_call_details.method
        service, _, method = path.lstrip("/").partition("/")
        if service == self.proto_service_name and method in self.proto_codecs:
            return self._proto_handler(method)
        if service != self.service_name:
            return None
        snake = "".join(
            ("_" + c.lower()) if c.isupper() else c for c in method
        ).lstrip("_")
        if method in self.rpc_methods:
            fn = getattr(self, snake)

            def unary(request: bytes, context) -> bytes:
                try:
                    out = fn(json.loads(request or b"{}"))
                except KeyError as e:
                    context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                except ValueError as e:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return json.dumps(out).encode()

            return grpc.unary_unary_rpc_method_handler(
                unary, request_deserializer=_ident,
                response_serializer=_ident)
        if method in self.stream_methods:
            sfn = getattr(self, "stream_" + snake)

            def streaming(request: bytes, context):
                if not _stream_slots.acquire(blocking=False):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"too many concurrent streams (max {_MAX_STREAMS})")
                try:
                    for out in sfn(json.loads(request or b"{}"), context):
                        yield json.dumps(out).encode()
                finally:
                    _stream_slots.release()

            return grpc.unary_stream_rpc_method_handler(
                streaming, request_deserializer=_ident,
                response_serializer=_ident)
        return None

    def _proto_handler(self, method: str):
        dec, enc = self.proto_codecs[method]
        snake = "".join(
            ("_" + c.lower()) if c.isupper() else c for c in method
        ).lstrip("_")
        if method in self.stream_methods:
            sfn = getattr(self, "stream_" + snake)

            def p_streaming(request: bytes, context):
                if not _stream_slots.acquire(blocking=False):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"too many concurrent streams (max {_MAX_STREAMS})")
                try:
                    for out in sfn(dec(request), context):
                        yield enc(self, out)
                finally:
                    _stream_slots.release()

            return grpc.unary_stream_rpc_method_handler(
                p_streaming, request_deserializer=_ident,
                response_serializer=_ident)
        fn = getattr(self, self.proto_method_overrides.get(method, snake))

        def p_unary(request: bytes, context) -> bytes:
            try:
                # enc may re-read stores (a concurrent pruner can delete
                # between loads) — its KeyError must map to NOT_FOUND too
                return enc(self, fn(dec(request)))
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

        return grpc.unary_unary_rpc_method_handler(
            p_unary, request_deserializer=_ident, response_serializer=_ident)


# --- proto codec helpers (tendermint/services/*/v1/*.proto shapes) ---------

from cometbft_tpu.utils import protobuf as pb  # noqa: E402


def _dec_empty(_data: bytes) -> dict:
    return {}


def _dec_height_i64(data: bytes) -> dict:
    r = pb.Reader(data)
    h = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            h = r.read_varint_i64()
        else:
            r.skip(w)
    return {"height": str(h)}


def _dec_height_u64(data: bytes) -> dict:
    r = pb.Reader(data)
    h = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            h = r.read_uvarint()
        else:
            r.skip(w)
    return {"height": str(h)}


def _enc_empty(_self, _out: dict) -> bytes:
    return b""


def _enc_height_i64(_self, out: dict) -> bytes:
    return pb.Writer().varint_i64(1, int(out["height"])).output()


def _enc_version(_self, out: dict) -> bytes:
    w = pb.Writer()
    w.string(1, str(out["node"]))
    w.string(2, str(out["abci"]))
    w.uvarint(3, int(out["p2p"]))
    w.uvarint(4, int(out["block"]))
    return w.output()


class VersionService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.VersionService"
    proto_service_name = "tendermint.services.version.v1.VersionService"
    rpc_methods = frozenset({"GetVersion"})
    proto_codecs = {"GetVersion": (_dec_empty, _enc_version)}

    def get_version(self, _req: dict) -> dict:
        return {
            "node": ver.CMTSemVer,
            "abci": ver.ABCIVersion,
            "p2p": ver.P2PProtocol,
            "block": ver.BlockProtocol,
        }


def _enc_block_resp(_self, out: dict) -> bytes:
    """tendermint.services.block.v1 GetByHeightResponse/GetLatestResponse:
    block_id=1 (tendermint.types.BlockID), block=2 (tendermint.types.Block
    — the framework's Block.to_proto is that wire layout)."""
    bid = pb.Writer()
    bid.bytes(1, bytes.fromhex(out["block_id"]["hash"]))
    psh = pb.Writer()
    psh.uvarint(1, out["block_id"]["part_set_header"]["total"])
    psh.bytes(2, bytes.fromhex(out["block_id"]["part_set_header"]["hash"]))
    bid.message(2, psh.output(), always=True)
    w = pb.Writer()
    w.message(1, bid.output(), always=True)
    w.message(2, bytes.fromhex(out["block_proto"]), always=True)
    return w.output()


class BlockService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.BlockService"
    proto_service_name = "tendermint.services.block.v1.BlockService"
    rpc_methods = frozenset({"GetByHeight", "GetLatest"})
    stream_methods = frozenset({"GetLatestHeight"})
    proto_codecs = {
        "GetByHeight": (_dec_height_i64, _enc_block_resp),
        "GetLatest": (_dec_empty, _enc_block_resp),
        "GetLatestHeight": (_dec_empty, _enc_height_i64),
    }

    def __init__(self, block_store):
        self.block_store = block_store

    def _block_payload(self, height: int) -> dict:
        meta = self.block_store.load_block_meta(height)
        block = self.block_store.load_block(height)
        if meta is None or block is None:
            raise KeyError(f"block at height {height} not found")
        return {
            "block_id": {
                "hash": meta.block_id.hash.hex(),
                "part_set_header": {
                    "total": meta.block_id.part_set_header.total,
                    "hash": meta.block_id.part_set_header.hash.hex(),
                },
            },
            "height": str(height),
            "block_proto": block.to_proto().hex(),
        }

    def get_by_height(self, req: dict) -> dict:
        if "height" not in req:
            raise ValueError("missing height")  # INVALID_ARGUMENT, not 404
        h = int(req["height"])
        # block.proto: "If set to 0, the latest height will be returned"
        return self._block_payload(h if h else self.block_store.height())

    def get_latest(self, _req: dict) -> dict:
        return self._block_payload(self.block_store.height())

    def stream_get_latest_height(self, _req: dict, context):
        """blockservice/service.go:98 GetLatestHeight: push the head
        height whenever it advances, until the client goes away."""
        last = 0
        # polling (0.2 s) keeps this free of event-bus plumbing into the
        # sync worker thread; 5 store reads/s per subscriber, stream count
        # capped by _MAX_STREAMS
        while context.is_active():
            h = self.block_store.height()
            if h > last:
                last = h
                yield {"height": str(h)}
            time.sleep(0.2)


def _enc_block_results(self_, out: dict) -> bytes:
    """tendermint.services.block_results.v1 GetBlockResultsResponse —
    encoded from the RAW stored FinalizeBlock response via the ABCI proto
    codec (the JSON dict form base64s its bytes)."""
    from cometbft_tpu.abci import proto_codec as apc

    height = int(out["height"])
    resp = self_.state_store.load_finalize_block_response(height)
    if resp is None:  # pruned between handler and encoder -> NOT_FOUND
        raise KeyError(f"block results at height {height} not found")
    w = pb.Writer()
    w.varint_i64(1, height)
    for t in resp.tx_results:
        tw = pb.Writer()
        apc._enc_tx_result_fields(tw, t)
        w.message(2, tw.output(), always=True)
    for e in resp.events:
        w.message(3, apc._enc_event(e), always=True)
    for u in resp.validator_updates:
        w.message(4, apc._enc_validator_update(u), always=True)
    w.message(5, apc._enc_consensus_params(resp.consensus_param_updates))
    w.bytes(6, resp.app_hash)
    return w.output()


class BlockResultsService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.BlockResultsService"
    proto_service_name = (
        "tendermint.services.block_results.v1.BlockResultsService")
    rpc_methods = frozenset({"GetBlockResults"})
    proto_codecs = {"GetBlockResults": (_dec_height_i64, _enc_block_results)}
    # the proto encoder reads the raw stored object itself; skip the JSON
    # handler's base64 conversion work on this path
    proto_method_overrides = {"GetBlockResults": "resolve_results_height"}

    def __init__(self, state_store, block_store):
        self.state_store = state_store
        self.block_store = block_store

    def resolve_results_height(self, req: dict) -> dict:
        height = int(req.get("height") or 0) or self.block_store.height()
        if self.state_store.load_finalize_block_response(height) is None:
            raise KeyError(f"block results at height {height} not found")
        return {"height": str(height)}

    def get_block_results(self, req: dict) -> dict:
        from cometbft_tpu.abci import codec as abci_codec

        height = int(req.get("height") or 0) or self.block_store.height()
        resp = self.state_store.load_finalize_block_response(height)
        if resp is None:
            raise KeyError(f"block results at height {height} not found")
        return {
            "height": str(height),
            "txs_results": [abci_codec._to_jsonable(r) for r in resp.tx_results],
            "finalize_block_events": [
                abci_codec._to_jsonable(e) for e in resp.events],
            "app_hash": resp.app_hash.hex(),
        }


def _enc_block_retain(_self, out: dict) -> bytes:
    w = pb.Writer()
    w.uvarint(1, int(out["app_retain_height"]))
    w.uvarint(2, int(out["pruning_service_retain_height"]))
    return w.output()


def _enc_service_retain(_self, out: dict) -> bytes:
    return pb.Writer().uvarint(
        1, int(out["pruning_service_retain_height"])).output()


def _enc_height_u64(_self, out: dict) -> bytes:
    return pb.Writer().uvarint(1, int(out["height"])).output()


class PruningService(_JsonServicer):
    """The data-companion control plane (pruningservice/service.go):
    retain heights set here gate what the background pruner may delete."""

    service_name = "cometbft_tpu.rpc.PruningService"
    proto_service_name = "tendermint.services.pruning.v1.PruningService"
    rpc_methods = frozenset({
        "SetBlockRetainHeight", "GetBlockRetainHeight",
        "SetBlockResultsRetainHeight", "GetBlockResultsRetainHeight",
        "SetTxIndexerRetainHeight", "GetTxIndexerRetainHeight",
        "SetBlockIndexerRetainHeight", "GetBlockIndexerRetainHeight",
    })
    proto_codecs = {
        "SetBlockRetainHeight": (_dec_height_u64, _enc_empty),
        "GetBlockRetainHeight": (_dec_empty, _enc_block_retain),
        "SetBlockResultsRetainHeight": (_dec_height_u64, _enc_empty),
        "GetBlockResultsRetainHeight": (_dec_empty, _enc_service_retain),
        "SetTxIndexerRetainHeight": (_dec_height_u64, _enc_empty),
        "GetTxIndexerRetainHeight": (_dec_empty, _enc_height_u64),
        "SetBlockIndexerRetainHeight": (_dec_height_u64, _enc_empty),
        "GetBlockIndexerRetainHeight": (_dec_empty, _enc_height_u64),
    }

    def __init__(self, pruner):
        self.pruner = pruner

    def set_block_retain_height(self, req: dict) -> dict:
        self.pruner.set_companion_block_retain_height(int(req["height"]))
        return {}

    def get_block_retain_height(self, _req: dict) -> dict:
        return {
            "app_retain_height": str(
                self.pruner.state_store.load_retain_height(
                    pruner_mod.APP_RETAIN)),
            "pruning_service_retain_height": str(
                self.pruner.state_store.load_retain_height(
                    pruner_mod.COMPANION_RETAIN)),
        }

    def set_block_results_retain_height(self, req: dict) -> dict:
        self.pruner.set_abci_res_retain_height(int(req["height"]))
        return {}

    def get_block_results_retain_height(self, _req: dict) -> dict:
        return {"pruning_service_retain_height": str(
            self.pruner.get_abci_res_retain_height())}

    def set_tx_indexer_retain_height(self, req: dict) -> dict:
        self.pruner.set_tx_indexer_retain_height(int(req["height"]))
        return {}

    def get_tx_indexer_retain_height(self, _req: dict) -> dict:
        return {"height": str(self.pruner.get_tx_indexer_retain_height())}

    def set_block_indexer_retain_height(self, req: dict) -> dict:
        self.pruner.set_block_indexer_retain_height(int(req["height"]))
        return {}

    def get_block_indexer_retain_height(self, _req: dict) -> dict:
        return {"height": str(self.pruner.get_block_indexer_retain_height())}


class _MultiHandler(grpc.GenericRpcHandler):
    def __init__(self, servicers):
        self.servicers = servicers

    def service(self, handler_call_details):
        for s in self.servicers:
            h = s.service(handler_call_details)
            if h is not None:
                return h
        return None


def serve(servicers, addr: str) -> tuple[grpc.Server, str]:
    """Start a gRPC server hosting the servicers; returns (server,
    'host:bound_port')."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_MultiHandler(servicers),))
    host = addr.removeprefix("tcp://")
    port = server.add_insecure_port(host)
    if port == 0:
        raise RuntimeError(f"gRPC bind failed on {addr!r}")
    server.start()
    bound = f"{host.rsplit(':', 1)[0]}:{port}"
    return server, bound


# ----------------------------------------------------------------- client


class GRPCServicesClient:
    """Minimal client for the JSON-framed services (tests, operator
    tooling, the data companion)."""

    def __init__(self, addr: str):
        self.channel = grpc.aio.insecure_channel(addr.removeprefix("tcp://"))

    async def call(self, service: str, method: str, req: dict | None = None) -> dict:
        rpc = self.channel.unary_unary(
            f"/cometbft_tpu.rpc.{service}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        out = await rpc(json.dumps(req or {}).encode())
        return json.loads(out)

    async def stream(self, service: str, method: str, req: dict | None = None):
        rpc = self.channel.unary_stream(
            f"/cometbft_tpu.rpc.{service}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        async for out in rpc(json.dumps(req or {}).encode()):
            yield json.loads(out)

    async def close(self) -> None:
        await self.channel.close()


async def wait_closed(server: grpc.Server, grace: float = 1.0) -> None:
    """Stop the server and wait for the drain, so a restart can rebind."""
    await asyncio.to_thread(server.stop(grace=grace).wait)
