"""gRPC RPC services: version / block / block-results / pruning.

Reference: rpc/grpc/server/services/{versionservice,blockservice,
blockresultservice,pruningservice} — a gRPC surface beside the JSON-RPC
server, with the pruning (data-companion) service on a separate
PRIVILEGED listener (config.go:520-543 GRPCConfig/GRPCPrivilegedConfig).

Transport follows abci/grpc.py: unary methods on grpc's generic-handler
API with the framework's JSON encoding (no generated stubs; a documented
delta from the reference's proto wire). GetLatestHeight is a server
stream, as in the reference (blockservice/service.go:98): it yields a
height whenever the store head advances.

Service names:
  cometbft_tpu.rpc.VersionService / GetVersion
  cometbft_tpu.rpc.BlockService   / GetByHeight, GetLatest,
                                    GetLatestHeight (stream)
  cometbft_tpu.rpc.BlockResultsService / GetBlockResults
  cometbft_tpu.rpc.PruningService (privileged) /
      SetBlockRetainHeight, GetBlockRetainHeight,
      SetBlockResultsRetainHeight, GetBlockResultsRetainHeight,
      SetTxIndexerRetainHeight, GetTxIndexerRetainHeight,
      SetBlockIndexerRetainHeight, GetBlockIndexerRetainHeight
"""

from __future__ import annotations

import asyncio
import json
import time
from concurrent import futures

import grpc

from cometbft_tpu import version as ver
from cometbft_tpu.state import pruner as pruner_mod


def _ident(b: bytes) -> bytes:
    return b


# long-lived streams each hold one thread-pool worker; bound them so idle
# subscribers can never starve the unary RPCs sharing the executor
import threading

_MAX_STREAMS = 4
_stream_slots = threading.BoundedSemaphore(_MAX_STREAMS)


class _JsonServicer:
    """Maps /<service>/<Method> onto self.<snake_case Method>(dict)->dict.
    Only methods listed in rpc_methods / stream_methods are reachable —
    never arbitrary attributes (untrusted input picks the method name)."""

    service_name = ""
    rpc_methods: frozenset[str] = frozenset()
    stream_methods: frozenset[str] = frozenset()

    def service(self, handler_call_details):
        path = handler_call_details.method
        service, _, method = path.lstrip("/").partition("/")
        if service != self.service_name:
            return None
        snake = "".join(
            ("_" + c.lower()) if c.isupper() else c for c in method
        ).lstrip("_")
        if method in self.rpc_methods:
            fn = getattr(self, snake)

            def unary(request: bytes, context) -> bytes:
                try:
                    out = fn(json.loads(request or b"{}"))
                except KeyError as e:
                    context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                except ValueError as e:
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return json.dumps(out).encode()

            return grpc.unary_unary_rpc_method_handler(
                unary, request_deserializer=_ident,
                response_serializer=_ident)
        if method in self.stream_methods:
            sfn = getattr(self, "stream_" + snake)

            def streaming(request: bytes, context):
                if not _stream_slots.acquire(blocking=False):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"too many concurrent streams (max {_MAX_STREAMS})")
                try:
                    for out in sfn(json.loads(request or b"{}"), context):
                        yield json.dumps(out).encode()
                finally:
                    _stream_slots.release()

            return grpc.unary_stream_rpc_method_handler(
                streaming, request_deserializer=_ident,
                response_serializer=_ident)
        return None


class VersionService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.VersionService"
    rpc_methods = frozenset({"GetVersion"})

    def get_version(self, _req: dict) -> dict:
        return {
            "node": ver.CMTSemVer,
            "abci": ver.ABCIVersion,
            "p2p": ver.P2PProtocol,
            "block": ver.BlockProtocol,
        }


class BlockService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.BlockService"
    rpc_methods = frozenset({"GetByHeight", "GetLatest"})
    stream_methods = frozenset({"GetLatestHeight"})

    def __init__(self, block_store):
        self.block_store = block_store

    def _block_payload(self, height: int) -> dict:
        meta = self.block_store.load_block_meta(height)
        block = self.block_store.load_block(height)
        if meta is None or block is None:
            raise KeyError(f"block at height {height} not found")
        return {
            "block_id": {
                "hash": meta.block_id.hash.hex(),
                "part_set_header": {
                    "total": meta.block_id.part_set_header.total,
                    "hash": meta.block_id.part_set_header.hash.hex(),
                },
            },
            "height": str(height),
            "block_proto": block.to_proto().hex(),
        }

    def get_by_height(self, req: dict) -> dict:
        if "height" not in req:
            raise ValueError("missing height")  # INVALID_ARGUMENT, not 404
        return self._block_payload(int(req["height"]))

    def get_latest(self, _req: dict) -> dict:
        return self._block_payload(self.block_store.height())

    def stream_get_latest_height(self, _req: dict, context):
        """blockservice/service.go:98 GetLatestHeight: push the head
        height whenever it advances, until the client goes away."""
        last = 0
        # polling (0.2 s) keeps this free of event-bus plumbing into the
        # sync worker thread; 5 store reads/s per subscriber, stream count
        # capped by _MAX_STREAMS
        while context.is_active():
            h = self.block_store.height()
            if h > last:
                last = h
                yield {"height": str(h)}
            time.sleep(0.2)


class BlockResultsService(_JsonServicer):
    service_name = "cometbft_tpu.rpc.BlockResultsService"
    rpc_methods = frozenset({"GetBlockResults"})

    def __init__(self, state_store, block_store):
        self.state_store = state_store
        self.block_store = block_store

    def get_block_results(self, req: dict) -> dict:
        from cometbft_tpu.abci import codec as abci_codec

        height = int(req.get("height") or self.block_store.height())
        resp = self.state_store.load_finalize_block_response(height)
        if resp is None:
            raise KeyError(f"block results at height {height} not found")
        return {
            "height": str(height),
            "txs_results": [abci_codec._to_jsonable(r) for r in resp.tx_results],
            "finalize_block_events": [
                abci_codec._to_jsonable(e) for e in resp.events],
            "app_hash": resp.app_hash.hex(),
        }


class PruningService(_JsonServicer):
    """The data-companion control plane (pruningservice/service.go):
    retain heights set here gate what the background pruner may delete."""

    service_name = "cometbft_tpu.rpc.PruningService"
    rpc_methods = frozenset({
        "SetBlockRetainHeight", "GetBlockRetainHeight",
        "SetBlockResultsRetainHeight", "GetBlockResultsRetainHeight",
        "SetTxIndexerRetainHeight", "GetTxIndexerRetainHeight",
        "SetBlockIndexerRetainHeight", "GetBlockIndexerRetainHeight",
    })

    def __init__(self, pruner):
        self.pruner = pruner

    def set_block_retain_height(self, req: dict) -> dict:
        self.pruner.set_companion_block_retain_height(int(req["height"]))
        return {}

    def get_block_retain_height(self, _req: dict) -> dict:
        return {
            "app_retain_height": str(
                self.pruner.state_store.load_retain_height(
                    pruner_mod.APP_RETAIN)),
            "pruning_service_retain_height": str(
                self.pruner.state_store.load_retain_height(
                    pruner_mod.COMPANION_RETAIN)),
        }

    def set_block_results_retain_height(self, req: dict) -> dict:
        self.pruner.set_abci_res_retain_height(int(req["height"]))
        return {}

    def get_block_results_retain_height(self, _req: dict) -> dict:
        return {"pruning_service_retain_height": str(
            self.pruner.get_abci_res_retain_height())}

    def set_tx_indexer_retain_height(self, req: dict) -> dict:
        self.pruner.set_tx_indexer_retain_height(int(req["height"]))
        return {}

    def get_tx_indexer_retain_height(self, _req: dict) -> dict:
        return {"height": str(self.pruner.get_tx_indexer_retain_height())}

    def set_block_indexer_retain_height(self, req: dict) -> dict:
        self.pruner.set_block_indexer_retain_height(int(req["height"]))
        return {}

    def get_block_indexer_retain_height(self, _req: dict) -> dict:
        return {"height": str(self.pruner.get_block_indexer_retain_height())}


class _MultiHandler(grpc.GenericRpcHandler):
    def __init__(self, servicers):
        self.servicers = servicers

    def service(self, handler_call_details):
        for s in self.servicers:
            h = s.service(handler_call_details)
            if h is not None:
                return h
        return None


def serve(servicers, addr: str) -> tuple[grpc.Server, str]:
    """Start a gRPC server hosting the servicers; returns (server,
    'host:bound_port')."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((_MultiHandler(servicers),))
    host = addr.removeprefix("tcp://")
    port = server.add_insecure_port(host)
    if port == 0:
        raise RuntimeError(f"gRPC bind failed on {addr!r}")
    server.start()
    bound = f"{host.rsplit(':', 1)[0]}:{port}"
    return server, bound


# ----------------------------------------------------------------- client


class GRPCServicesClient:
    """Minimal client for the JSON-framed services (tests, operator
    tooling, the data companion)."""

    def __init__(self, addr: str):
        self.channel = grpc.aio.insecure_channel(addr.removeprefix("tcp://"))

    async def call(self, service: str, method: str, req: dict | None = None) -> dict:
        rpc = self.channel.unary_unary(
            f"/cometbft_tpu.rpc.{service}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        out = await rpc(json.dumps(req or {}).encode())
        return json.loads(out)

    async def stream(self, service: str, method: str, req: dict | None = None):
        rpc = self.channel.unary_stream(
            f"/cometbft_tpu.rpc.{service}/{method}",
            request_serializer=_ident, response_deserializer=_ident)
        async for out in rpc(json.dumps(req or {}).encode()):
            yield json.loads(out)

    async def close(self) -> None:
        await self.channel.close()


async def wait_closed(server: grpc.Server, grace: float = 1.0) -> None:
    """Stop the server and wait for the drain, so a restart can rebind."""
    await asyncio.to_thread(server.stop(grace=grace).wait)
