from cometbft_tpu.rpc.server import RPCServer

__all__ = ["RPCServer"]
