"""JSON-RPC 2.0 server over HTTP (+ URI GET convenience routes).

Reference: rpc/jsonrpc/server/http_server.go + http_uri_handler.go. A
hand-rolled asyncio HTTP/1.1 server (stdlib-only, like everything else):
POST / with a JSON-RPC envelope, or GET /<route>?k=v with query params —
both hit the same Environment handlers. WebSocket subscriptions arrive
with the pubsub EventBus (rpc/core/events.go analog).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import urllib.parse

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService, TaskRunner
from cometbft_tpu.rpc.core import Environment, QuotedStr, RPCError, UriStr


class _RawText:
    """Marker for non-JSON HTTP responses (the /metrics exposition)."""

    def __init__(self, text: str):
        self.text = text

MAX_BODY = 1_000_000
MAX_HEADERS = 64

# Overload route classes (libs/overload.py): write routes inject work
# into the node (mempool, evidence) and get the smaller budget; reads
# only serve existing state. Control/ops routes are exempt — an
# operator must be able to ask a saturated node how saturated it is.
WRITE_ROUTES = frozenset({
    "broadcast_tx_async", "broadcast_tx_sync", "broadcast_tx_commit",
    "broadcast_evidence", "check_tx",
})
EXEMPT_ROUTES = frozenset({
    "health", "status", "crypto_health", "storage_health", "net_info",
    "net_telemetry", "dial_seeds", "dial_peers",
})
WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"  # RFC 6455 §1.3
WS_MAX_FRAME = 1 << 20
WS_MAX_MESSAGE = 1 << 21  # aggregate cap across fragments (HTTP has MAX_BODY)


_openapi_cache: str | None = None


def _openapi_spec() -> str:
    global _openapi_cache
    if _openapi_cache is None:
        import os as _os

        path = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)), "openapi.yaml")
        with open(path) as f:
            _openapi_cache = f.read()
    return _openapi_cache


class RPCServer(BaseService):
    def __init__(self, node, config, logger: cmtlog.Logger | None = None,
                 env=None):
        """node may be None when `env` supplies the routes (light proxy) —
        then logger is required and node-backed extras (metrics endpoint,
        websocket subscriptions) are disabled."""
        super().__init__("RPC", logger or node.logger.with_fields(module="rpc"))
        self.node = node
        self.config = config
        self.env = env if env is not None else Environment(node)
        self.routes = self.env.routes()
        self._server: asyncio.Server | None = None
        self.bound_addr = ""
        # overload guard: bounded per-route-class in-flight budgets with
        # a short queue deadline, then shed (-32005 + retry hint). All
        # single-event-loop state — no lock needed.
        self._budgets = {
            "read": getattr(config, "overload_read_inflight", 256),
            "write": getattr(config, "overload_write_inflight", 64),
        }
        self._inflight = {"read": 0, "write": 0}
        self._queue_timeout = getattr(config, "overload_queue_timeout", 0.05)
        self._write_timeout = getattr(config, "slow_client_timeout", 10.0)

    async def on_start(self) -> None:
        addr = self.config.laddr.removeprefix("tcp://")
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle_conn, host or "127.0.0.1", int(port)
        )
        sock = self._server.sockets[0].getsockname()
        self.bound_addr = f"{sock[0]}:{sock[1]}"
        reg = getattr(self.node, "overload", None)
        if reg is not None:
            reg.register("rpc", self._rpc_utilization)
        self.logger.info("RPC listening", addr=self.bound_addr)

    async def on_stop(self) -> None:
        reg = getattr(self.node, "overload", None)
        if reg is not None:
            reg.unregister("rpc")
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # env-held services (the light fleet's head watcher) must not
        # outlive the RPC plane
        closer = getattr(self.env, "close", None)
        if closer is not None:
            try:
                await closer()
            except Exception:  # noqa: BLE001 - shutdown is best-effort
                pass

    # ------------------------------------------------------------- serving

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request_line = await reader.readline()
                if not request_line:
                    return
                parts = request_line.decode("latin-1").split()
                if len(parts) != 3:
                    return
                method, target, _version = parts
                headers = {}
                for _ in range(MAX_HEADERS):
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                if headers.get("upgrade", "").lower() == "websocket":
                    await self._handle_websocket(reader, writer, headers)
                    return
                body = b""
                n = int(headers.get("content-length", 0) or 0)
                if n > MAX_BODY:
                    await self._respond(writer, 413, {"error": "body too large"})
                    return
                if n:
                    body = await reader.readexactly(n)
                keep_alive = headers.get("connection", "").lower() != "close"
                status, payload = await self._dispatch(method, target, body)
                await self._respond(writer, status, payload, keep_alive)
                if not keep_alive:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:  # noqa: BLE001 - a bad request must not kill the server
            self.logger.error("rpc connection error", err=str(e))
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, method: str, target: str, body: bytes):
        if method == "POST":
            try:
                req = json.loads(body or b"{}")
            except json.JSONDecodeError:
                return 400, _err_envelope(None, -32700, "parse error")
            if isinstance(req, list):  # batch
                out = [await self._call_one(r) for r in req]
                return 200, out
            return 200, await self._call_one(req)
        if method == "GET":
            path, _, query = target.partition("?")
            route = path.strip("/")
            if route == "":
                return 200, {"routes": sorted(self.routes)}
            if route == "metrics":
                # Prometheus text exposition (config.instrumentation;
                # reference serves this on prometheus_laddr — one process
                # port here, same scrape contract). The crypto backend-
                # health plane lives in the process-global registry (the
                # device is shared across in-proc nodes) and is appended
                # after the node's own series.
                reg = getattr(self.node, "metrics_registry", None)
                if reg is None:
                    return 404, {"error": "metrics disabled"}
                from cometbft_tpu.libs import metrics as cmtmetrics

                body = reg.render()
                if reg is not cmtmetrics.global_registry():
                    cmtmetrics.crypto_metrics()    # ensure series exist
                    cmtmetrics.netchaos_metrics()  # (net-chaos plane too)
                    cmtmetrics.sched_metrics()     # (verify scheduler)
                    cmtmetrics.light_fleet_metrics()  # (serving plane)
                    cmtmetrics.overload_metrics()  # (overload plane)
                    body += cmtmetrics.global_registry().render()
                return 200, _RawText(body)
            if route == "openapi.yaml":
                # the machine-readable API description (reference:
                # rpc/openapi/openapi.yaml) — immutable at runtime, read
                # once (blocking file I/O must not recur on the event loop)
                return 200, _RawText(_openapi_spec())
            params = {k: v[0] for k, v in urllib.parse.parse_qs(query).items()}
            # quoted URI params are string literals, unquoted hex/number
            # (http_uri_handler.go); keep which on the value so []byte args
            # decode correctly — JSON-body params stay plain str (base64)
            params = {
                k: QuotedStr(v[1:-1]) if len(v) >= 2 and v[0] == v[-1] == '"' else UriStr(v)
                for k, v in params.items()
            }
            envelope = {"jsonrpc": "2.0", "id": -1, "method": route, "params": params}
            return 200, await self._call_one(envelope)
        return 405, {"error": "method not allowed"}

    # ------------------------------------------------------ overload guard

    @staticmethod
    def _route_class(method: str) -> str | None:
        """None = exempt from the overload guard (control plane)."""
        if method in EXEMPT_ROUTES or method.startswith("unsafe_"):
            return None
        return "write" if method in WRITE_ROUTES else "read"

    def _rpc_utilization(self) -> float:
        """The rpc plane's signal for the overload registry: the most
        loaded route class's in-flight fraction."""
        return max(
            (self._inflight[k] / b
             for k, b in self._budgets.items() if b > 0),
            default=0.0)

    async def _admit(self, klass: str) -> bool:
        """Take an in-flight slot for `klass`, waiting out at most the
        queue deadline for one to free. False = shed the request."""
        budget = self._budgets.get(klass, 0)
        if budget <= 0:  # unguarded class (budget disabled)
            self._inflight[klass] = self._inflight.get(klass, 0) + 1
            return True
        if self._inflight[klass] < budget:
            self._inflight[klass] += 1
            return True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self._queue_timeout
        while loop.time() < deadline:
            await asyncio.sleep(0.005)
            if self._inflight[klass] < budget:
                self._inflight[klass] += 1
                return True
        return False

    def _shed_envelope(self, rid, klass: str) -> dict:
        from cometbft_tpu.libs import overload as _ovl

        reg = getattr(self.node, "overload", None)
        retry = _ovl.RETRY_AFTER_MS[_ovl.SATURATED]
        if reg is not None:
            reg.shed("rpc")
            retry = reg.retry_after_ms("rpc") or retry
        return _err_envelope(
            rid, -32005,
            f"rpc overloaded: {klass} budget exhausted "
            f"({self._budgets[klass]} in flight)",
            {"plane": "rpc", "retry_after_ms": retry})

    async def _call_one(self, req: dict) -> dict:
        rid = req.get("id", -1)
        method = req.get("method", "")
        handler = self.routes.get(method)
        if handler is None:
            return _err_envelope(rid, -32601, f"method {method!r} not found")
        params = req.get("params") or {}
        if not isinstance(params, dict):
            return _err_envelope(rid, -32602, "params must be a map")
        klass = self._route_class(method)
        if klass is not None and not await self._admit(klass):
            return self._shed_envelope(rid, klass)
        try:
            result = await handler(params)
        except RPCError as e:
            return _err_envelope(rid, e.code, str(e),
                                 getattr(e, "data", None))
        except Exception as e:  # noqa: BLE001
            self.logger.error("rpc handler failed", method=method, err=str(e))
            return _err_envelope(rid, -32603, f"internal error: {e}")
        finally:
            if klass is not None:
                self._inflight[klass] -= 1
        return {"jsonrpc": "2.0", "id": rid, "result": result}

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       payload, keep_alive: bool = False) -> None:
        if isinstance(payload, _RawText):
            body = payload.text.encode()
            ctype = "text/plain; version=0.0.4"
        else:
            body = json.dumps(payload).encode()
            ctype = "application/json"
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed",
                  413: "Payload Too Large"}.get(status, "Error")
        conn = "keep-alive" if keep_alive else "close"
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {conn}\r\n\r\n"
        )
        writer.write(head.encode() + body)
        # slow-client write timeout: a reader that stops draining must
        # not pin this handler (and its response buffer) forever — time
        # the flush out and let the connection-level handler close it
        try:
            await asyncio.wait_for(writer.drain(), self._write_timeout)
        except asyncio.TimeoutError:
            raise ConnectionError(
                "slow client: response flush timed out") from None


    # ---------------------------------------------------------- websocket
    # Reference: rpc/jsonrpc/server/ws_handler.go + rpc/core/events.go —
    # JSON-RPC over an RFC 6455 socket, with subscribe/unsubscribe backed
    # by the EventBus; matching events are pushed as they fire.

    async def _handle_websocket(self, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key", "")
        if not key:
            await self._respond(writer, 400, {"error": "missing Sec-WebSocket-Key"})
            return
        accept = base64.b64encode(
            hashlib.sha1((key + WS_GUID).encode()).digest()).decode()
        writer.write(
            ("HTTP/1.1 101 Switching Protocols\r\nUpgrade: websocket\r\n"
             f"Connection: Upgrade\r\nSec-WebSocket-Accept: {accept}\r\n\r\n").encode())
        await writer.drain()

        peer = writer.get_extra_info("peername")
        client_id = f"ws-{peer[0]}:{peer[1]}" if peer else f"ws-{id(writer)}"
        tasks = TaskRunner(client_id)
        send_lock = asyncio.Lock()

        async def send_json(payload: dict) -> None:
            async with send_lock:
                await _ws_send(writer, json.dumps(payload).encode())

        try:
            while True:
                opcode, data, controls = await _ws_recv(reader)
                for cop, cdata in controls + [(opcode, data)]:
                    if cop == 0x9:  # ping -> pong
                        async with send_lock:
                            await _ws_send(writer, cdata, opcode=0xA)
                if opcode == 0x8:  # close
                    return
                if opcode not in (0x1, 0x2):
                    continue
                try:
                    req = json.loads(data)
                except json.JSONDecodeError:
                    await send_json(_err_envelope(None, -32700, "parse error"))
                    continue
                await self._ws_call(req, client_id, tasks, send_json)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            await tasks.cancel_all()
            closer = getattr(self.env, "ws_client_closed", None)
            if closer is not None:
                try:
                    await closer(client_id)
                except Exception:  # noqa: BLE001
                    pass
            try:
                if getattr(self.node, "event_bus", None) is not None:
                    self.node.event_bus.unsubscribe_all(client_id)
            except Exception:  # noqa: BLE001
                pass

    async def _ws_call(self, req: dict, client_id: str, tasks: TaskRunner,
                       send_json) -> None:
        rid = req.get("id", -1)
        method = req.get("method", "")
        params = req.get("params") or {}
        if method in ("light_subscribe", "light_unsubscribe"):
            # the serving plane's streaming route (light/fleet.py):
            # verified headers pushed as heights commit, with
            # backpressure and per-client send budgets enforced by the
            # fleet — independent of the event bus
            handler = getattr(
                self.env,
                "ws_light_subscribe" if method == "light_subscribe"
                else "ws_light_unsubscribe", None)
            if handler is None:
                await send_json(_err_envelope(
                    rid, -32601, "light streaming unavailable on this "
                                 "endpoint"))
                return
            await handler(req, client_id, tasks, send_json)
            return
        bus = getattr(self.node, "event_bus", None)
        if bus is None:
            # node-less servers (light proxy) may relay subscriptions
            # upstream via an env-provided hook
            ws_proxy = getattr(self.env, "ws_passthrough", None)
            if ws_proxy is not None and method in (
                    "subscribe", "unsubscribe", "unsubscribe_all"):
                await ws_proxy(req, client_id, tasks, send_json)
                return
            await send_json(_err_envelope(
                rid, -32601, "subscriptions unavailable on this endpoint"))
            return
        if method == "subscribe":
            query = params.get("query", "")
            if not query:
                await send_json(_err_envelope(rid, -32602, "missing query"))
                return
            if (bus.server.num_client_subscriptions(client_id)
                    >= self.config.max_subscriptions_per_client):
                await send_json(_err_envelope(rid, -32603, "too many subscriptions"))
                return
            try:
                sub = bus.subscribe(client_id, query)
            except Exception as e:  # noqa: BLE001
                await send_json(_err_envelope(rid, -32602, f"subscribe failed: {e}"))
                return
            tasks.spawn(self._pump_events(sub, query, rid, send_json),
                        name=f"ws-sub-{len(query)}")
            await send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
        elif method == "unsubscribe":
            try:
                bus.unsubscribe(client_id, params.get("query", ""))
                await send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
            except Exception as e:  # noqa: BLE001
                await send_json(_err_envelope(rid, -32603, str(e)))
        elif method == "unsubscribe_all":
            try:
                bus.unsubscribe_all(client_id)
            except Exception:  # noqa: BLE001
                pass
            await send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
        else:
            await send_json(await self._call_one(req))

    async def _pump_events(self, sub, query: str, rid, send_json) -> None:
        """events.go:105: forward matching events until cancellation."""
        while True:
            msg = await sub.out.get()
            if msg is None:  # canceled
                # tell the client its subscription died (slow consumer /
                # server shutdown) — a silent stop would leave it waiting
                # forever on a healthy TCP conn (ref ws_handler.go sends
                # the cancellation reason)
                try:
                    await send_json(_err_envelope(
                        f"{rid}#event", -32000,
                        f"subscription canceled: {sub.canceled or 'server closed it'} "
                        f"(query: {query})"))
                except (ConnectionError, asyncio.IncompleteReadError, OSError):
                    pass
                return
            await send_json({
                "jsonrpc": "2.0",
                "id": f"{rid}#event",
                "result": {
                    "query": query,
                    "data": _event_value(msg.data),
                    "events": msg.events,
                },
            })


def _event_value(data) -> dict:
    """Serialize event payloads for RPC consumers (shape follows the
    reference's result_event types loosely)."""
    from cometbft_tpu.abci import codec as abci_codec
    from cometbft_tpu.types import event_bus as eb

    if isinstance(data, eb.EventDataTx):
        return {"type": "tendermint/event/Tx", "value": {
            "TxResult": {
                "height": str(data.height), "index": data.index,
                "tx": base64.b64encode(data.tx).decode(),
                "result": abci_codec._to_jsonable(data.result),
            }}}
    if isinstance(data, eb.EventDataNewBlock):
        blk = data.block
        return {"type": "tendermint/event/NewBlock", "value": {
            "block": {
                "header": {"height": str(blk.header.height),
                           "chain_id": blk.header.chain_id,
                           "app_hash": blk.header.app_hash.hex().upper()},
                "num_txs": str(len(blk.data.txs)),
            }}}
    if isinstance(data, eb.EventDataRoundState):
        return {"type": "tendermint/event/RoundState", "value": {
            "height": str(data.height), "round": data.round_, "step": data.step}}
    return {"type": f"tendermint/event/{type(data).__name__}", "value": {}}


async def _ws_recv(reader) -> tuple[int, bytes, list[tuple[int, bytes]]]:
    """Read one (possibly fragmented) RFC 6455 message from a client.
    Control frames may legally interleave with message fragments
    (RFC 6455 §5.4); they are collected and returned alongside the data
    message so no fragment state is lost. A close control short-circuits.
    Returns (opcode, payload, controls-seen-before-completion)."""
    opcode = None
    buf = b""
    controls: list[tuple[int, bytes]] = []
    while True:
        h = await reader.readexactly(2)
        fin = h[0] & 0x80
        op = h[0] & 0x0F
        masked = h[1] & 0x80
        ln = h[1] & 0x7F
        if ln == 126:
            ln = int.from_bytes(await reader.readexactly(2), "big")
        elif ln == 127:
            ln = int.from_bytes(await reader.readexactly(8), "big")
        if ln > WS_MAX_FRAME or len(buf) + ln > WS_MAX_MESSAGE:
            raise ConnectionError("ws frame/message too large")
        mask = await reader.readexactly(4) if masked else b""
        payload = await reader.readexactly(ln)
        if masked:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        if op == 0x8:  # close ends everything, fragments moot
            return op, payload, controls
        if op in (0x9, 0xA):
            if opcode is None and not buf:
                return op, payload, controls  # no fragmentation in flight
            controls.append((op, payload))
            continue
        opcode = opcode if op == 0 else op
        buf += payload
        if fin:
            return opcode or 0x1, buf, controls


async def _ws_send(writer, payload: bytes, opcode: int = 0x1) -> None:
    ln = len(payload)
    head = bytes([0x80 | opcode])
    if ln < 126:
        head += bytes([ln])
    elif ln < (1 << 16):
        head += bytes([126]) + ln.to_bytes(2, "big")
    else:
        head += bytes([127]) + ln.to_bytes(8, "big")
    writer.write(head + payload)
    await writer.drain()


def _err_envelope(rid, code: int, message: str, data: dict | None = None) -> dict:
    err: dict = {"code": code, "message": message}
    if data is not None:
        err["data"] = data
    return {"jsonrpc": "2.0", "id": rid, "error": err}
