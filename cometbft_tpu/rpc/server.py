"""JSON-RPC 2.0 server over HTTP (+ URI GET convenience routes).

Reference: rpc/jsonrpc/server/http_server.go + http_uri_handler.go. A
hand-rolled asyncio HTTP/1.1 server (stdlib-only, like everything else):
POST / with a JSON-RPC envelope, or GET /<route>?k=v with query params —
both hit the same Environment handlers. WebSocket subscriptions arrive
with the pubsub EventBus (rpc/core/events.go analog).
"""

from __future__ import annotations

import asyncio
import json
import urllib.parse

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.rpc.core import Environment, RPCError

MAX_BODY = 1_000_000
MAX_HEADERS = 64


class RPCServer(BaseService):
    def __init__(self, node, config, logger: cmtlog.Logger | None = None):
        super().__init__("RPC", logger or node.logger.with_fields(module="rpc"))
        self.node = node
        self.config = config
        self.env = Environment(node)
        self.routes = self.env.routes()
        self._server: asyncio.Server | None = None
        self.bound_addr = ""

    async def on_start(self) -> None:
        addr = self.config.laddr.removeprefix("tcp://")
        host, _, port = addr.rpartition(":")
        self._server = await asyncio.start_server(
            self._handle_conn, host or "127.0.0.1", int(port)
        )
        sock = self._server.sockets[0].getsockname()
        self.bound_addr = f"{sock[0]}:{sock[1]}"
        self.logger.info("RPC listening", addr=self.bound_addr)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------- serving

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request_line = await reader.readline()
                if not request_line:
                    return
                parts = request_line.decode("latin-1").split()
                if len(parts) != 3:
                    return
                method, target, _version = parts
                headers = {}
                for _ in range(MAX_HEADERS):
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                n = int(headers.get("content-length", 0) or 0)
                if n > MAX_BODY:
                    await self._respond(writer, 413, {"error": "body too large"})
                    return
                if n:
                    body = await reader.readexactly(n)
                keep_alive = headers.get("connection", "").lower() != "close"
                status, payload = await self._dispatch(method, target, body)
                await self._respond(writer, status, payload, keep_alive)
                if not keep_alive:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:  # noqa: BLE001 - a bad request must not kill the server
            self.logger.error("rpc connection error", err=str(e))
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, method: str, target: str, body: bytes):
        if method == "POST":
            try:
                req = json.loads(body or b"{}")
            except json.JSONDecodeError:
                return 400, _err_envelope(None, -32700, "parse error")
            if isinstance(req, list):  # batch
                out = [await self._call_one(r) for r in req]
                return 200, out
            return 200, await self._call_one(req)
        if method == "GET":
            path, _, query = target.partition("?")
            route = path.strip("/")
            if route == "":
                return 200, {"routes": sorted(self.routes)}
            params = {k: v[0] for k, v in urllib.parse.parse_qs(query).items()}
            # URI params arrive quoted (reference http_uri_handler.go)
            params = {k: v.strip('"') for k, v in params.items()}
            envelope = {"jsonrpc": "2.0", "id": -1, "method": route, "params": params}
            return 200, await self._call_one(envelope)
        return 405, {"error": "method not allowed"}

    async def _call_one(self, req: dict) -> dict:
        rid = req.get("id", -1)
        method = req.get("method", "")
        handler = self.routes.get(method)
        if handler is None:
            return _err_envelope(rid, -32601, f"method {method!r} not found")
        params = req.get("params") or {}
        if not isinstance(params, dict):
            return _err_envelope(rid, -32602, "params must be a map")
        try:
            result = await handler(params)
        except RPCError as e:
            return _err_envelope(rid, e.code, str(e))
        except Exception as e:  # noqa: BLE001
            self.logger.error("rpc handler failed", method=method, err=str(e))
            return _err_envelope(rid, -32603, f"internal error: {e}")
        return {"jsonrpc": "2.0", "id": rid, "result": result}

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       payload, keep_alive: bool = False) -> None:
        body = json.dumps(payload).encode()
        reason = {200: "OK", 400: "Bad Request", 405: "Method Not Allowed",
                  413: "Payload Too Large"}.get(status, "Error")
        conn = "keep-alive" if keep_alive else "close"
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {conn}\r\n\r\n"
        )
        writer.write(head.encode() + body)
        await writer.drain()


def _err_envelope(rid, code: int, message: str) -> dict:
    return {"jsonrpc": "2.0", "id": rid, "error": {"code": code, "message": message}}
