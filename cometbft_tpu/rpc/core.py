"""RPC route handlers — the node's client-visible API surface.

Reference: rpc/core/ (routes.go:12-56 route table; env.go Environment).
Each handler reads node internals and returns a JSON-serializable dict,
matching the reference's response shapes (hex-encoded hashes, stringified
int64s, base64 txs) closely enough for familiarity without claiming
byte-compat.
"""

from __future__ import annotations

import base64

from cometbft_tpu.abci import types as abci

# rpc/core/env.go:32 genesisChunkSize (16 MB)
GENESIS_CHUNK_SIZE = 16 * 1024 * 1024


def header_dict(h) -> dict:
    """Complete JSON header — every field, lossless. Shared by the node RPC
    and the light proxy (light/proxy.py)."""
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": str(h.time),
        "last_block_id": {
            "hash": _hex(h.last_block_id.hash),
            "parts": {"total": h.last_block_id.part_set_header.total,
                      "hash": _hex(h.last_block_id.part_set_header.hash)},
        },
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
    }


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _hex(b: bytes) -> str:
    return b.hex().upper()


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: dict | None = None):
        super().__init__(message)
        self.code = code
        # machine-readable error detail (JSON-RPC 2.0 `error.data`): the
        # overload plane rides here — every -32005 shed carries
        # {"plane": ..., "retry_after_ms": ...} so clients back off
        # without parsing message text
        self.data = data


def _int_param(value, name: str) -> int:
    """Parse a client-supplied integer param: malformed input is the
    CLIENT's error (-32602 invalid params), never -32603 internal."""
    try:
        return int(value)
    except (TypeError, ValueError):
        raise RPCError(
            -32602, f"bad {name} param (want int): {value!r}") from None


def _hex_param(value, name: str) -> bytes:
    """Parse a client-supplied hex param the same way: -32602, not a
    raw ValueError surfacing as -32603."""
    if isinstance(value, str) and value[:2] in ("0x", "0X"):
        value = value[2:]
    try:
        return bytes.fromhex(value)
    except (TypeError, ValueError):
        raise RPCError(
            -32602, f"bad {name} param (want hex): {value!r}") from None


class QuotedStr(str):
    """A URI arg that arrived as a '"quoted"' string literal — for []byte
    params its UTF-8 bytes ARE the value (reference
    rpc/jsonrpc/server/http_uri_handler.go: quoted args are string
    literals, unquoted are hex/number)."""


class UriStr(str):
    """An unquoted URI arg — []byte params decode as hex (0x optional),
    matching the reference URI handler; JSON-body params (plain str) stay
    strictly base64 (proto3 JSON), so base64 payloads that merely look like
    hex are never misdecoded."""


def _ws_err(rid, code: int, message: str, data: dict | None = None) -> dict:
    err: dict = {"code": code, "message": message}
    if data is not None:
        err["data"] = data
    return {"jsonrpc": "2.0", "id": rid, "error": err}


class Environment:
    """rpc/core/env.go: the handlers' view of the node."""

    def __init__(self, node):
        self.node = node
        self._bg_tasks: set = set()
        self._gen_chunks: list[str] | None = None
        # lazily-built light-client fleet service (light/fleet.py) behind
        # the light_verify / light_subscribe routes
        self._light_fleet = None
        self._fleet_lock = None  # created on the serving loop
        self._fleet_head_sub = None  # NewBlock subscription feeding it

    def _shed_data(self, plane: str, retry_after_ms: int | None = None,
                   record: bool = False) -> dict:
        """Build the unified -32005 `error.data` payload; with `record`,
        also account the shed on the overload registry (every shed lands
        on /metrics with its plane label). `record=False` is for errors
        whose subsystem already counted itself (ErrMempoolIsFull)."""
        from cometbft_tpu.libs import overload as _ovl

        reg = getattr(self.node, "overload", None)
        if reg is not None:
            if record:
                reg.shed(plane)
            if not retry_after_ms:
                retry_after_ms = reg.retry_after_ms(plane)
        if not retry_after_ms:
            retry_after_ms = _ovl.RETRY_AFTER_MS[_ovl.SATURATED]
        return {"plane": plane, "retry_after_ms": retry_after_ms}

    # ------------------------------------------------------------- info

    async def health(self, _params: dict) -> dict:
        """Errors (not an empty OK) once the consensus routine has died —
        a validator that stopped committing must not answer healthy
        (ref consensus/state.go:789-802 containment)."""
        cs = getattr(self.node, "consensus_state", None)
        if cs is not None and getattr(cs, "failed", False):
            raise RPCError(-32603, "consensus failure: receive routine dead")
        out: dict = {}
        # overload plane snapshot (libs/overload.py): per-plane watermark
        # level, utilization, and shed counts — saturated-but-alive is a
        # state operators page on, so it rides the liveness probe
        reg = getattr(self.node, "overload", None)
        if reg is not None:
            out["overload"] = reg.health()
        return out

    async def crypto_health(self, _params: dict) -> dict:
        """The device-fault resilience snapshot (no reference analog):
        active verify backend, breaker states, retry/failure counters,
        the verify scheduler's `verify_sched` section (batch fill,
        per-class queue depth, deadline misses — sched/scheduler.py),
        the multi-chip `mesh` section (live size, per-chip fault-domain
        breakers, eviction/readmission/redispatch churn —
        parallel/mesh.py) and any armed chaos schedule (ops/dispatch.py
        health_snapshot). Served in inspect mode too — a crashed node's
        disk plus the process-global device state remain examinable."""
        from cometbft_tpu.ops import dispatch

        snap = dispatch.health_snapshot()
        # certificate-plane section (cert/plane.py): per-NODE production
        # and consumption counters, merged here because the rest of the
        # snapshot is process-global device state
        plane = getattr(self.node, "cert_plane", None)
        if plane is not None:
            snap["cert"] = plane.health()
        return snap

    async def storage_health(self, _params: dict) -> dict:
        """The storage-fault resilience snapshot (crypto_health's disk
        sibling): WAL fsync p50/p99 and truncation/repair counts, db
        write latency, CRC-guard corruption detections, per-(site,kind)
        injected-fault counters, the armed disk-chaos schedule, and the
        node's durability knobs. Served in inspect mode too — a crashed
        node's storage plane remains examinable."""
        from cometbft_tpu.libs import diskchaos
        from cometbft_tpu.libs import metrics as cmtmetrics

        snap = cmtmetrics.storage_metrics().health()
        snap["disk_chaos"] = diskchaos.snapshot()
        cfg = getattr(self.node, "config", None)
        if cfg is not None:
            snap["config"] = {
                "synchronous": cfg.storage.synchronous,
                "checksum": cfg.storage.checksum,
                "db_backend": cfg.base.db_backend,
            }
        return snap

    async def status(self, _params: dict) -> dict:
        """rpc/core/status.go."""
        n = self.node
        latest_height = n.block_store.height()
        meta = n.block_store.load_block_meta(latest_height) if latest_height else None
        earliest = n.block_store.base()
        emeta = n.block_store.load_block_meta(earliest) if earliest else None
        pub_key = n.priv_validator.get_pub_key() if n.priv_validator else None
        return {
            "node_info": {
                "id": n.node_key.id(),
                "listen_addr": n.node_info.listen_addr,
                "network": n.node_info.network,
                "version": n.node_info.version,
                "moniker": n.node_info.moniker,
            },
            "sync_info": {
                "latest_block_hash": _hex(meta.block_id.hash) if meta else "",
                "latest_app_hash": _hex(meta.header.app_hash) if meta else "",
                "latest_block_height": str(latest_height),
                "latest_block_time": str(meta.header.time) if meta else "",
                "earliest_block_height": str(earliest),
                "earliest_block_hash": _hex(emeta.block_id.hash) if emeta else "",
                "catching_up": n.consensus_reactor.wait_sync,
                "consensus_failed": bool(
                    getattr(n.consensus_state, "failed", False)),
            },
            "validator_info": {
                "address": _hex(pub_key.address()) if pub_key else "",
                "pub_key": {"type": pub_key.type_(), "value": _b64(pub_key.bytes_())}
                if pub_key else None,
                "voting_power": "0",
            },
            "versions": self._versions_block(),
        }

    def _versions_block(self) -> dict:
        """Build/version identity — mirrored as the cometbft_build_info
        gauge on /metrics so dashboards and RPC agree on what is running."""
        from cometbft_tpu import version as _version

        cfg = getattr(self.node, "config", None)
        crypto_cfg = getattr(cfg, "crypto", None)
        schemes = ["ed25519", "secp256k1", "sr25519"]
        if crypto_cfg is None or getattr(crypto_cfg, "bls_enabled", True):
            schemes.append("bls12381")
        return {
            "version": _version.CMTSemVer,
            "abci": _version.ABCIVersion,
            "block_protocol": str(_version.BlockProtocol),
            "p2p_protocol": str(_version.P2PProtocol),
            "tpu_crypto_backend": str(_version.TPUCryptoBackend),
            "backend": getattr(crypto_cfg, "backend", "cpu"),
            "schemes": schemes,
        }

    async def net_info(self, _params: dict) -> dict:
        """rpc/core/net.go."""
        sw = self.node.switch
        return {
            "listening": True,
            "listeners": [self.node.node_info.listen_addr],
            "n_peers": str(sw.n_peers()),
            "peers": [
                {
                    "node_info": {
                        "id": p.id,
                        "moniker": p.node_info.moniker,
                        "listen_addr": p.node_info.listen_addr,
                    },
                    "is_outbound": p.outbound,
                    "connection_status": p.status(),
                }
                for p in sw.peers.values()
            ],
        }

    async def net_telemetry(self, _params: dict) -> dict:
        """Wire-plane telemetry (no reference analog): the full per-peer/
        per-channel network accounting rollup — bytes/msgs/packets both
        directions per channel per peer, send-queue depth + high-water,
        send-routine stall split, ping RTT EWMAs — plus the live link
        models (the host<->device tunnel estimate the kernels feed, and
        the aggregate p2p RTT view) and the armed net-chaos schedule.
        `cometbft netinfo` renders this across a fleet; the e2e runner
        snapshots it per node into the run report."""
        from cometbft_tpu.libs import linkmodel
        from cometbft_tpu.p2p import netchaos

        sw = getattr(self.node, "switch", None)
        # inspect mode serves a _NoSwitch stub: degrade to an empty rollup
        # (link models + chaos snapshot below are process-global and real)
        tele = getattr(sw, "net_telemetry", None)
        wire = tele() if tele is not None else {
            "n_peers": 0, "peers": [], "channels": {},
            "totals": {}, "peer_scores": {}}
        node_key = getattr(self.node, "node_key", None)
        node_info = getattr(self.node, "node_info", None)
        # gossip accounting (vote amplification as a measured number):
        # the consensus reactor's per-peer sent/needed rollup — absent in
        # inspect mode, where there is no live reactor
        cons = getattr(self.node, "consensus_reactor", None)
        acct = getattr(cons, "gossip_accounting", None)
        # discovery plane: the address book's hashed-bucket occupancy view
        # (per-source-group spread vs the geometric eclipse bound)
        book = getattr(self.node, "addr_book", None)
        return {
            "node_id": node_key.id() if node_key is not None else "",
            "moniker": node_info.moniker if node_info is not None else "",
            "listen_addr": (node_info.listen_addr
                            if node_info is not None else ""),
            **wire,
            "gossip": acct() if acct is not None else None,
            "discovery": book.stats() if book is not None else None,
            "tunnel": linkmodel.tunnel().snapshot(),
            "p2p_link": linkmodel.p2p().snapshot(),
            "net_chaos": netchaos.snapshot(),
        }

    async def genesis(self, _params: dict) -> dict:
        import json

        return {"genesis": json.loads(self.node.genesis_doc.to_json())}

    # ------------------------------------------------------------ blocks

    def _height_param(self, params: dict, default: int) -> int:
        h = params.get("height")
        if h is None or h == "":
            return default
        h = _int_param(h, "height")
        base, top = self.node.block_store.base(), self.node.block_store.height()
        if h < base or h > top:
            raise RPCError(-32603, f"height {h} is not available (range {base}-{top})")
        return h

    def _block_dict(self, block) -> dict:
        return {
            "header": {
                "chain_id": block.header.chain_id,
                "height": str(block.header.height),
                "time": str(block.header.time),
                "last_block_id": {"hash": _hex(block.header.last_block_id.hash)},
                "app_hash": _hex(block.header.app_hash),
                "data_hash": _hex(block.header.data_hash),
                "validators_hash": _hex(block.header.validators_hash),
                "proposer_address": _hex(block.header.proposer_address),
            },
            "data": {"txs": [_b64(tx) for tx in block.data.txs]},
            "evidence": {"evidence": [
                {
                    "type": type(ev).__name__,
                    "height": str(ev.height()),
                    "validator_addresses": [
                        d["validator_address"].hex().upper()
                        for d in ev.abci()],
                }
                for ev in block.evidence.evidence
            ]},
            "last_commit": {
                "height": str(block.last_commit.height),
                "round": block.last_commit.round_,
                "block_id": {"hash": _hex(block.last_commit.block_id.hash)},
                "signatures": [
                    {
                        "block_id_flag": int(cs.block_id_flag),
                        "validator_address": _hex(cs.validator_address),
                        "timestamp": str(cs.timestamp),
                        "signature": _b64(cs.signature) if cs.signature else None,
                    }
                    for cs in block.last_commit.signatures
                ],
            } if block.last_commit else None,
        }

    async def block(self, params: dict) -> dict:
        """rpc/core/blocks.go Block."""
        height = self._height_param(params, self.node.block_store.height())
        block = self.node.block_store.load_block(height)
        if block is None:
            raise RPCError(-32603, f"block at height {height} not found")
        return {
            "block_id": {"hash": _hex(block.hash())},
            "block": self._block_dict(block),
        }

    async def block_by_hash(self, params: dict) -> dict:
        h = _hex_param(params.get("hash"), "hash")
        block = self.node.block_store.load_block_by_hash(h)
        if block is None:
            raise RPCError(-32603, "block not found")
        return {"block_id": {"hash": _hex(block.hash())}, "block": self._block_dict(block)}

    async def blockchain(self, params: dict) -> dict:
        """rpc/core/blocks.go BlockchainInfo: metas for a height range."""
        top = self.node.block_store.height()
        base = self.node.block_store.base()
        max_h = min(_int_param(params.get("maxHeight") or top, "maxHeight"),
                    top)
        min_h = max(_int_param(params.get("minHeight")
                               or max(base, max_h - 19), "minHeight"), base)
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = self.node.block_store.load_block_meta(h)
            if m is not None:
                metas.append({
                    "block_id": {"hash": _hex(m.block_id.hash)},
                    "block_size": m.block_size,
                    "header": {
                        "height": str(m.header.height),
                        "time": str(m.header.time),
                        "app_hash": _hex(m.header.app_hash),
                        "proposer_address": _hex(m.header.proposer_address),
                    },
                    "num_txs": m.num_txs,
                })
        return {"last_height": str(top), "block_metas": metas}

    def _header_dict(self, h) -> dict:
        return header_dict(h)

    async def header(self, params: dict) -> dict:
        """rpc/core/blocks.go:176 Header."""
        height = self._height_param(params, self.node.block_store.height())
        meta = self.node.block_store.load_block_meta(height)
        if meta is None:
            raise RPCError(-32603, f"header at height {height} not found")
        return {"header": self._header_dict(meta.header)}

    async def header_by_hash(self, params: dict) -> dict:
        """rpc/core/blocks.go:205 HeaderByHash."""
        h = _hex_param(params.get("hash"), "hash")
        block = self.node.block_store.load_block_by_hash(h)
        if block is None:
            raise RPCError(-32603, "header not found")
        return {"header": self._header_dict(block.header)}

    async def block_results(self, params: dict) -> dict:
        """rpc/core/blocks.go:244 BlockResults: the persisted
        FinalizeBlock response for a committed height — tx results, events,
        validator and consensus-param updates, app hash."""
        from cometbft_tpu.abci import codec as abci_codec

        height = self._height_param(params, self.node.block_store.height())
        resp = self.node.state_store.load_finalize_block_response(height)
        if resp is None:
            raise RPCError(
                -32603, f"block results at height {height} not found")
        return {
            "height": str(height),
            "txs_results": [abci_codec._to_jsonable(r) for r in resp.tx_results],
            "finalize_block_events": [
                abci_codec._to_jsonable(e) for e in resp.events],
            "validator_updates": [
                abci_codec._to_jsonable(u) for u in resp.validator_updates],
            "consensus_param_updates": (
                abci_codec._to_jsonable(resp.consensus_param_updates)
                if resp.consensus_param_updates is not None else None),
            "app_hash": _hex(resp.app_hash),
        }

    async def consensus_params(self, params: dict) -> dict:
        """rpc/core/consensus.go:99 ConsensusParams: params in effect at a
        height (default: latest uncommitted = store top + 1, and explicit
        heights up to top + 1 are valid — like validators)."""
        top = self.node.block_store.height()
        h = params.get("height")
        if h in (None, ""):
            height = top + 1
        else:
            height = _int_param(h, "height")
            base = self.node.block_store.base()
            if height < base or height > top + 1:
                raise RPCError(
                    -32603,
                    f"height {height} is not available (range {base}-{top + 1})")
        cp = self.node.state_store.load_consensus_params(height)
        if cp is None:
            raise RPCError(
                -32603, f"consensus params at height {height} not found")
        return {
            "block_height": str(height),
            "consensus_params": {
                "block": {
                    "max_bytes": str(cp.block.max_bytes),
                    "max_gas": str(cp.block.max_gas),
                },
                "evidence": {
                    "max_age_num_blocks": str(cp.evidence.max_age_num_blocks),
                    "max_age_duration": str(cp.evidence.max_age_duration_ns),
                    "max_bytes": str(cp.evidence.max_bytes),
                },
                "validator": {"pub_key_types": cp.validator.pub_key_types},
                "version": {"app": str(cp.version.app)},
                "abci": {
                    "vote_extensions_enable_height": str(
                        cp.abci.vote_extensions_enable_height),
                },
            },
        }

    async def dump_consensus_state(self, _params: dict) -> dict:
        """rpc/core/consensus.go:56 DumpConsensusState: own round state
        plus every peer's tracked consensus round state."""
        from cometbft_tpu.consensus.reactor import PEER_STATE_KEY

        own = await self.consensus_state({})
        peer_states = []
        sw = self.node.switch
        for p in (list(sw.peers.values()) if sw is not None else []):
            ps = p.get(PEER_STATE_KEY)
            if ps is None:
                continue
            prs = ps.prs
            peer_states.append({
                "node_address": f"{p.id}@{p.node_info.listen_addr}",
                "peer_state": {
                    "round_state": {
                        "height": str(prs.height),
                        "round": prs.round_,
                        "step": int(prs.step),
                        "proposal": prs.proposal,
                        "catchup_commit_round": prs.catchup_commit_round,
                        "last_commit_round": prs.last_commit_round,
                    },
                },
            })
        return {"round_state": own["round_state"], "peers": peer_states}

    async def check_tx(self, params: dict) -> dict:
        """rpc/core/mempool.go:188 CheckTx: run the app's CheckTx WITHOUT
        adding to the mempool."""
        from cometbft_tpu.abci import codec as abci_codec

        tx = self._tx_param(params)
        res = await self.node.proxy_app.mempool.check_tx(
            abci.RequestCheckTx(tx=tx))
        return abci_codec._to_jsonable(res)

    async def genesis_chunked(self, params: dict) -> dict:
        """rpc/core/net.go:107 GenesisChunked: base64 chunks of the genesis
        document for payloads too large for one response."""
        chunks = self._genesis_chunks()
        if not chunks:
            raise RPCError(-32603, "genesis chunks are not initialized")
        cid = _int_param(params.get("chunk") or 0, "chunk")
        if cid < 0 or cid >= len(chunks):
            raise RPCError(
                -32602,
                f"there are {len(chunks)} chunks, {cid} is invalid")
        return {
            "chunk": str(cid),
            "total": str(len(chunks)),
            "data": chunks[cid],
        }

    def _genesis_chunks(self) -> list[str]:
        if self._gen_chunks is None:
            data = self.node.genesis_doc.to_json().encode()
            size = GENESIS_CHUNK_SIZE
            self._gen_chunks = [
                _b64(data[i:i + size]) for i in range(0, len(data), size)
            ]
        return self._gen_chunks

    async def commit(self, params: dict) -> dict:
        """rpc/core/blocks.go Commit: the COMPLETE signed header — every
        header field and every commit signature — so a light client can
        verify it (lossless, unlike a summary view)."""
        height = self._height_param(params, self.node.block_store.height())
        commit = self.node.block_store.load_block_commit(height)
        meta = self.node.block_store.load_block_meta(height)
        if commit is None or meta is None:
            raise RPCError(-32603, f"commit at height {height} not found")
        return {
            "canonical": True,
            "signed_header": {
                "header": self._header_dict(meta.header),
                "commit": {
                    "height": str(commit.height),
                    "round": commit.round_,
                    "block_id": {
                        "hash": _hex(commit.block_id.hash),
                        "parts": {"total": commit.block_id.part_set_header.total,
                                  "hash": _hex(commit.block_id.part_set_header.hash)},
                    },
                    "signatures": [
                        {
                            "block_id_flag": int(cs.block_id_flag),
                            "validator_address": _hex(cs.validator_address),
                            "timestamp": str(cs.timestamp),
                            "signature": _b64(cs.signature),
                        }
                        for cs in commit.signatures
                    ],
                },
            },
        }

    async def light_block(self, params: dict) -> dict:
        """Framework extension: the wire-exact LightBlock proto (base64) at
        a height — SignedHeader from the stores + the valset whose hash the
        header carries. The RPC light provider (light/rpc_provider.py) and
        statesync bootstrap consume this; a JSON rebuild of a commit can
        never be trusted to be byte-exact, the proto is."""
        top = self.node.block_store.height()
        try:
            height = self._height_param(params, top)
        except RPCError as e:
            raise RPCError(-32001, str(e)) from e  # out of range = no material
        meta = self.node.block_store.load_block_meta(height)
        # canonical commit lands with block height+1; the head falls back to
        # the seen commit (rpc/core/blocks.go Commit canonical=false)
        commit = (self.node.block_store.load_block_commit(height)
                  or self.node.block_store.load_seen_commit(height))
        vals = self.node.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            # -32001: no block material at this height (distinct code so the
            # RPC light provider classifies without parsing message text)
            raise RPCError(-32001, f"light block at height {height} not available")
        from cometbft_tpu.types.light import LightBlock, SignedHeader

        lb = LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )
        return {"height": str(height), "light_block": _b64(lb.to_proto())}

    async def commit_certificate(self, params: dict) -> dict:
        """Framework extension (cert/): the succinct finality certificate
        at a height — one aggregated BLS signature + signer bitmap,
        verified anywhere with ONE pairing check. -32001 when the height
        has no certificate (uncertifiable set, not yet produced, or
        quarantined): consumers fall back to per-vote verification over
        `light_block`, the same material-missing convention that route
        uses."""
        plane = getattr(self.node, "cert_plane", None)
        if plane is None:
            raise RPCError(
                -32601, "certificate plane disabled (set cert.enabled)")
        top = self.node.block_store.height()
        try:
            height = self._height_param(params, top)
        except RPCError as e:
            raise RPCError(-32001, str(e)) from e  # out of range = no material
        raw = plane.serve(height)
        if raw is None:
            raise RPCError(
                -32001, f"no commit certificate at height {height}")
        from cometbft_tpu.cert import CommitCertificate

        out = {"height": str(height), "certificate": _b64(raw)}
        try:
            out["summary"] = CommitCertificate.decode(raw).summary()
        except ValueError:
            pass  # raw bytes still served; consumers verify anyway
        return out

    # ------------------------------------------------------- light fleet
    # The serving plane (light/fleet.py): coalesced skipping
    # verification + checkpoint skip-list cache behind `light_verify`,
    # streaming verified headers behind the WS `light_subscribe` route
    # (rpc/server.py hands that one to ws_light_subscribe below).

    async def _ensure_fleet(self):
        import asyncio

        from cometbft_tpu.light.fleet import LightFleet

        cfg = getattr(self.node, "config", None)
        lc = getattr(cfg, "light", None)
        if lc is None or not lc.fleet_enabled:
            raise RPCError(
                -32601, "light fleet disabled (set light.fleet_enabled)")
        if self._fleet_lock is None:
            self._fleet_lock = asyncio.Lock()
        async with self._fleet_lock:
            if self._light_fleet is not None:
                return self._light_fleet
            from cometbft_tpu.light.client import TrustOptions
            from cometbft_tpu.light.provider import NodeBackedProvider
            from cometbft_tpu.light.rpc_provider import RPCProvider

            chain_id = self.node.genesis_doc.chain_id
            provider = NodeBackedProvider(self.node)
            base = self.node.block_store.base() or 1
            try:
                root = await provider.light_block(base)
            except Exception as e:  # noqa: BLE001 - no material yet
                raise RPCError(
                    -32001, f"no light-block material to anchor the "
                            f"fleet yet: {e}") from e
            period_ns = int(lc.fleet_trust_period * 1e9)
            witnesses = [
                RPCProvider(chain_id, u.strip())
                for u in lc.fleet_witnesses.split(",") if u.strip()
            ]
            from cometbft_tpu.light.fleet import shared_cache

            fleet = LightFleet(
                chain_id, provider,
                TrustOptions(period_ns=period_ns, height=root.height,
                             hash_=root.hash()),
                witnesses=witnesses or None,
                # the per-chain shared cache: statesync seeds it before
                # the fleet exists, the fleet keeps it warm afterwards
                cache=shared_cache(
                    chain_id, capacity=lc.fleet_cache_capacity,
                    trust_period_ns=period_ns,
                    skip_base=lc.fleet_skip_base),
                cache_capacity=lc.fleet_cache_capacity,
                skip_base=lc.fleet_skip_base,
                trust_period_ns=period_ns,
                max_inflight=lc.fleet_max_inflight,
                subscriber_queue=lc.fleet_subscriber_queue,
                send_budget=lc.fleet_send_budget,
                max_subscribers=lc.fleet_max_subscribers,
                poll_interval=lc.fleet_poll_interval,
                logger=getattr(self.node, "logger", None),
            )
            await fleet.initialize()
            self._attach_head_events(fleet)
            self._light_fleet = fleet
            return fleet

    def _attach_head_events(self, fleet) -> None:
        """Event-driven head publishing (PR 11 residual): bridge the
        node's NewBlock events into fleet.notify_height so the head
        watcher wakes on commit instead of sleeping out a poll interval.
        Best-effort — a node without an event bus (inspect shims, tests)
        just leaves the fleet on the poll fallback."""
        import asyncio

        bus = getattr(self.node, "event_bus", None)
        if bus is None:
            return
        from cometbft_tpu.types import event_bus as eb

        try:
            sub = bus.subscribe("light-fleet-head", eb.QUERY_NEW_BLOCK)
        except Exception:  # noqa: BLE001 - already subscribed / no server
            return
        self._fleet_head_sub = sub

        async def _pump() -> None:
            while True:
                msg = await sub.out.get()
                if msg is None:  # cancellation wake-up
                    if sub.canceled is not None:
                        return
                    continue
                block = getattr(msg.data, "block", None)
                header = getattr(block, "header", None)
                height = getattr(header, "height", None)
                if height:
                    fleet.notify_height(int(height))

        task = asyncio.get_running_loop().create_task(
            _pump(), name="light-fleet-head-events")
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def light_verify(self, params: dict) -> dict:
        """Fleet-served skipping verification (no reference analog): the
        header at `height` verified through the shared checkpoint cache
        and coalesced in-flight bisections — thousands of concurrent
        clients asking for overlapping ranges cost one verification per
        unique height. Returns the wire-exact LightBlock proto (base64)
        plus a fleet accounting snapshot."""
        from cometbft_tpu.light.errors import LightClientError
        from cometbft_tpu.light.fleet import FleetSaturated

        fleet = await self._ensure_fleet()
        try:
            height = int(params.get("height") or 0)
        except (TypeError, ValueError) as e:
            raise RPCError(-32602, f"bad height param: {e}") from e
        if height <= 0:
            height = self.node.block_store.height()
        # optional client pin: hex hash of the validator set the client
        # expects at that height — a mismatch errors instead of serving
        pin = params.get("valset_hash") or ""
        try:
            pin_bytes = bytes.fromhex(pin) if pin else b""
        except ValueError as e:
            raise RPCError(-32602, f"bad valset_hash param (want hex): "
                                   f"{e}") from e
        try:
            lb = await fleet.verify_height(height, pin_bytes)
        except FleetSaturated as e:
            raise RPCError(-32005, str(e),
                           data=self._shed_data("light", record=True)) from e
        except LightClientError as e:
            raise RPCError(-32001, f"light verification failed: {e}") from e
        # counters() not health(): the response's accounting block must
        # be O(1) — health() sorts the latency sample buffer, which a
        # cache-hit-heavy serving load would pay on EVERY request
        return {
            "height": str(lb.height),
            "light_block": _b64(lb.to_proto()),
            "fleet": fleet.counters(),
        }

    async def ws_light_subscribe(self, req: dict, client_id: str, tasks,
                                 send_json) -> None:
        """WS half of the serving plane (rpc/server.py dispatches the
        `light_subscribe` method here): register the client with the
        fleet and pump verified headers at it until it falls behind
        (backpressure drop), spends its send budget, or disconnects."""
        from cometbft_tpu.light.fleet import FleetSaturated

        rid = req.get("id", -1)
        params = req.get("params") or {}
        try:
            fleet = await self._ensure_fleet()
        except RPCError as e:
            await send_json(_ws_err(rid, e.code, str(e)))
            return
        try:
            from_height = _int_param(params.get("from_height") or 0,
                                     "from_height")
        except RPCError as e:
            await send_json(_ws_err(rid, e.code, str(e)))
            return
        try:
            sub = fleet.subscribe(client_id, from_height)
        except FleetSaturated as e:
            await send_json(_ws_err(rid, -32005, str(e),
                                    data=self._shed_data("light",
                                                         record=True)))
            return
        tasks.spawn(self._pump_light(sub, rid, send_json),
                    name=f"light-sub-{client_id}")
        await send_json({"jsonrpc": "2.0", "id": rid, "result": {}})

    async def ws_light_unsubscribe(self, req: dict, client_id: str, _tasks,
                                   send_json) -> None:
        if self._light_fleet is not None:
            self._light_fleet.unsubscribe(client_id)
        await send_json({"jsonrpc": "2.0", "id": req.get("id", -1),
                         "result": {}})

    async def _pump_light(self, sub, rid, send_json) -> None:
        """Drain one subscription's queue onto the socket. The close
        reason is sent before the stream goes quiet (the ws_handler.go
        cancellation-notice convention)."""
        import asyncio as _aio

        from cometbft_tpu.light.fleet import SubscriptionClosed

        while True:
            try:
                lb = await sub.next()
            except SubscriptionClosed as e:
                try:
                    await send_json(_ws_err(
                        f"{rid}#header", -32000,
                        f"light subscription closed: {e.reason}"))
                except (ConnectionError, _aio.IncompleteReadError, OSError):
                    pass
                return
            await send_json({
                "jsonrpc": "2.0",
                "id": f"{rid}#header",
                "result": {
                    "height": str(lb.height),
                    "light_block": _b64(lb.to_proto()),
                },
            })

    async def ws_client_closed(self, client_id: str) -> None:
        """rpc/server.py calls this when a WS connection dies: release
        the client's fleet subscription alongside its event-bus subs."""
        if self._light_fleet is not None:
            self._light_fleet.unsubscribe(client_id)

    async def close(self) -> None:
        """Server shutdown hook: stop the fleet's head watcher (and the
        event-bus pump feeding it) so no task outlives the RPC plane."""
        if self._fleet_head_sub is not None:
            self._fleet_head_sub.cancel("rpc environment closed")
            self._fleet_head_sub = None
        if self._light_fleet is not None:
            await self._light_fleet.stop()

    async def validators(self, params: dict) -> dict:
        """rpc/core/consensus.go Validators. Unlike block queries, validator
        sets are known one block ahead (state store holds V at H+1), so an
        explicit height up to store-top+1 is valid."""
        height = None
        if params.get("height"):
            height = _int_param(params["height"], "height")
            base, top = self.node.block_store.base(), self.node.block_store.height()
            if height < base or height > top + 1:
                raise RPCError(
                    -32603, f"height {height} is not available (range {base}-{top + 1})")
        if height is None:
            vals = self.node.consensus_state.rs.validators
        else:
            vals = self.node.state_store.load_validators(height)
        if vals is None:
            raise RPCError(-32603, "validator set not available")
        return {
            "block_height": str(height or self.node.block_store.height()),
            "validators": [
                {
                    "address": _hex(v.address),
                    "pub_key": {"type": v.pub_key.type_(), "value": _b64(v.pub_key.bytes_())},
                    "voting_power": str(v.voting_power),
                    "proposer_priority": str(v.proposer_priority),
                }
                for v in vals.validators
            ],
            "count": str(len(vals.validators)),
            "total": str(len(vals.validators)),
        }

    async def consensus_state(self, _params: dict) -> dict:
        rs = self.node.consensus_state.rs
        return {"round_state": {
            "height/round/step": rs.height_round_step(),
            "height": str(rs.height), "round": rs.round_, "step": int(rs.step),
            "proposal_block_hash": _hex(rs.proposal_block.hash()) if rs.proposal_block else "",
            "locked_block_hash": _hex(rs.locked_block.hash()) if rs.locked_block else "",
            "valid_block_hash": _hex(rs.valid_block.hash()) if rs.valid_block else "",
        }}

    # ------------------------------------------------------------- abci

    async def abci_info(self, _params: dict) -> dict:
        res = await self.node.proxy_app.query.info(abci.RequestInfo())
        return {"response": {
            "data": res.data, "version": res.version,
            "app_version": str(res.app_version),
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": _b64(res.last_block_app_hash),
        }}

    async def abci_query(self, params: dict) -> dict:
        data = params.get("data", "")
        req = abci.RequestQuery(
            data=_hex_param(data, "data") if data else b"",
            path=params.get("path", ""),
            height=_int_param(params.get("height") or 0, "height"),
            prove=bool(params.get("prove", False)),
        )
        res = await self.node.proxy_app.query.query(req)
        return {"response": {
            "code": res.code, "log": res.log, "info": res.info,
            "key": _b64(res.key), "value": _b64(res.value),
            "height": str(res.height),
        }}

    # ---------------------------------------------------------- mempool

    def _tx_param(self, params: dict) -> bytes:
        tx = params.get("tx")
        if tx is None:
            raise RPCError(-32602, "missing tx param")
        if isinstance(tx, QuotedStr):
            return tx.encode()  # URI string literal: raw bytes
        if isinstance(tx, UriStr):
            return _hex_param(tx, "tx")
        try:
            # JSON body: proto3 base64
            return base64.b64decode(tx, validate=True)
        except (TypeError, ValueError):
            raise RPCError(
                -32602, "bad tx param (want base64)") from None

    async def broadcast_tx_async(self, params: dict) -> dict:
        """rpc/core/mempool.go:27: fire and forget."""
        tx = self._tx_param(params)
        import asyncio

        task = asyncio.get_running_loop().create_task(self._checktx_quiet(tx))
        # strong ref: an un-referenced task can be GC'd before it runs
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        from cometbft_tpu.mempool.mempool import tx_hash

        return {"code": 0, "data": "", "log": "", "hash": _hex(tx_hash(tx))}

    async def _checktx_quiet(self, tx: bytes) -> None:
        try:
            await self.node.mempool.check_tx(tx)
        except Exception:  # noqa: BLE001
            pass

    async def broadcast_tx_sync(self, params: dict) -> dict:
        """rpc/core/mempool.go:48: wait for CheckTx — except under
        mempool pressure, where holding the connection open across the
        ABCI round-trip is exactly the work to shed: at the elevated
        watermark the route downgrades to fire-and-forget (async
        semantics, `"deferred": true` in the result) so admission keeps
        flowing without a sync caller's latency tail."""
        import asyncio

        tx = self._tx_param(params)
        from cometbft_tpu.libs import overload as _ovl
        from cometbft_tpu.mempool.mempool import (ErrMempoolIsFull,
                                                  ErrTxInCache, tx_hash)

        reg = getattr(self.node, "overload", None)
        if reg is not None and reg.level("mempool") >= _ovl.ELEVATED:
            task = asyncio.get_running_loop().create_task(
                self._checktx_quiet(tx))
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
            return {"code": 0, "data": "",
                    "log": "mempool pressure: sync downgraded to async",
                    "deferred": True, "hash": _hex(tx_hash(tx))}
        try:
            res = await self.node.mempool.check_tx(tx)
        except ErrTxInCache:
            return {"code": 0, "data": "", "log": "tx already in cache",
                    "hash": _hex(tx_hash(tx))}
        except ErrMempoolIsFull as e:
            raise RPCError(
                -32005, str(e),
                data=self._shed_data(e.plane, e.retry_after_ms)) from e
        except Exception as e:  # noqa: BLE001
            raise RPCError(-32603, f"tx rejected: {e}") from e
        return {"code": res.code, "data": _b64(res.data), "log": res.log,
                "hash": _hex(tx_hash(tx))}

    async def broadcast_tx_commit(self, params: dict) -> dict:
        """rpc/core/mempool.go:69 BroadcastTxCommit: subscribe to the tx's
        inclusion event BEFORE CheckTx, then wait for DeliverTx (bounded by
        timeout_broadcast_tx_commit)."""
        import asyncio

        from cometbft_tpu.abci import codec as abci_codec
        from cometbft_tpu.mempool.mempool import (ErrMempoolIsFull,
                                                  ErrTxInCache, tx_hash)
        from cometbft_tpu.types import event_bus as eb

        tx = self._tx_param(params)
        h = tx_hash(tx)
        bus = self.node.event_bus
        client = f"btc-{h.hex()[:16]}-{id(params)}"
        query = f"{eb.EVENT_TYPE_KEY} = '{eb.EVENT_TX}' AND {eb.TX_HASH_KEY} = '{h.hex().upper()}'"
        sub = bus.subscribe(client, query, capacity=1)
        try:
            try:
                check = await self.node.mempool.check_tx(tx)
            except ErrTxInCache:
                raise RPCError(-32603, "tx already exists in cache") from None
            except ErrMempoolIsFull as e:
                raise RPCError(
                    -32005, str(e),
                    data=self._shed_data(e.plane, e.retry_after_ms)) from e
            except Exception as e:  # noqa: BLE001
                raise RPCError(-32603, f"error on broadcastTxCommit: {e}") from e
            check_dict = {"code": check.code, "data": _b64(check.data),
                          "log": check.log}
            if check.code != 0:
                return {"check_tx": check_dict, "tx_result": {},
                        "hash": _hex(h), "height": "0"}
            timeout = self.node.config.rpc.timeout_broadcast_tx_commit
            try:
                msg = await asyncio.wait_for(sub.out.get(), timeout)
            except asyncio.TimeoutError:
                raise RPCError(
                    -32603, "timed out waiting for tx to be included in a block"
                ) from None
            if msg is None:
                raise RPCError(-32603, f"subscription canceled: {sub.canceled}")
            d = msg.data  # EventDataTx
            return {
                "check_tx": check_dict,
                "tx_result": abci_codec._to_jsonable(d.result),
                "hash": _hex(h),
                "height": str(d.height),
            }
        finally:
            try:
                bus.unsubscribe_all(client)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------ tx query

    async def tx(self, params: dict) -> dict:
        """rpc/core/tx.go Tx: look up a committed tx by hash."""
        from cometbft_tpu.abci import codec as abci_codec

        h = params.get("hash", "")
        raw = _hex_param(h, "hash") if isinstance(h, str) else h
        res = self.node.tx_indexer.get(raw)
        if res is None:
            raise RPCError(-32603, f"tx ({h}) not found")
        return {
            "hash": _hex(raw), "height": str(res.height), "index": res.index,
            "tx_result": abci_codec._to_jsonable(res.result), "tx": _b64(res.tx),
        }

    async def tx_search(self, params: dict) -> dict:
        """rpc/core/tx.go TxSearch over the KV indexer."""
        from cometbft_tpu.abci import codec as abci_codec
        from cometbft_tpu.types.block import tx_hash

        query = params.get("query", "")
        if not query:
            raise RPCError(-32602, "missing query param")
        limit = _int_param(params.get("per_page") or 30, "per_page")
        try:
            results = self.node.tx_indexer.search(query, limit=limit)
        except Exception as e:  # noqa: BLE001
            raise RPCError(-32602, f"bad query: {e}") from e
        return {
            "txs": [
                {"hash": _hex(tx_hash(r.tx)), "height": str(r.height),
                 "index": r.index, "tx_result": abci_codec._to_jsonable(r.result),
                 "tx": _b64(r.tx)}
                for r in results
            ],
            "total_count": str(len(results)),
        }

    async def block_search(self, params: dict) -> dict:
        """rpc/core/blocks.go BlockSearch over the block indexer."""
        query = params.get("query", "")
        if not query:
            raise RPCError(-32602, "missing query param")
        if self.node.block_indexer is None:
            raise RPCError(-32603, "block indexing disabled")
        try:
            heights = self.node.block_indexer.search(
                query, limit=_int_param(params.get("per_page") or 30,
                                        "per_page"))
        except Exception as e:  # noqa: BLE001
            raise RPCError(-32602, f"bad query: {e}") from e
        blocks = []
        for h in heights:
            blk = self.node.block_store.load_block(h)
            if blk is not None:
                blocks.append({"block_id": {"hash": _hex(blk.hash())},
                               "block": self._block_dict(blk)})
        return {"blocks": blocks, "total_count": str(len(blocks))}

    async def unconfirmed_txs(self, params: dict) -> dict:
        limit = _int_param(params.get("limit") or 30, "limit")
        txs = self.node.mempool.reap_max_txs(limit)
        return {
            "n_txs": str(len(txs)),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
            "txs": [_b64(tx) for tx in txs],
        }

    async def num_unconfirmed_txs(self, _params: dict) -> dict:
        return {
            "n_txs": str(self.node.mempool.size()),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
        }

    # --------------------------------------------------------- evidence

    async def broadcast_evidence(self, params: dict) -> dict:
        from cometbft_tpu.types.evidence import evidence_list_from_proto

        evs = evidence_list_from_proto(
            _hex_param(params.get("evidence"), "evidence"))
        for ev in evs:
            self.node.evidence_pool.add_evidence(ev)
        return {"hash": _hex(evs[0].hash()) if evs else ""}

    async def trace_dump(self, params: dict) -> dict:
        """Flight-recorder dump (libs/trace.py, no reference analog):
        the verify-plane span ring as Chrome trace-event JSON — save the
        `chrome_trace` value to a file and load it at ui.perfetto.dev —
        plus the rolling wall-time attribution. `format=spans` returns
        the raw span records instead (the attribution-model input);
        `slow=true` appends the slow-batch capture ring (full span trees
        of batches/heights that blew the latency budget). Served in
        inspect mode too: the tracer is process-global, so a post-mortem
        over a crashed node's home can still read what the dying process
        wrote if inspect runs in-process (e.g. tests)."""
        import asyncio

        from cometbft_tpu.libs import trace

        fmt = str(params.get("format", "chrome") or "chrome")
        out: dict = {
            "enabled": trace.enabled(),
            "spans_dropped": trace.dropped(),
            "attribution": trace.attribution(),
        }
        # rendering a full 64k-span ring to dicts costs tens of ms —
        # push it off the event loop consensus coroutines share, so
        # pulling a dump doesn't inject the latency spike being debugged
        loop = asyncio.get_running_loop()
        if fmt == "spans":
            out["spans"] = await loop.run_in_executor(None, trace.snapshot)
        elif fmt == "chrome":
            out["chrome_trace"] = await loop.run_in_executor(
                None, trace.chrome_trace)
        else:
            raise RPCError(-32602, f"unknown trace_dump format {fmt!r}"
                                   " (want chrome|spans)")
        if self._bool_param(params.get("slow", False)):
            out["slow_captures"] = await loop.run_in_executor(
                None, trace.slow_captures)
        return out

    async def consensus_timeline(self, params: dict) -> dict:
        """Per-height consensus phase timeline (no reference analog):
        the node's bounded heightline ring — one record per recent height
        with mono+wall timestamps for every critical-path event (proposal
        sent/received, first block part, proposal complete, prevote
        first/⅓/⅔, precommit quorum, commit, ABCI apply done) plus
        per-peer vote-arrival lag — and the per-peer clock-skew estimates
        needed to align timelines across nodes. `cometbft heightline`
        pulls this from a fleet and renders skew-corrected per-height
        anatomy. `min_height`/`limit` bound the response."""
        from cometbft_tpu.consensus import timeline
        from cometbft_tpu.libs import linkmodel

        min_height = _int_param(params.get("min_height", 0) or 0, "min_height")
        limit = _int_param(params.get("limit", 0) or 0, "limit")
        cs = getattr(self.node, "consensus_state", None)
        rec = getattr(cs, "timeline", None)
        node_key = getattr(self.node, "node_key", None)
        node_info = getattr(self.node, "node_info", None)
        cfg = getattr(self.node, "config", None)
        inst = getattr(cfg, "instrumentation", None)
        import time as _time
        return {
            "node_id": node_key.id() if node_key is not None else "",
            "moniker": node_info.moniker if node_info is not None else "",
            "now_wall_ns": _time.time_ns(),
            "enabled": timeline.enabled(),
            "height_slow_ms": (getattr(inst, "height_slow_ms", 0.0)
                               if inst is not None else 0.0),
            "heights": (rec.snapshot(min_height=min_height, limit=limit)
                        if rec is not None else []),
            "skew": linkmodel.skew().snapshot(),
        }

    async def postmortems(self, params: dict) -> dict:
        """Slow-height postmortem bundles (no reference analog): heights
        whose wall time exceeded instrumentation.height_slow_ms each
        auto-captured one bounded bundle (timeline, span tree, gossip
        accounting, wire-counter deltas, scheduler/crypto health). No
        `height` param lists capture summaries; `height=N` returns the
        full bundle for that height or errors if none was captured."""
        cs = getattr(self.node, "consensus_state", None)
        rec = getattr(cs, "timeline", None)
        node_key = getattr(self.node, "node_key", None)
        out: dict = {
            "node_id": node_key.id() if node_key is not None else "",
            "captures": rec.postmortems() if rec is not None else [],
        }
        h = params.get("height")
        if h is not None:
            bundle = (rec.postmortem(_int_param(h, "height"))
                      if rec is not None else None)
            if bundle is None:
                raise RPCError(
                    -32603, f"no postmortem captured for height {h}")
            out["postmortem"] = bundle
        return out

    # ------------------------------------------------------ unsafe routes

    @staticmethod
    def _addr_list(value) -> list[str]:
        """JSON body sends a real list; the URI handler sends one string
        (comma-separated) — list() on a str would explode it into
        characters."""
        if isinstance(value, str):
            return [a for a in value.split(",") if a]
        return [str(a) for a in (value or [])]

    @staticmethod
    def _bool_param(value) -> bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "t", "yes")
        return bool(value)

    async def unsafe_dial_seeds(self, params: dict) -> dict:
        """rpc/core/net.go:42 UnsafeDialSeeds."""
        seeds = self._addr_list(params.get("seeds"))
        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        await self.node.switch.dial_peers_async(seeds)
        return {"log": f"dialing seeds: {seeds}"}

    async def unsafe_dial_peers(self, params: dict) -> dict:
        """rpc/core/net.go:55 UnsafeDialPeers."""
        peers = self._addr_list(params.get("peers"))
        if not peers:
            raise RPCError(-32602, "no peers provided")
        persistent = self._bool_param(params.get("persistent", False))
        await self.node.switch.dial_peers_async(peers, persistent=persistent)
        return {"log": f"dialing peers: {peers}"}

    async def unsafe_flush_mempool(self, _params: dict) -> dict:
        await self.node.mempool.flush()
        return {}

    async def unsafe_disconnect_peers(self, _params: dict) -> dict:
        """Framework extension (the e2e 'disconnect' perturbation,
        test/e2e/runner/perturb.go:44-100 severs the container network;
        process-level nets sever here instead): drop every current peer
        conn. Persistent peers redial on their own backoff."""
        sw = self.node.switch
        peers = list(sw.peers.values())
        for p in peers:
            # operator action, not peer misbehavior: never score it
            await sw.stop_peer_for_error(p, "unsafe_disconnect_peers", score=0.0)
        return {"disconnected": len(peers)}

    async def unsafe_net_chaos(self, params: dict) -> dict:
        """Framework extension (the e2e 'partition' perturbation): arm or
        heal the process-global net-chaos registry at runtime. `spec` uses
        the CBFT_NET_CHAOS syntax (p2p/netchaos.py); `heal` clears the
        partition map (starting the heal clock); `clear` resets everything."""
        from cometbft_tpu.p2p import netchaos

        if self._bool_param(params.get("clear", False)):
            netchaos.reset()
            return {"net_chaos": netchaos.snapshot()}
        spec = str(params.get("spec", "") or "")
        if spec:
            netchaos.arm_spec(spec)
        if self._bool_param(params.get("heal", False)):
            netchaos.clear_partition()
        return {"net_chaos": netchaos.snapshot()}

    async def unsafe_disk_chaos(self, params: dict) -> dict:
        """Framework extension (the e2e disk-fault perturbations): arm or
        clear the process-global disk-chaos registry at runtime. `spec`
        uses the CBFT_DISK_CHAOS syntax (libs/diskchaos.py); `clear`
        resets everything."""
        from cometbft_tpu.libs import diskchaos

        if self._bool_param(params.get("clear", False)):
            diskchaos.reset()
            return {"disk_chaos": diskchaos.snapshot()}
        spec = str(params.get("spec", "") or "")
        if spec:
            try:
                diskchaos.arm_spec(spec)
            except ValueError as e:
                raise RPCError(-32602, str(e)) from None
        return {"disk_chaos": diskchaos.snapshot()}

    # ------------------------------------------------------------ table

    def routes(self) -> dict:
        """routes.go:12-56 (+ AddUnsafeRoutes when config.rpc.unsafe)."""
        table = self._routes_table()
        cfg = getattr(self.node, "config", None)
        if cfg is not None and getattr(cfg.rpc, "unsafe", False):
            table.update({
                "dial_seeds": self.unsafe_dial_seeds,
                "dial_peers": self.unsafe_dial_peers,
                "unsafe_flush_mempool": self.unsafe_flush_mempool,
                "unsafe_disconnect_peers": self.unsafe_disconnect_peers,
                "unsafe_net_chaos": self.unsafe_net_chaos,
                "unsafe_disk_chaos": self.unsafe_disk_chaos,
            })
        return table

    def _routes_table(self) -> dict:
        return {
            "health": self.health,
            "crypto_health": self.crypto_health,
            "storage_health": self.storage_health,
            "trace_dump": self.trace_dump,
            "consensus_timeline": self.consensus_timeline,
            "postmortems": self.postmortems,
            "status": self.status,
            "net_info": self.net_info,
            "net_telemetry": self.net_telemetry,
            "genesis": self.genesis,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "blockchain": self.blockchain,
            "commit": self.commit,
            "consensus_params": self.consensus_params,
            "dump_consensus_state": self.dump_consensus_state,
            "check_tx": self.check_tx,
            "genesis_chunked": self.genesis_chunked,
            "light_block": self.light_block,
            "light_verify": self.light_verify,
            "commit_certificate": self.commit_certificate,
            "validators": self.validators,
            "consensus_state": self.consensus_state,
            "abci_info": self.abci_info,
            "abci_query": self.abci_query,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "broadcast_evidence": self.broadcast_evidence,
        }
