"""Load generation + transaction-latency reporting.

Reference: test/loadtime — `load` stamps each generated transaction with
its creation time plus the load parameters (connections, rate, size) and
broadcasts it; `report` walks committed blocks and computes each stamped
tx's latency as block_time - tx_time, aggregating min/max/avg/stddev/
percentiles per experiment (test/loadtime/payload/payload.go,
test/loadtime/report/report.go:20-120).

Payload wire format here is JSON (prefix-tagged, zero-padded to the
requested size); the report accepts either a live RPC endpoint or a
BlockStore. The CLI surface is `cometbft_tpu loadtime run|report`.
"""

from __future__ import annotations

import asyncio
import base64
import json
import secrets
import statistics
import time
import urllib.request
from dataclasses import dataclass, field

PREFIX = b"ldtm:"


def make_tx(experiment_id: str, seq: int, size: int, rate: float,
            connections: int) -> bytes:
    """payload.go NewBytes: stamp creation time + load parameters, pad to
    `size` bytes so tx bytes/block dynamics match the experiment."""
    doc = {
        "id": experiment_id,
        "seq": seq,
        "time_ns": time.time_ns(),
        "rate": rate,
        "conns": connections,
        "size": size,
    }
    body = PREFIX + json.dumps(doc, separators=(",", ":")).encode()
    if len(body) < size:
        pad = size - len(body) - 1
        body += b"/" + secrets.token_hex((pad + 1) // 2).encode()[:pad]
    return body


def parse_tx(tx: bytes) -> dict | None:
    if not tx.startswith(PREFIX):
        return None
    raw = tx[len(PREFIX):]
    end = raw.rfind(b"/")
    if end != -1:
        candidate = raw[:end]
    else:
        candidate = raw
    try:
        return json.loads(candidate)
    except ValueError:
        try:
            return json.loads(raw)
        except ValueError:
            return None


@dataclass
class LoadResult:
    sent: int = 0
    accepted: int = 0
    rejected: int = 0
    errors: int = 0


async def generate_load(
    endpoints: list[str],
    rate: float,
    duration: float,
    size: int = 256,
    experiment_id: str = "",
    method: str = "broadcast_tx_async",
) -> tuple[str, LoadResult]:
    """Drive `rate` tx/s across the endpoints for `duration` seconds
    (round-robin). Posts run CONCURRENTLY (bounded in-flight pool) so the
    achieved rate is not capped at 1/RTT — the reference's tm-load-test
    connections behave the same way."""
    if not endpoints:
        raise ValueError("loadtime: at least one RPC endpoint is required")
    experiment_id = experiment_id or secrets.token_hex(8)
    res = LoadResult()
    interval = 1.0 / rate if rate > 0 else 0.01
    deadline = time.monotonic() + duration
    seq = 0
    sem = asyncio.Semaphore(64)

    def post(url: str, tx: bytes) -> bool:
        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": method,
            "params": {"tx": base64.b64encode(tx).decode()},
        }).encode()
        req = urllib.request.Request(
            url + "/", data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.load(r)
        return "error" not in doc and int(doc["result"].get("code", 0)) == 0

    async def send_one(url: str, tx: bytes) -> None:
        async with sem:
            try:
                ok = await asyncio.to_thread(post, url, tx)
                if ok:
                    res.accepted += 1
                else:
                    res.rejected += 1
            except Exception:  # noqa: BLE001 - endpoint hiccups count as errors
                res.errors += 1

    tasks: list[asyncio.Task] = []
    next_at = time.monotonic()
    while time.monotonic() < deadline:
        tx = make_tx(experiment_id, seq, size, rate, len(endpoints))
        url = endpoints[seq % len(endpoints)]
        seq += 1
        res.sent += 1
        tasks.append(asyncio.create_task(send_one(url, tx)))
        next_at += interval
        delay = next_at - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
    await asyncio.gather(*tasks)
    return experiment_id, res


async def generate_saturation(
    submit,
    waves: int,
    wave_size: int,
    size: int = 256,
    experiment_id: str = "",
    interval: float = 0.0,
    rate_hint: float = 0.0,
    max_inflight: int = 0,
) -> tuple[str, LoadResult]:
    """Saturation-wave generator for the overload plane: where
    generate_load paces to a target rate, each wave here fires
    `wave_size` submissions CONCURRENTLY and waits them all out — the
    point is to exceed the admission ceiling, not to hold a rate. The
    `submit` callable (async, tx -> bool) abstracts the path: the
    in-proc soak harness hands mempool.check_tx, e2e hands
    rpc_submitter(). True = accepted, False = shed/rejected,
    raise = transport error.

    `max_inflight` bounds CONCURRENT submissions (0 = unbounded). The
    in-proc soak must set this: it calls mempool.check_tx directly,
    bypassing the RPC server's in-flight budget, and an unbounded wave
    of thousands of tasks on the shared event loop starves the very
    consensus coroutines the soak is grading — a failure mode the RPC
    guard makes impossible over the wire. Mirror the write budget
    (rpc config overload_write_inflight) here."""
    experiment_id = experiment_id or secrets.token_hex(8)
    res = LoadResult()
    seq = 0
    sem = asyncio.Semaphore(max_inflight) if max_inflight > 0 else None

    async def one(tx: bytes) -> None:
        try:
            if await submit(tx):
                res.accepted += 1
            else:
                res.rejected += 1
        except Exception:  # noqa: BLE001 - transport hiccups count as errors
            res.errors += 1
        finally:
            if sem is not None:
                sem.release()

    for _ in range(waves):
        tasks = []
        for _ in range(wave_size):
            tx = make_tx(experiment_id, seq, size, rate_hint, 1)
            seq += 1
            res.sent += 1
            if sem is not None:
                await sem.acquire()
            tasks.append(asyncio.create_task(one(tx)))
        await asyncio.gather(*tasks)
        if interval > 0:
            await asyncio.sleep(interval)
    return experiment_id, res


def rpc_submitter(endpoint: str, method: str = "broadcast_tx_sync"):
    """An HTTP `submit` callable for generate_saturation: POST one tx,
    classify any JSON-RPC error (the unified -32005 overload shed
    included) as a rejection, transport failures raise (counted as
    errors by the generator)."""

    def post(tx: bytes) -> bool:
        body = json.dumps({
            "jsonrpc": "2.0", "id": 1, "method": method,
            "params": {"tx": base64.b64encode(tx).decode()},
        }).encode()
        req = urllib.request.Request(
            endpoint + "/", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.load(r)
        if "error" in doc:
            return False
        return int(doc["result"].get("code", 0)) == 0

    async def submit(tx: bytes) -> bool:
        return await asyncio.to_thread(post, tx)

    return submit


# ---------------------------------------------------------------- report


@dataclass
class Report:
    """report.go:20-120 Report: latency stats for one experiment id."""

    experiment_id: str
    txs: int = 0
    negative: int = 0
    all_latencies_s: list[float] = field(default_factory=list)

    def add(self, latency_s: float) -> None:
        self.txs += 1
        if latency_s < 0:
            self.negative += 1
        self.all_latencies_s.append(latency_s)

    def stats(self) -> dict:
        lat = sorted(self.all_latencies_s)
        if not lat:
            return {"experiment_id": self.experiment_id, "txs": 0}

        def pct(q: float) -> float:
            # nearest-rank: ceil(n*q)-th smallest (1-indexed)
            import math

            return lat[max(0, math.ceil(len(lat) * q) - 1)]

        return {
            "experiment_id": self.experiment_id,
            "txs": self.txs,
            "negative_latencies": self.negative,
            "min_s": round(lat[0], 4),
            "max_s": round(lat[-1], 4),
            "avg_s": round(statistics.fmean(lat), 4),
            "stddev_s": round(statistics.pstdev(lat), 4) if len(lat) > 1 else 0.0,
            "p50_s": round(pct(0.50), 4),
            "p95_s": round(pct(0.95), 4),
            "p99_s": round(pct(0.99), 4),
        }


def report_from_blocks(blocks) -> dict[str, Report]:
    """blocks: iterable of (block_time_ns, [tx bytes]) — per-experiment
    latency = block time - stamped creation time (report.go Load)."""
    out: dict[str, Report] = {}
    for block_time_ns, txs in blocks:
        for tx in txs:
            doc = parse_tx(tx)
            if doc is None:
                continue
            rep = out.setdefault(str(doc.get("id")), Report(str(doc.get("id"))))
            rep.add((block_time_ns - int(doc["time_ns"])) / 1e9)
    return out


def blocks_from_store(block_store, from_height: int = 0, to_height: int = 0):
    base = max(block_store.base(), from_height or 1)
    top = min(block_store.height(), to_height or block_store.height())
    for h in range(base, top + 1):
        block = block_store.load_block(h)
        if block is not None:
            yield block.header.time.unix_ns(), list(block.data.txs)


def blocks_from_rpc(url: str, from_height: int = 0, to_height: int = 0):
    """Walk committed blocks over the RPC surface (report-without-disk),
    on ONE keep-alive connection — a conn-per-height walk over hundreds of
    heights churns sockets for no reason."""
    import http.client
    from urllib.parse import urlparse

    parsed = urlparse(url if "//" in url else "http://" + url)
    conn_box = [http.client.HTTPConnection(
        parsed.hostname, parsed.port, timeout=10)]

    def get(route):
        last = None
        for _ in range(3):  # reconnect retries: the node may be mid-commit
            try:
                conn_box[0].request("GET", "/" + route)
                resp = conn_box[0].getresponse()
                doc = json.loads(resp.read())
                # error replies (e.g. the height raced the pruner) are a
                # skip, not an abort
                return doc.get("result")
            except (OSError, http.client.HTTPException) as e:  # noqa: PERF203
                last = e
                conn_box[0].close()
                conn_box[0] = http.client.HTTPConnection(
                    parsed.hostname, parsed.port, timeout=10)
        raise last

    status = get("status")["sync_info"]
    base = max(int(status["earliest_block_height"]), from_height or 1)
    top = min(int(status["latest_block_height"]), to_height or 1 << 62)
    from datetime import datetime, timezone

    for h in range(base, top + 1):
        got = get(f"block?height={h}")
        if got is None:  # pruned/unavailable height: skip
            continue
        blk = got["block"]
        t = blk["header"]["time"]  # RFC3339Nano (cmttime.Timestamp.rfc3339)
        body, _, frac = t.rstrip("Z").partition(".")
        dt = datetime.strptime(body, "%Y-%m-%dT%H:%M:%S").replace(
            tzinfo=timezone.utc)
        ns = int(dt.timestamp()) * 10**9 + int((frac or "0").ljust(9, "0")[:9])
        yield ns, [base64.b64decode(x) for x in blk["data"]["txs"]]
