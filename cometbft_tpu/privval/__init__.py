"""Validator key custody (reference: privval/).

FilePV: file-backed signer with height/round/step double-sign protection
(privval/file.go:100 CheckHRS). Remote signer protocol in signer.py.
"""

from cometbft_tpu.privval.file_pv import FilePV, PrivValidator  # noqa: F401
