"""Remote-signer privval over a socket (reference: privval/
signer_listener_endpoint.go:30, signer_dialer_endpoint.go,
signer_client.go, signer_server.go).

Topology mirrors the reference: the NODE LISTENS on
priv_validator_laddr; the SIGNER (the machine holding the key) DIALS IN —
the key holder initiates, so the node never needs credentials to reach the
HSM box. Once connected:

  node --(SignVoteRequest/SignProposalRequest/PubKeyRequest/Ping)--> signer
  signer --(Signed*Response | error)--> node

The consensus engine's PrivValidator interface is synchronous, so
SignerClient speaks blocking sockets with deadlines (signing is on the
consensus actor and sub-millisecond on the wire); SignerServer runs a
plain thread loop around a FilePV — the double-sign guard lives WITH the
key, exactly like the reference's remote signer.

Wire format: 4-byte big-endian length prefix + a oneof-tagged protobuf
message (proto/tendermint/privval/types.proto shape, hand-rolled like the
rest of the framework's codecs)."""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from cometbft_tpu import crypto
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.privval.file_pv import ErrDoubleSign, PrivValidator
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validator import pub_key_from_proto, pub_key_to_proto
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import protobuf as pb

_PUBKEY_REQ = 1
_PUBKEY_RESP = 2
_SIGN_VOTE_REQ = 3
_SIGNED_VOTE_RESP = 4
_SIGN_PROPOSAL_REQ = 5
_SIGNED_PROPOSAL_RESP = 6
_PING_REQ = 7
_PING_RESP = 8

_MAX_MSG = 1 << 20


def _frame(tag: int, body: bytes) -> bytes:
    w = pb.Writer()
    w.message(tag, body, always=True)
    out = w.output()
    return struct.pack(">I", len(out)) + out


def _read_frame(sock: socket.socket) -> tuple[int, bytes]:
    hdr = _recv_exact(sock, 4)
    (ln,) = struct.unpack(">I", hdr)
    if ln > _MAX_MSG:
        raise ConnectionError(f"privval frame too large ({ln})")
    data = _recv_exact(sock, ln)
    r = pb.Reader(data)
    tag, _ = r.read_tag()
    return tag, r.read_bytes()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("privval connection closed")
        buf += part
    return buf


def _err_body(chain_id: str, payload: bytes, err: str,
              sign_extension: bool = False) -> bytes:
    w = pb.Writer()
    if payload:
        w.bytes(1, payload)
    w.string(2, err)
    w.string(3, chain_id)
    if sign_extension:
        w.uvarint(4, 1)
    return w.output()


def _parse_body(body: bytes) -> tuple[bytes, str, str, bool]:
    payload, err, chain_id, sign_ext = b"", "", "", False
    r = pb.Reader(body)
    while not r.at_end():
        f, wt = r.read_tag()
        if f == 1:
            payload = r.read_bytes()
        elif f == 2:
            err = r.read_string()
        elif f == 3:
            chain_id = r.read_string()
        elif f == 4:
            sign_ext = bool(r.read_uvarint())
        else:
            r.skip(wt)
    return payload, err, chain_id, sign_ext


class RemoteSignerError(Exception):
    pass


class SignerServer:
    """The key-holder side (signer_server.go + signer_dialer_endpoint.go):
    dials the node's priv_validator_laddr and answers sign requests from
    its local FilePV (double-sign guard enforced here, with the key)."""

    def __init__(self, pv: PrivValidator, addr: tuple[str, int],
                 logger: cmtlog.Logger | None = None,
                 retries: int = 10, retry_delay: float = 0.2):
        self.pv = pv
        self.addr = addr
        self.logger = logger or cmtlog.nop()
        self.retries = retries
        self.retry_delay = retry_delay
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="signer-server")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _dial(self) -> socket.socket:
        import time

        last: Exception | None = None
        for _ in range(self.retries):
            if self._stop.is_set():
                raise ConnectionError("signer stopped")
            try:
                s = socket.create_connection(self.addr, timeout=3.0)
                s.settimeout(None)
                return s
            except OSError as e:
                last = e
                time.sleep(self.retry_delay)
        raise ConnectionError(f"signer could not reach node: {last}")

    def _run(self) -> None:
        try:
            self._sock = self._dial()
        except ConnectionError as e:
            self.logger.error("signer dial failed", err=str(e))
            return
        sock = self._sock
        while not self._stop.is_set():
            try:
                tag, body = _read_frame(sock)
            except (ConnectionError, OSError):
                return
            try:
                resp = self._handle(tag, body)
            except Exception as e:  # noqa: BLE001 - never kill the loop
                self.logger.error("signer request failed", err=str(e))
                resp = _frame(tag + 1, _err_body("", b"", str(e)))
            try:
                sock.sendall(resp)
            except (ConnectionError, OSError):
                return

    def _handle(self, tag: int, body: bytes) -> bytes:
        payload, _, chain_id, sign_ext = _parse_body(body)
        if tag == _PING_REQ:
            return _frame(_PING_RESP, b"")
        if tag == _PUBKEY_REQ:
            return _frame(_PUBKEY_RESP,
                          _err_body(chain_id, pub_key_to_proto(self.pv.get_pub_key()), ""))
        if tag == _SIGN_VOTE_REQ:
            vote = Vote.from_proto(payload)
            try:
                self.pv.sign_vote(chain_id, vote, sign_extension=sign_ext)
            except ErrDoubleSign as e:
                return _frame(_SIGNED_VOTE_RESP, _err_body(chain_id, b"", str(e)))
            return _frame(_SIGNED_VOTE_RESP, _err_body(chain_id, vote.to_proto(), ""))
        if tag == _SIGN_PROPOSAL_REQ:
            proposal = Proposal.from_proto(payload)
            try:
                self.pv.sign_proposal(chain_id, proposal)
            except ErrDoubleSign as e:
                return _frame(_SIGNED_PROPOSAL_RESP, _err_body(chain_id, b"", str(e)))
            return _frame(_SIGNED_PROPOSAL_RESP,
                          _err_body(chain_id, proposal.to_proto(), ""))
        raise RemoteSignerError(f"unknown privval request tag {tag}")


class SignerClient(PrivValidator):
    """The node side (signer_listener_endpoint.go:30 + signer_client.go):
    listen for the signer's dial-in, then satisfy the PrivValidator
    interface by round-tripping every signing request."""

    def __init__(self, laddr: tuple[str, int], timeout: float = 5.0,
                 accept_timeout: float = 15.0):
        self._listener = socket.create_server(laddr)
        self._listener.settimeout(accept_timeout)
        self.laddr = self._listener.getsockname()
        self.timeout = timeout
        self._conn: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._pub: Optional[crypto.PubKey] = None

    def accept(self) -> None:
        """Block until the remote signer dials in."""
        conn, _ = self._listener.accept()
        conn.settimeout(self.timeout)
        self._conn = conn

    def close(self) -> None:
        for s in (self._conn, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _round_trip(self, tag: int, body: bytes) -> bytes:
        if self._conn is None:
            raise RemoteSignerError("no signer connected")
        with self._lock:
            self._conn.sendall(_frame(tag, body))
            resp_tag, resp_body = _read_frame(self._conn)
        if resp_tag != tag + 1:
            raise RemoteSignerError(
                f"privval response tag {resp_tag}, want {tag + 1}")
        payload, err, _, _ = _parse_body(resp_body)
        if err:
            if "conflicting data" in err or "double sign" in err:
                raise ErrDoubleSign(err)
            raise RemoteSignerError(err)
        return payload

    def ping(self) -> None:
        self._round_trip(_PING_REQ, b"")

    def get_pub_key(self) -> crypto.PubKey:
        if self._pub is None:
            payload = self._round_trip(_PUBKEY_REQ, _err_body("", b"", ""))
            self._pub = pub_key_from_proto(payload)
        return self._pub

    def sign_vote(self, chain_id: str, vote: Vote, sign_extension: bool = False) -> None:
        payload = self._round_trip(
            _SIGN_VOTE_REQ,
            _err_body(chain_id, vote.to_proto(), "", sign_extension=sign_extension))
        signed = Vote.from_proto(payload)
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        payload = self._round_trip(
            _SIGN_PROPOSAL_REQ, _err_body(chain_id, proposal.to_proto(), ""))
        signed = Proposal.from_proto(payload)
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp
