"""FilePV — file-backed validator signer with double-sign protection.

Reference: privval/file.go. Key file holds the private key (written once);
state file tracks (height, round, step, signature, sign_bytes) and refuses
to sign conflicting messages at the same HRS (file.go:100 CheckHRS) —
signing twice at one HRS is the equivocation the evidence subsystem exists
to punish, so the signer is the last line of defense.

Step ordering within a round: propose(1) < prevote(2) < precommit(3).
Re-signing the SAME bytes at the same HRS returns the cached signature
(needed after a crash-restart mid-step); differing bytes that differ only
in timestamp also re-sign with the cached signature (file.go:280-320 —
the reference tolerates timestamp regeneration).
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass

from cometbft_tpu import crypto
from cometbft_tpu.crypto import bls12381, ed25519
from cometbft_tpu.libs import diskio, fail
from cometbft_tpu.types.basic import SignedMsgType
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import protobuf as pb

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {
    SignedMsgType.PREVOTE: STEP_PREVOTE,
    SignedMsgType.PRECOMMIT: STEP_PRECOMMIT,
}


class ErrDoubleSign(Exception):
    pass


class PrivValidator:
    """The signing interface consensus programs against
    (types/priv_validator.go)."""

    def get_pub_key(self) -> crypto.PubKey:
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote, sign_extension: bool = False) -> None:
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        raise NotImplementedError


def _atomic_write(path: str, data: bytes, site: str | None = None) -> None:
    """FULL-grade durability: temp-file fsync AND directory fsync after
    the rename (libs/diskio.durable_replace). The sign-state is the one
    write whose loss enables a double-sign — a bare os.replace left the
    rename in the un-fsynced directory, where power loss could resurrect
    the OLD sign state with the new signature already on the wire."""
    diskio.atomic_write_durable(path, data, site=site)


@dataclass
class _LastSignState:
    height: int = 0
    round_: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """file.go:100-135: returns True if this exact HRS was signed before
        (caller may reuse); raises on regression."""
        if self.height > height:
            raise ErrDoubleSign(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round_ > round_:
                raise ErrDoubleSign(f"round regression at height {height}. Got {round_}, last round {self.round_}")
            if self.round_ == round_:
                if self.step > step:
                    raise ErrDoubleSign(
                        f"step regression at height {height} round {round_}. Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise ErrDoubleSign("no sign_bytes but HRS matches")
                    return True
        return False


class FilePV(PrivValidator):
    def __init__(self, priv_key: crypto.PrivKey, key_file: str = "", state_file: str = ""):
        self.priv_key = priv_key
        self.key_file = key_file
        self.state_file = state_file
        self.last_sign_state = _LastSignState()
        if state_file and os.path.exists(state_file):
            self._load_state()

    # --------------------------------------------------------- file I/O

    # Amino-style JSON tags per key scheme. Ed25519 persists only the
    # 32-byte seed (reference file format); BLS persists the scalar.
    _KEY_CODECS = {
        ed25519.KEY_TYPE: (
            "tendermint/PubKeyEd25519", "tendermint/PrivKeyEd25519",
            lambda priv: priv.bytes_()[:32],
        ),
        bls12381.KEY_TYPE: (
            "cometbft/PubKeyBls12_381", "cometbft/PrivKeyBls12_381",
            lambda priv: priv.bytes_(),
        ),
    }
    _PRIV_DECODERS = {
        "tendermint/PrivKeyEd25519": ed25519.PrivKey,
        "cometbft/PrivKeyBls12_381": bls12381.PrivKey,
    }

    @classmethod
    def generate(cls, key_file: str = "", state_file: str = "",
                 key_type: str = ed25519.KEY_TYPE) -> "FilePV":
        if key_type == bls12381.KEY_TYPE:
            priv: crypto.PrivKey = bls12381.gen_priv_key()
        elif key_type == ed25519.KEY_TYPE:
            priv = ed25519.gen_priv_key()
        else:
            raise ValueError(f"FilePV.generate: unsupported key type {key_type!r}")
        pv = cls(priv, key_file, state_file)
        if key_file:
            pv.save_key()
        return pv

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        with open(key_file) as f:
            doc = json.load(f)
        ctor = cls._PRIV_DECODERS.get(
            doc["priv_key"].get("type", "tendermint/PrivKeyEd25519"),
            ed25519.PrivKey,
        )
        priv = ctor(base64.b64decode(doc["priv_key"]["value"]))
        return cls(priv, key_file, state_file)

    @classmethod
    def load_or_generate(cls, key_file: str, state_file: str,
                         key_type: str = ed25519.KEY_TYPE) -> "FilePV":
        if os.path.exists(key_file):
            return cls.load(key_file, state_file)
        pv = cls.generate(key_file, state_file, key_type=key_type)
        return pv

    def save_key(self) -> None:
        pub = self.priv_key.pub_key()
        pub_tag, priv_tag, priv_enc = self._KEY_CODECS[self.priv_key.type_()]
        doc = {
            "address": pub.address().hex().upper(),
            "pub_key": {"type": pub_tag,
                        "value": base64.b64encode(pub.bytes_()).decode()},
            "priv_key": {"type": priv_tag,
                         "value": base64.b64encode(priv_enc(self.priv_key)).decode()},
        }
        _atomic_write(self.key_file, json.dumps(doc, indent=2).encode())

    def _save_state(self) -> None:
        if not self.state_file:
            return
        # crash window: signed in memory, nothing persisted, signature
        # NOT yet released to the caller — dying here must never enable
        # a double-sign (the restarted signer may legally re-sign)
        fail.fail_point("privval.save")
        st = self.last_sign_state
        doc = {
            "height": st.height,
            "round": st.round_,
            "step": st.step,
            "signature": base64.b64encode(st.signature).decode(),
            "signbytes": st.sign_bytes.hex(),
        }
        _atomic_write(self.state_file, json.dumps(doc, indent=2).encode(),
                      site="privval.save")

    def _load_state(self) -> None:
        with open(self.state_file) as f:
            doc = json.load(f)
        self.last_sign_state = _LastSignState(
            height=int(doc.get("height", 0)),
            round_=int(doc.get("round", 0)),
            step=int(doc.get("step", 0)),
            signature=base64.b64decode(doc.get("signature", "")),
            sign_bytes=bytes.fromhex(doc.get("signbytes", "")),
        )

    # --------------------------------------------------------- signing

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote, sign_extension: bool = False) -> None:
        """file.go signVote: HRS guard, timestamp-tolerant re-sign."""
        step = _VOTE_STEP.get(vote.type_)
        if step is None:
            raise ValueError(f"signVote: invalid vote type {vote.type_}")
        sign_bytes = vote.sign_bytes(chain_id)
        same_hrs = self.last_sign_state.check_hrs(vote.height, vote.round_, step)
        if same_hrs:
            st = self.last_sign_state
            if sign_bytes == st.sign_bytes:
                vote.signature = st.signature
            elif _vote_differs_only_by_timestamp(st.sign_bytes, sign_bytes):
                vote.signature = st.signature
                # keep the originally signed timestamp in the vote
                prev = _parse_canonical_vote_timestamp(st.sign_bytes)
                if prev is not None:
                    vote.timestamp = prev
            else:
                raise ErrDoubleSign("conflicting data: same HRS, different vote")
            if sign_extension and vote.type_ == SignedMsgType.PRECOMMIT and not vote.block_id.is_nil():
                vote.extension_signature = self.priv_key.sign(vote.extension_sign_bytes(chain_id))
            return
        sig = self.priv_key.sign(sign_bytes)
        self.last_sign_state = _LastSignState(
            height=vote.height, round_=vote.round_, step=step,
            signature=sig, sign_bytes=sign_bytes,
        )
        self._save_state()
        vote.signature = sig
        if sign_extension and vote.type_ == SignedMsgType.PRECOMMIT and not vote.block_id.is_nil():
            vote.extension_signature = self.priv_key.sign(vote.extension_sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        sign_bytes = proposal.sign_bytes(chain_id)
        same_hrs = self.last_sign_state.check_hrs(proposal.height, proposal.round_, STEP_PROPOSE)
        if same_hrs:
            st = self.last_sign_state
            if sign_bytes == st.sign_bytes:
                proposal.signature = st.signature
            elif _proposal_differs_only_by_timestamp(st.sign_bytes, sign_bytes):
                proposal.signature = st.signature
            else:
                raise ErrDoubleSign("conflicting data: same HRS, different proposal")
            return
        sig = self.priv_key.sign(sign_bytes)
        self.last_sign_state = _LastSignState(
            height=proposal.height, round_=proposal.round_, step=STEP_PROPOSE,
            signature=sig, sign_bytes=sign_bytes,
        )
        self._save_state()
        proposal.signature = sig


def _strip_timestamp(sign_bytes: bytes, ts_field: int) -> bytes | None:
    """Remove the canonical timestamp field so two sign-bytes can be
    compared modulo timestamp (file.go checkVotesOnlyDifferByTimestamp)."""
    try:
        body, _ = pb.unmarshal_delimited(sign_bytes)
        r = pb.Reader(body)
        out = pb.Writer()
        while not r.at_end():
            start = r.pos
            f, w = r.read_tag()
            if f == ts_field and w == 2:
                r.skip(w)
                continue
            r.skip(w)
            out.buf += body[start:r.pos]
        return out.output()
    except ValueError:
        return None


def _vote_differs_only_by_timestamp(a: bytes, b: bytes) -> bool:
    sa, sb = _strip_timestamp(a, 5), _strip_timestamp(b, 5)
    return sa is not None and sa == sb


def _proposal_differs_only_by_timestamp(a: bytes, b: bytes) -> bool:
    sa, sb = _strip_timestamp(a, 6), _strip_timestamp(b, 6)
    return sa is not None and sa == sb


def _parse_canonical_vote_timestamp(sign_bytes: bytes):
    """Parse the canonical timestamp (field 5) back out of vote sign-bytes —
    used to re-sign with the originally signed timestamp after a restart."""
    from cometbft_tpu.utils import cmttime

    try:
        body, _ = pb.unmarshal_delimited(sign_bytes)
        r = pb.Reader(body)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 5 and w == 2:
                secs, nanos = r.read_timestamp()
                return cmttime.Timestamp(secs, nanos)
            r.skip(w)
    except ValueError:
        pass
    return None
