"""ABCI clients: in-process (local) and unix/tcp socket transports — async.

Reference: abci/client/local_client.go (mutex-serialized in-proc calls),
abci/client/socket_client.go (request/response over a stream). The engine is
asyncio-based; app calls are awaitable. Local calls run on a worker thread
under one app-wide lock (the app is a non-reentrant state machine and must
not block the event loop); socket calls await stream I/O. The wire codec is
the framework-native length-prefixed encoding (codec.py).
"""

from __future__ import annotations

import asyncio
import threading

from cometbft_tpu.abci import codec
from cometbft_tpu.abci import types as abci


class ClientError(Exception):
    pass


_METHODS = [
    "info", "query", "check_tx", "init_chain", "prepare_proposal",
    "process_proposal", "finalize_block", "extend_vote",
    "verify_vote_extension", "commit", "list_snapshots", "offer_snapshot",
    "load_snapshot_chunk", "apply_snapshot_chunk",
]


class Client:
    """Async call surface used by proxy.AppConns — one coroutine per ABCI
    method, generated onto the class below."""

    async def echo(self, msg: str) -> abci.ResponseEcho: ...

    async def flush(self) -> None: ...

    async def close(self) -> None: ...


def _make_method(name: str):
    async def call(self, req):
        return await self._call(name, req)

    call.__name__ = name
    return call


for _m in _METHODS:
    setattr(Client, _m, _make_method(_m))


class LocalClient(Client):
    """In-proc client (reference: abci/client/local_client.go): direct app
    calls on a worker thread, serialized by one shared threading.Lock across
    all 4 logical connections (proxy/client.go NewLocalClientCreator)."""

    def __init__(self, app: abci.Application, lock: threading.Lock | None = None):
        self.app = app
        self.lock = lock or threading.Lock()

    async def _call(self, name: str, req):
        def run():
            with self.lock:
                return getattr(self.app, name)(req)

        return await asyncio.to_thread(run)

    async def echo(self, msg: str) -> abci.ResponseEcho:
        return abci.ResponseEcho(message=msg)

    async def flush(self) -> None:
        return None

    async def close(self) -> None:
        return None


class SocketClient(Client):
    """Request/response over a unix or TCP socket. One in-flight call per
    connection (asyncio.Lock); the engine's 4 logical connections provide
    cross-subsystem concurrency, as in the reference.

    wire="proto" speaks the reference's varint-delimited
    tendermint.abci.Request/Response protobuf (abci/proto_codec.py), so this
    client drives any existing ABCI app, including the reference's own
    kvstore; wire="json" is the framework-native frame."""

    def __init__(self, addr: str, wire: str = "proto"):
        from cometbft_tpu.abci import proto_codec

        self.addr = addr
        if wire not in ("proto", "json"):
            raise ValueError(f"unknown ABCI wire format {wire!r}")
        self._codec = proto_codec if wire == "proto" else codec
        self.wire = wire
        self._lock = asyncio.Lock()
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def connect(self, timeout: float = 10.0) -> None:
        if self.addr.startswith("unix://"):
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_unix_connection(self.addr[len("unix://"):]), timeout
            )
        else:
            host, _, port = self.addr.removeprefix("tcp://").rpartition(":")
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), timeout
            )
        if self.wire == "json":
            # the server's wire autodetector keys on the connection's FIRST
            # byte (0x00 = JSON 4-byte length header). A first frame >= 16 MB
            # would start nonzero and be misread as proto, so lock the mode
            # in with a tiny echo before any real (possibly huge) request.
            self._writer.write(self._codec.encode_request(
                "echo", abci.RequestEcho(message="")))
            await self._writer.drain()
            await asyncio.wait_for(
                self._codec.decode_response_async(self._reader), timeout)

    async def _call(self, name: str, req):
        if self._writer is None:
            await self.connect()
        async with self._lock:
            self._writer.write(self._codec.encode_request(name, req))
            await self._writer.drain()
            resp_name, resp = await self._codec.decode_response_async(self._reader)
        if resp_name == "exception":
            raise ClientError(resp)
        if resp_name != name:
            raise ClientError(f"out-of-order response: want {name}, got {resp_name}")
        return resp

    async def echo(self, msg: str) -> abci.ResponseEcho:
        return await self._call("echo", abci.RequestEcho(message=msg))

    async def flush(self) -> None:
        await self._call("flush", abci.RequestFlush())

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None
