"""ABCI over gRPC (reference: abci/client/grpc_client.go +
abci/server/grpc_server.go).

The reference exposes one unary RPC per ABCI method on a
protoc-generated `tendermint.abci.ABCI` service. The server here hosts
BOTH encodings on one port, keyed by service name:

  /tendermint.abci.ABCI/<CamelMethod>   — raw proto request/response
      bodies (abci/proto_codec.py), wire-compatible with the reference's
      generated stubs: any existing gRPC ABCI client connects unmodified.
  /cometbft_tpu.abci.ABCI/<method>      — the framework-native JSON
      frames (legacy transport, kept for in-framework callers).

Server: serve_grpc(app, addr) -> started grpc.Server (thread-pool; the
Application interface is synchronous).
Client: GRPCClient over grpc.aio — one in-flight request per method call,
matching the Client contract used by the proxy connections; wire="proto"
(default) speaks the tendermint.abci.ABCI service.
"""

from __future__ import annotations

import json
import struct
import threading
from concurrent import futures

import grpc
import grpc.aio

from cometbft_tpu.abci import codec
from cometbft_tpu.abci import proto_codec
from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import Client, ClientError

SERVICE = "cometbft_tpu.abci.ABCI"
PROTO_SERVICE = "tendermint.abci.ABCI"

_METHODS = sorted(codec._REQUEST_TYPES)
_CAMEL = {m: "".join(p.capitalize() for p in m.split("_")) for m in _METHODS}
_BY_CAMEL = {v: k for k, v in _CAMEL.items()}


def _ident(b: bytes) -> bytes:
    return b


def _strip_frame(data: bytes) -> dict:
    if len(data) < 4:
        raise ValueError("short ABCI frame")
    (n,) = struct.unpack(">I", data[:4])
    if n != len(data) - 4:
        raise ValueError("ABCI frame length mismatch")
    return json.loads(data[4:])


class _AppHandler(grpc.GenericRpcHandler):
    """grpc_server.go: every ABCI verb is a unary RPC onto the app."""

    def __init__(self, app: abci.Application):
        self.app = app
        self._lock = threading.Lock()  # app calls are serialized, like local

    def service(self, handler_call_details):
        path = handler_call_details.method  # "/<service>/<Method>"
        try:
            service, method = path.lstrip("/").split("/", 1)
        except ValueError:
            return None
        if service == PROTO_SERVICE and method in _BY_CAMEL:
            m = _BY_CAMEL[method]

            def proto_handler(request_bytes: bytes, context) -> bytes:
                req = proto_codec._REQ_DECODERS[m](request_bytes)
                resp = self._run(m, req)
                return proto_codec._RESP_ENCODERS[m](resp)

            return grpc.unary_unary_rpc_method_handler(
                proto_handler, request_deserializer=_ident,
                response_serializer=_ident)
        if service != SERVICE or method not in codec._REQUEST_TYPES:
            return None

        def handler(request_bytes: bytes, context) -> bytes:
            m, req = codec._decode_request_body(_strip_frame(request_bytes))
            resp = self._run(m, req)
            return codec.encode_response(m, resp)

        return grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=_ident, response_serializer=_ident)

    def _run(self, m: str, req):
        with self._lock:
            if m == "echo":
                return abci.ResponseEcho(message=req.message)
            if m == "flush":
                return abci.ResponseFlush()
            return getattr(self.app, m)(req)


def serve_grpc(app: abci.Application, addr: str) -> tuple[grpc.Server, str]:
    """-> (started server, bound 'host:port'). addr may use port 0."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((_AppHandler(app),))
    host = addr.removeprefix("grpc://").removeprefix("tcp://")
    port = server.add_insecure_port(host)
    server.start()
    bound = f"{host.rsplit(':', 1)[0]}:{port}"
    return server, bound


class GRPCClient(Client):
    """grpc_client.go over grpc.aio — satisfies the proxy Client contract.
    wire="proto" (default) calls the reference-compatible
    tendermint.abci.ABCI service; wire="json" the legacy framework one."""

    def __init__(self, addr: str, wire: str = "proto"):
        self.addr = addr.removeprefix("grpc://").removeprefix("tcp://")
        if wire not in ("proto", "json"):
            raise ValueError(f"unknown ABCI wire format {wire!r}")
        self.wire = wire
        self._channel: grpc.aio.Channel | None = None
        self._stubs: dict[str, object] = {}

    async def _ensure(self) -> None:
        if self._channel is None:
            self._channel = grpc.aio.insecure_channel(self.addr)
            for m in _METHODS:
                path = (f"/{PROTO_SERVICE}/{_CAMEL[m]}" if self.wire == "proto"
                        else f"/{SERVICE}/{m}")
                self._stubs[m] = self._channel.unary_unary(
                    path,
                    request_serializer=_ident,
                    response_deserializer=_ident,
                )

    async def _call(self, name: str, req) -> object:
        await self._ensure()
        if self.wire == "proto":
            try:
                raw = await self._stubs[name](
                    proto_codec._REQ_ENCODERS[name](req))
            except grpc.aio.AioRpcError as e:
                raise ClientError(
                    f"grpc abci call {name} failed: {e.details()}") from e
            return proto_codec._RESP_DECODERS[name](raw)
        try:
            raw = await self._stubs[name](codec.encode_request(name, req))
        except grpc.aio.AioRpcError as e:
            raise ClientError(f"grpc abci call {name} failed: {e.details()}") from e
        m, resp = codec._decode_response_body(_strip_frame(raw))
        if m == "exception":
            raise ClientError(f"abci app exception in {name}: {resp}")
        return resp

    async def echo(self, msg: str) -> abci.ResponseEcho:
        return await self._call("echo", abci.RequestEcho(message=msg))

    async def flush(self) -> None:
        await self._call("flush", abci.RequestFlush())

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None


# the proxy-facing per-method coroutines (same generation as client.py)
def _make_method(name: str):
    async def call(self, req):
        return await self._call(name, req)

    return call


for _m in _METHODS:
    if _m not in ("echo", "flush"):
        setattr(GRPCClient, _m, _make_method(_m))
