"""ABCI call-sequence conformance checker.

Reference: test/e2e/pkg/grammar/checker.go + the clean-start / recovery
context-free grammars derived from the ABCI 2.0 expected-behavior spec.
The reference generates a parser with gogll; the grammars are regular
enough for a direct recursive-descent over the recorded call names:

  clean-start = init_chain [state-sync] consensus-exec
  state-sync  = *(offer_snapshot *apply_chunk) offer_snapshot 1*apply_chunk
  recovery    = consensus-exec
  consensus-exec   = 1*consensus-height
  consensus-height = *consensus-round finalize_block commit
  consensus-round  = prepare_proposal [process_proposal] | process_proposal

RecordingApplication wraps any Application, recording the consensus/
snapshot-connection calls the grammar covers so a running node's trace can
be checked (the reference records the same subset and trims the trailing
partial height, checker.go:74)."""

from __future__ import annotations

GRAMMAR_CALLS = (
    "init_chain", "offer_snapshot", "apply_snapshot_chunk",
    "prepare_proposal", "process_proposal", "finalize_block", "commit",
)


class GrammarError(Exception):
    def __init__(self, trace: list[str], pos: int, why: str):
        window = " ".join(trace[max(0, pos - 3):pos + 3])
        super().__init__(f"ABCI grammar violation at call {pos} ({why}); "
                         f"context: ...{window}...")
        self.pos = pos


class RecordingApplication:
    """Transparent Application wrapper recording grammar-relevant calls."""

    def __init__(self, inner):
        self._inner = inner
        self.trace: list[str] = []

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if name in GRAMMAR_CALLS and callable(fn):
            def wrapped(*a, **kw):
                self.trace.append(name)
                return fn(*a, **kw)

            return wrapped
        return fn


def _trim_last_partial_height(trace: list[str]) -> list[str]:
    """checker.go:74 filterRequests: the node may be mid-height when the
    trace is captured; drop everything after the last commit."""
    for i in range(len(trace) - 1, -1, -1):
        if trace[i] == "commit":
            return trace[:i + 1]
    return []


def check(trace: list[str], clean_start: bool) -> None:
    """Raise GrammarError unless the trace parses. clean_start: the node
    booted from genesis (expects init_chain and optionally state sync);
    otherwise the recovery grammar (pure consensus-exec) applies."""
    t = _trim_last_partial_height([c for c in trace if c in GRAMMAR_CALLS])
    if not t:
        raise GrammarError(trace, 0, "no complete height recorded")
    i = 0

    def peek(k: int = 0) -> str | None:
        return t[i + k] if i + k < len(t) else None

    if clean_start:
        if peek() != "init_chain":
            raise GrammarError(t, i, "clean start must begin with init_chain")
        i += 1
        # state-sync: attempts then a success (offer + 1*apply), optional
        while peek() == "offer_snapshot":
            i += 1
            applied = 0
            while peek() == "apply_snapshot_chunk":
                i += 1
                applied += 1
            if peek() != "offer_snapshot" and applied == 0:
                raise GrammarError(
                    t, i, "a successful state sync needs >=1 apply_snapshot_chunk")

    # consensus-exec: 1 or more heights
    heights = 0
    while i < len(t):
        # *consensus-round
        while peek() in ("prepare_proposal", "process_proposal"):
            if peek() == "prepare_proposal":
                i += 1
                if peek() == "process_proposal":
                    i += 1
            else:
                i += 1
        if peek() != "finalize_block":
            raise GrammarError(t, i, f"expected finalize_block, got {peek()!r}")
        i += 1
        if peek() != "commit":
            raise GrammarError(t, i, f"expected commit after finalize_block, got {peek()!r}")
        i += 1
        heights += 1
    if heights == 0:
        raise GrammarError(t, i, "no consensus heights")
