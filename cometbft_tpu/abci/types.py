"""ABCI request/response types + the 17-method Application interface.

Reference: abci/types/application.go:9-35 (interface),
proto/tendermint/abci/types.proto (wire shapes). Python dataclasses carry
the same fields; the socket transport (client.py/server.py) maps them to the
wire via a compact tagged encoding.
"""

from __future__ import annotations

import enum
from abc import ABC
from dataclasses import dataclass, field

from cometbft_tpu.utils import cmttime

CODE_TYPE_OK = 0


class CheckTxType(enum.IntEnum):
    NEW = 0
    RECHECK = 1


class ProposalStatus(enum.IntEnum):
    """ResponseProcessProposal.Status."""

    UNKNOWN = 0
    ACCEPT = 1
    REJECT = 2


class VerifyStatus(enum.IntEnum):
    """ResponseVerifyVoteExtension.Status."""

    UNKNOWN = 0
    ACCEPT = 1
    REJECT = 2


class OfferSnapshotResult(enum.IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    REJECT = 3
    REJECT_FORMAT = 4
    REJECT_SENDER = 5


class ApplySnapshotChunkResult(enum.IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    RETRY = 3
    RETRY_SNAPSHOT = 4
    REJECT_SNAPSHOT = 5


# ---------------------------------------------------------------- common


@dataclass
class EventAttribute:
    key: str
    value: str
    index: bool = True


@dataclass
class Event:
    type_: str
    attributes: list[EventAttribute] = field(default_factory=list)


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int


@dataclass
class VoteInfo:
    validator_address: bytes
    validator_power: int
    block_id_flag: int  # types.BlockIDFlag


@dataclass
class ExtendedVoteInfo:
    validator_address: bytes
    validator_power: int
    block_id_flag: int
    vote_extension: bytes = b""
    extension_signature: bytes = b""


@dataclass
class CommitInfo:
    round_: int
    votes: list[VoteInfo] = field(default_factory=list)


@dataclass
class ExtendedCommitInfo:
    round_: int
    votes: list[ExtendedVoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    type_: str  # "DUPLICATE_VOTE" | "LIGHT_CLIENT_ATTACK"
    validator_address: bytes
    validator_power: int
    height: int
    time: cmttime.Timestamp
    total_voting_power: int


@dataclass
class Snapshot:
    height: int
    format_: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


# ---------------------------------------------------------------- requests


@dataclass
class RequestEcho:
    message: str = ""


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type_: CheckTxType = CheckTxType.NEW


@dataclass
class RequestInitChain:
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    chain_id: str = ""
    consensus_params: object | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class RequestPrepareProposal:
    max_tx_bytes: int = 0
    txs: list[bytes] = field(default_factory=list)
    local_last_commit: ExtendedCommitInfo = field(default_factory=lambda: ExtendedCommitInfo(0))
    misbehavior: list[Misbehavior] = field(default_factory=list)
    height: int = 0
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class RequestProcessProposal:
    txs: list[bytes] = field(default_factory=list)
    proposed_last_commit: CommitInfo = field(default_factory=lambda: CommitInfo(0))
    misbehavior: list[Misbehavior] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class RequestFinalizeBlock:
    txs: list[bytes] = field(default_factory=list)
    decided_last_commit: CommitInfo = field(default_factory=lambda: CommitInfo(0))
    misbehavior: list[Misbehavior] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class RequestExtendVote:
    hash: bytes = b""
    height: int = 0
    round_: int = 0
    txs: list[bytes] = field(default_factory=list)
    proposed_last_commit: CommitInfo = field(default_factory=lambda: CommitInfo(0))
    misbehavior: list[Misbehavior] = field(default_factory=list)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)


@dataclass
class RequestVerifyVoteExtension:
    hash: bytes = b""
    validator_address: bytes = b""
    height: int = 0
    vote_extension: bytes = b""


@dataclass
class RequestCommit:
    pass


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot | None = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format_: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


@dataclass
class RequestFlush:
    pass


# ---------------------------------------------------------------- responses


@dataclass
class ResponseEcho:
    message: str = ""


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: list = field(default_factory=list)
    height: int = 0
    codespace: str = ""


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseInitChain:
    consensus_params: object | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponsePrepareProposal:
    txs: list[bytes] = field(default_factory=list)


@dataclass
class ResponseProcessProposal:
    status: ProposalStatus = ProposalStatus.UNKNOWN

    def is_accepted(self) -> bool:
        return self.status == ProposalStatus.ACCEPT


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def hash_bytes(self) -> bytes:
        """Deterministic encoding for LastResultsHash (reference:
        types/results.go ABCIResults.Hash — only Code/Data/GasWanted/GasUsed
        are hashed, deterministic fields)."""
        from cometbft_tpu.utils import protobuf as pb

        w = pb.Writer()
        w.uvarint(1, self.code)
        w.bytes(2, self.data)
        w.varint_i64(5, self.gas_wanted)
        w.varint_i64(6, self.gas_used)
        return w.output()


@dataclass
class ResponseFinalizeBlock:
    events: list[Event] = field(default_factory=list)
    tx_results: list[ExecTxResult] = field(default_factory=list)
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: object | None = None
    app_hash: bytes = b""


@dataclass
class ResponseExtendVote:
    vote_extension: bytes = b""


@dataclass
class ResponseVerifyVoteExtension:
    status: VerifyStatus = VerifyStatus.UNKNOWN

    def is_accepted(self) -> bool:
        return self.status == VerifyStatus.ACCEPT


@dataclass
class ResponseCommit:
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: list[Snapshot] = field(default_factory=list)


@dataclass
class ResponseOfferSnapshot:
    result: OfferSnapshotResult = OfferSnapshotResult.UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class ResponseApplySnapshotChunk:
    result: ApplySnapshotChunkResult = ApplySnapshotChunkResult.UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


@dataclass
class ResponseFlush:
    pass


# ---------------------------------------------------------------- interface


class Application(ABC):
    """The 17-method ABCI 2.0 surface (abci/types/application.go:9-35),
    grouped by logical connection (proxy multiplexes 4 of them,
    proxy/app_conn.go:18-56)."""

    # Info/Query connection
    def info(self, req: RequestInfo) -> ResponseInfo: ...

    def query(self, req: RequestQuery) -> ResponseQuery: ...

    # Mempool connection
    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx: ...

    # Consensus connection
    def init_chain(self, req: RequestInitChain) -> ResponseInitChain: ...

    def prepare_proposal(self, req: RequestPrepareProposal) -> ResponsePrepareProposal: ...

    def process_proposal(self, req: RequestProcessProposal) -> ResponseProcessProposal: ...

    def finalize_block(self, req: RequestFinalizeBlock) -> ResponseFinalizeBlock: ...

    def extend_vote(self, req: RequestExtendVote) -> ResponseExtendVote: ...

    def verify_vote_extension(self, req: RequestVerifyVoteExtension) -> ResponseVerifyVoteExtension: ...

    def commit(self, req: RequestCommit) -> ResponseCommit: ...

    # State-sync connection
    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots: ...

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot: ...

    def load_snapshot_chunk(self, req: RequestLoadSnapshotChunk) -> ResponseLoadSnapshotChunk: ...

    def apply_snapshot_chunk(self, req: RequestApplySnapshotChunk) -> ResponseApplySnapshotChunk: ...


class BaseApplication(Application):
    """No-op defaults (abci/types/application.go:40-110): accept every tx,
    accept every proposal, empty snapshots."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery(code=CODE_TYPE_OK)

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx(code=CODE_TYPE_OK)

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def prepare_proposal(self, req: RequestPrepareProposal) -> ResponsePrepareProposal:
        # default: pass txs through within the byte budget
        txs, total = [], 0
        for tx in req.txs:
            total += len(tx)
            if req.max_tx_bytes and total > req.max_tx_bytes:
                break
            txs.append(tx)
        return ResponsePrepareProposal(txs=txs)

    def process_proposal(self, req: RequestProcessProposal) -> ResponseProcessProposal:
        return ResponseProcessProposal(status=ProposalStatus.ACCEPT)

    def finalize_block(self, req: RequestFinalizeBlock) -> ResponseFinalizeBlock:
        return ResponseFinalizeBlock(
            tx_results=[ExecTxResult(code=CODE_TYPE_OK) for _ in req.txs]
        )

    def extend_vote(self, req: RequestExtendVote) -> ResponseExtendVote:
        return ResponseExtendVote()

    def verify_vote_extension(self, req: RequestVerifyVoteExtension) -> ResponseVerifyVoteExtension:
        return ResponseVerifyVoteExtension(status=VerifyStatus.ACCEPT)

    def commit(self, req: RequestCommit) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot(result=OfferSnapshotResult.ABORT)

    def load_snapshot_chunk(self, req: RequestLoadSnapshotChunk) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: RequestApplySnapshotChunk) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk(result=ApplySnapshotChunkResult.ABORT)
