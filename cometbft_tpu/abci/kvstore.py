"""In-process kvstore example application.

Functional mirror of the reference example app (abci/example/kvstore):
'key=value' txs stored in a map; 'val:BASE64PUBKEY!POWER' txs update the
validator set; AppHash commits to the state deterministically. Used by the
multi-validator consensus harness exactly as the reference uses its kvstore
in consensus tests (consensus/common_test.go).
"""

from __future__ import annotations

import base64
import hashlib
import json

from cometbft_tpu.abci import types as abci

VALIDATOR_PREFIX = "val:"


class KVStoreApplication(abci.BaseApplication):
    def __init__(self):
        self.state: dict[str, str] = {}
        self.height = 0
        self.app_hash = b"\x00" * 8
        self.pending_updates: list[abci.ValidatorUpdate] = []
        self.validators: dict[bytes, int] = {}  # pubkey -> power
        self.staged: dict[str, str] | None = None
        self.staged_hash = b""
        self.tx_count = 0
        self.snapshot_interval = 0  # 0 = snapshots off
        self.snapshots: list[tuple[abci.Snapshot, list[bytes]]] = []
        self._restoring: tuple[abci.Snapshot, list[bytes]] | None = None

    # ------------------------------------------------------------ helpers

    def _is_validator_tx(self, tx: bytes) -> bool:
        return tx.startswith(VALIDATOR_PREFIX.encode())

    def _parse_validator_tx(self, tx: bytes) -> abci.ValidatorUpdate | None:
        try:
            body = tx.decode()[len(VALIDATOR_PREFIX):]
            pub_b64, power_s = body.split("!")
            return abci.ValidatorUpdate(
                pub_key_type="ed25519",
                pub_key_bytes=base64.b64decode(pub_b64),
                power=int(power_s),
            )
        except Exception:  # noqa: BLE001
            return None

    def _parse_kv(self, tx: bytes) -> tuple[str, str] | None:
        try:
            s = tx.decode()
        except UnicodeDecodeError:
            return None
        if "=" in s:
            k, v = s.split("=", 1)
            return k, v
        return s, s

    def _compute_hash(self, state: dict[str, str], height: int) -> bytes:
        blob = json.dumps(state, sort_keys=True).encode() + height.to_bytes(8, "big")
        return hashlib.sha256(blob).digest()

    # ------------------------------------------------------------- ABCI

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="0.1.0",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash if self.height else b"",
        )

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        for vu in req.validators:
            self.validators[vu.pub_key_bytes] = vu.power
        # seed state from genesis app_state (reference kvstore app.go
        # InitChain: a JSON object of initial key/values)
        if req.app_state_bytes:
            try:
                seed = json.loads(req.app_state_bytes)
            except ValueError:  # covers JSONDecodeError AND UnicodeDecodeError
                seed = None
            if isinstance(seed, dict):
                for k, v in seed.items():
                    if isinstance(k, str) and isinstance(v, str):
                        self.state[k] = v
        return abci.ResponseInitChain(app_hash=self.app_hash)

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        if self._is_validator_tx(req.tx):
            if self._parse_validator_tx(req.tx) is None:
                return abci.ResponseCheckTx(code=1, log="invalid validator tx")
            return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)
        if self._parse_kv(req.tx) is None:
            return abci.ResponseCheckTx(code=1, log="tx must be utf-8 key=value")
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def process_proposal(self, req: abci.RequestProcessProposal) -> abci.ResponseProcessProposal:
        for tx in req.txs:
            if self._is_validator_tx(tx):
                if self._parse_validator_tx(tx) is None:
                    return abci.ResponseProcessProposal(status=abci.ProposalStatus.REJECT)
            elif self._parse_kv(tx) is None:
                return abci.ResponseProcessProposal(status=abci.ProposalStatus.REJECT)
        return abci.ResponseProcessProposal(status=abci.ProposalStatus.ACCEPT)

    def finalize_block(self, req: abci.RequestFinalizeBlock) -> abci.ResponseFinalizeBlock:
        staged = dict(self.state)
        if req.misbehavior:
            # make Misbehavior deliveries app-observable (queryable via
            # abci_query) — deterministic: req.misbehavior comes from the
            # committed block, identical on every node
            prev = int(staged.get("__misbehavior_count__", "0"))
            staged["__misbehavior_count__"] = str(prev + len(req.misbehavior))
        results: list[abci.ExecTxResult] = []
        updates: list[abci.ValidatorUpdate] = []
        for tx in req.txs:
            if self._is_validator_tx(tx):
                vu = self._parse_validator_tx(tx)
                if vu is None:
                    results.append(abci.ExecTxResult(code=1, log="invalid validator tx"))
                    continue
                updates.append(vu)
                self.validators[vu.pub_key_bytes] = vu.power
                results.append(abci.ExecTxResult(code=abci.CODE_TYPE_OK))
                continue
            kv = self._parse_kv(tx)
            if kv is None:
                results.append(abci.ExecTxResult(code=1, log="invalid tx"))
                continue
            k, v = kv
            staged[k] = v
            self.tx_count += 1
            results.append(
                abci.ExecTxResult(
                    code=abci.CODE_TYPE_OK,
                    events=[
                        abci.Event(
                            type_="app",
                            attributes=[
                                abci.EventAttribute(key="key", value=k),
                                abci.EventAttribute(key="creator", value="kvstore"),
                            ],
                        )
                    ],
                )
            )
        self.staged = staged
        self.staged_hash = self._compute_hash(staged, req.height)
        self.pending_updates = updates
        return abci.ResponseFinalizeBlock(
            tx_results=results,
            validator_updates=updates,
            app_hash=self.staged_hash,
        )

    def commit(self, req: abci.RequestCommit) -> abci.ResponseCommit:
        if self.staged is not None:
            self.state = self.staged
            self.app_hash = self.staged_hash
            self.staged = None
            self.height += 1
        if self.snapshot_interval and self.height % self.snapshot_interval == 0:
            self._take_snapshot()
        return abci.ResponseCommit(retain_height=0)

    # ------------------------------------------------------- state sync
    # (reference shape: abci/example/kvstore has no snapshots; the e2e app
    # does — test/e2e/app/snapshots.go. Same JSON-chunks design here.)

    SNAPSHOT_FORMAT = 1
    SNAPSHOT_CHUNK_SIZE = 1 << 16

    def _take_snapshot(self) -> None:
        import hashlib

        payload = json.dumps(
            {"height": self.height, "app_hash": self.app_hash.hex(),
             "state": self.state, "tx_count": self.tx_count},
            sort_keys=True,
        ).encode()
        chunks = [
            payload[i:i + self.SNAPSHOT_CHUNK_SIZE]
            for i in range(0, max(len(payload), 1), self.SNAPSHOT_CHUNK_SIZE)
        ]
        snap = abci.Snapshot(
            height=self.height, format_=self.SNAPSHOT_FORMAT,
            chunks=len(chunks), hash=hashlib.sha256(payload).digest(),
        )
        self.snapshots.append((snap, chunks))
        del self.snapshots[:-5]  # keep the 5 newest

    def list_snapshots(self, req: abci.RequestListSnapshots) -> abci.ResponseListSnapshots:
        return abci.ResponseListSnapshots(snapshots=[s for s, _ in self.snapshots])

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        for snap, chunks in self.snapshots:
            if (snap.height == req.height and snap.format_ == req.format_
                    and 0 <= req.chunk < len(chunks)):
                return abci.ResponseLoadSnapshotChunk(chunk=chunks[req.chunk])
        return abci.ResponseLoadSnapshotChunk()

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        s = req.snapshot
        if s is None or s.format_ != self.SNAPSHOT_FORMAT:
            return abci.ResponseOfferSnapshot(
                result=abci.OfferSnapshotResult.REJECT_FORMAT)
        self._restoring = (s, [])
        return abci.ResponseOfferSnapshot(result=abci.OfferSnapshotResult.ACCEPT)

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        import hashlib

        if self._restoring is None:
            return abci.ResponseApplySnapshotChunk(
                result=abci.ApplySnapshotChunkResult.ABORT)
        snap, got = self._restoring
        got.append(req.chunk)
        if len(got) < snap.chunks:
            return abci.ResponseApplySnapshotChunk(
                result=abci.ApplySnapshotChunkResult.ACCEPT)
        payload = b"".join(got)
        if hashlib.sha256(payload).digest() != snap.hash:
            self._restoring = None
            return abci.ResponseApplySnapshotChunk(
                result=abci.ApplySnapshotChunkResult.REJECT_SNAPSHOT)
        doc = json.loads(payload)
        self.state = doc["state"]
        self.height = doc["height"]
        self.app_hash = bytes.fromhex(doc["app_hash"])
        self.tx_count = doc.get("tx_count", 0)
        self._restoring = None
        return abci.ResponseApplySnapshotChunk(
            result=abci.ApplySnapshotChunkResult.ACCEPT)

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        key = req.data.decode()
        if req.path == "/store" or req.path == "":
            val = self.state.get(key)
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                key=req.data,
                value=val.encode() if val is not None else b"",
                log="exists" if val is not None else "does not exist",
                height=self.height,
            )
        return abci.ResponseQuery(code=1, log=f"unknown path {req.path}")
