"""Socket ABCI server: host an Application out-of-process (asyncio).

Reference: abci/server/socket_server.go. Each connection is served by its
own task; app calls are executed on worker threads under one app-wide lock
(the app is a single non-reentrant state machine).
"""

from __future__ import annotations

import asyncio
import os
import threading

from cometbft_tpu.abci import codec
from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs.service import BaseService, TaskRunner


class ABCIServer(BaseService):
    def __init__(self, app: abci.Application, addr: str):
        super().__init__("ABCIServer")
        self.app = app
        self.addr = addr
        self.app_lock = threading.Lock()
        self._server: asyncio.AbstractServer | None = None
        self._tasks = TaskRunner("abci-server")

    async def on_start(self) -> None:
        if self.addr.startswith("unix://"):
            path = self.addr[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._server = await asyncio.start_unix_server(self._serve, path)
        else:
            host, _, port = self.addr.removeprefix("tcp://").rpartition(":")
            self._server = await asyncio.start_server(
                self._serve, host or "127.0.0.1", int(port)
            )

    def bound_addr(self) -> str:
        """Actual address after bind (useful with tcp port 0)."""
        import socket as socketlib

        assert self._server is not None
        sock = self._server.sockets[0]
        if sock.family == getattr(socketlib, "AF_UNIX", None):
            return self.addr
        host, port = sock.getsockname()[:2]
        return f"tcp://{host}:{port}"

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while self.is_running:
                try:
                    method, req = await codec.decode_request_async(reader)
                except (EOFError, asyncio.IncompleteReadError, ConnectionError):
                    return
                if method == "echo":
                    writer.write(codec.encode_response("echo", abci.ResponseEcho(message=req.message)))
                elif method == "flush":
                    writer.write(codec.encode_response("flush", abci.ResponseFlush()))
                else:
                    try:
                        resp = await self._dispatch(method, req)
                        writer.write(codec.encode_response(method, resp))
                    except Exception as e:  # noqa: BLE001 - report to client
                        writer.write(codec.encode_exception(f"{type(e).__name__}: {e}"))
                await writer.drain()
        finally:
            writer.close()

    async def _dispatch(self, method: str, req):
        def run():
            with self.app_lock:
                return getattr(self.app, method)(req)

        return await asyncio.to_thread(run)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        await self._tasks.cancel_all()
