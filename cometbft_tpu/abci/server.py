"""Socket ABCI server: host an Application out-of-process (asyncio).

Reference: abci/server/socket_server.go. Each connection is served by its
own task; app calls are executed on worker threads under one app-wide lock
(the app is a single non-reentrant state machine).

Wire format is detected per connection from the first byte: the reference's
varint-delimited proto Request stream starts with a nonzero length prefix,
while the framework-native JSON frame starts with a 4-byte big-endian
length whose first byte is zero for any sane frame (<16 MB). A reference
node or abci-cli therefore connects with no configuration. A first byte of
0x00 alone is ambiguous (it is also the varint length of an empty proto
frame), so the detector peeks the next 4 bytes: JSON carries 3 more length
bytes then '{'.
"""

from __future__ import annotations

import asyncio
import os
import threading

from cometbft_tpu.abci import codec
from cometbft_tpu.abci import proto_codec
from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs.service import BaseService, TaskRunner


class _PrefixedReader:
    """StreamReader facade replaying bytes the wire autodetector peeked
    past a 0x00 first byte before handing the stream to the proto reader."""

    def __init__(self, reader: asyncio.StreamReader, buf: bytes):
        self._reader = reader
        self._buf = buf

    async def readexactly(self, n: int) -> bytes:
        out = b""
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
        if len(out) < n:
            out += await self._reader.readexactly(n - len(out))
        return out


class ABCIServer(BaseService):
    def __init__(self, app: abci.Application, addr: str):
        super().__init__("ABCIServer")
        self.app = app
        self.addr = addr
        self.app_lock = threading.Lock()
        self._server: asyncio.AbstractServer | None = None
        self._tasks = TaskRunner("abci-server")
        self._conns: set[asyncio.StreamWriter] = set()

    async def on_start(self) -> None:
        if self.addr.startswith("unix://"):
            path = self.addr[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._server = await asyncio.start_unix_server(self._serve, path)
        else:
            host, _, port = self.addr.removeprefix("tcp://").rpartition(":")
            self._server = await asyncio.start_server(
                self._serve, host or "127.0.0.1", int(port)
            )

    def bound_addr(self) -> str:
        """Actual address after bind (useful with tcp port 0)."""
        import socket as socketlib

        assert self._server is not None
        sock = self._server.sockets[0]
        if sock.family == getattr(socketlib, "AF_UNIX", None):
            return self.addr
        host, port = sock.getsockname()[:2]
        return f"tcp://{host}:{port}"

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            try:
                first = await reader.readexactly(1)
                if first == b"\x00":
                    # Ambiguous first byte: a JSON frame's 4-byte BE length
                    # starts 0x00 for bodies < 2^24, but 0x00 is also the
                    # varint length of an empty proto frame. JSON carries 3
                    # more length bytes then '{' — peek them to decide.
                    peek = await reader.readexactly(4)
                    if peek[3:4] == b"{":
                        wire = codec
                        read_req = self._json_reader(reader, first + peek)
                    else:
                        wire = proto_codec
                        read_req = self._proto_reader(
                            _PrefixedReader(reader, peek), first)
                else:
                    wire = proto_codec
                    read_req = self._proto_reader(reader, first)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            while self.is_running:
                try:
                    method, req = await read_req()
                except (EOFError, asyncio.IncompleteReadError, ConnectionError):
                    return
                if method == "echo":
                    writer.write(wire.encode_response("echo", abci.ResponseEcho(message=req.message)))
                elif method == "flush":
                    writer.write(wire.encode_response("flush", abci.ResponseFlush()))
                else:
                    try:
                        resp = await self._dispatch(method, req)
                        writer.write(wire.encode_response(method, resp))
                    except Exception as e:  # noqa: BLE001 - report to client
                        writer.write(wire.encode_exception(f"{type(e).__name__}: {e}"))
                await writer.drain()
        finally:
            self._conns.discard(writer)
            writer.close()

    @staticmethod
    def _json_reader(reader, consumed: bytes):
        """consumed: the 5 autodetection bytes (4-byte BE length + the
        leading '{' of the body)."""
        state = {"consumed": consumed}

        async def read():
            if state["consumed"] is not None:
                import json as _json
                import struct as _struct

                buf = state["consumed"]
                state["consumed"] = None
                (n,) = _struct.unpack(">I", buf[:4])
                raw = buf[4:] + await reader.readexactly(n - 1)
                return codec._decode_request_body(_json.loads(raw))
            return await codec.decode_request_async(reader)

        return read

    @staticmethod
    def _proto_reader(reader, first: bytes):
        """first: the single already-consumed varint byte (0x00 here means
        an empty proto frame — the autodetector's peeked bytes ride a
        _PrefixedReader so the next frame is not lost)."""
        state = {"first": first}

        async def read():
            while True:
                pre, state["first"] = state["first"] or b"", None
                data = await proto_codec.read_delimited_async(
                    reader, first_byte=pre)
                if data:
                    return proto_codec.decode_request_bytes(data)
                # zero-length frame (an empty Request): nothing to serve,
                # keep the stream aligned and read the next frame

        return read

    async def _dispatch(self, method: str, req):
        def run():
            with self.app_lock:
                return getattr(self.app, method)(req)

        return await asyncio.to_thread(run)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Python 3.12 wait_closed() also waits for per-connection
            # handlers; close live client connections so an app-side stop
            # never hangs on an idle client
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
        await self._tasks.cancel_all()
