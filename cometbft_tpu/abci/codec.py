"""Wire codec for the socket ABCI transport.

Frame = 4-byte big-endian length + JSON body {"m": method, "r": request}.
Dataclasses serialize structurally; bytes fields go base64. This is the
framework's native app-server protocol (the analog of the reference's
varint-delimited proto Request/Response, abci/client/socket_client.go).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import struct
from typing import Any

from cometbft_tpu.abci import types as abci
from cometbft_tpu.utils import cmttime

_REQUEST_TYPES: dict[str, type] = {
    "echo": abci.RequestEcho,
    "flush": abci.RequestFlush,
    "info": abci.RequestInfo,
    "query": abci.RequestQuery,
    "check_tx": abci.RequestCheckTx,
    "init_chain": abci.RequestInitChain,
    "prepare_proposal": abci.RequestPrepareProposal,
    "process_proposal": abci.RequestProcessProposal,
    "finalize_block": abci.RequestFinalizeBlock,
    "extend_vote": abci.RequestExtendVote,
    "verify_vote_extension": abci.RequestVerifyVoteExtension,
    "commit": abci.RequestCommit,
    "list_snapshots": abci.RequestListSnapshots,
    "offer_snapshot": abci.RequestOfferSnapshot,
    "load_snapshot_chunk": abci.RequestLoadSnapshotChunk,
    "apply_snapshot_chunk": abci.RequestApplySnapshotChunk,
}

_RESPONSE_TYPES: dict[str, type] = {
    "echo": abci.ResponseEcho,
    "flush": abci.ResponseFlush,
    "info": abci.ResponseInfo,
    "query": abci.ResponseQuery,
    "check_tx": abci.ResponseCheckTx,
    "init_chain": abci.ResponseInitChain,
    "prepare_proposal": abci.ResponsePrepareProposal,
    "process_proposal": abci.ResponseProcessProposal,
    "finalize_block": abci.ResponseFinalizeBlock,
    "extend_vote": abci.ResponseExtendVote,
    "verify_vote_extension": abci.ResponseVerifyVoteExtension,
    "commit": abci.ResponseCommit,
    "list_snapshots": abci.ResponseListSnapshots,
    "offer_snapshot": abci.ResponseOfferSnapshot,
    "load_snapshot_chunk": abci.ResponseLoadSnapshotChunk,
    "apply_snapshot_chunk": abci.ResponseApplySnapshotChunk,
}


def _to_jsonable(obj: Any) -> Any:
    if obj is None or isinstance(obj, (int, float, str, bool)):
        return obj
    if isinstance(obj, bytes):
        return {"__b": base64.b64encode(obj).decode()}
    if isinstance(obj, enum.Enum):
        return int(obj.value)
    if isinstance(obj, cmttime.Timestamp):
        return {"__t": [obj.seconds, obj.nanos]}
    if dataclasses.is_dataclass(obj):
        return {
            f.name: _to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj)}")


def _from_jsonable(cls: type, data: Any) -> Any:
    if data is None:
        return None
    if isinstance(data, dict) and "__b" in data:
        return base64.b64decode(data["__b"])
    if isinstance(data, dict) and "__t" in data:
        return cmttime.Timestamp(*data["__t"])
    if dataclasses.is_dataclass(cls):
        kwargs = {}
        hints = {f.name: f.type for f in dataclasses.fields(cls)}
        resolved = _resolve_field_types(cls)
        for f in dataclasses.fields(cls):
            if f.name not in data:
                continue
            kwargs[f.name] = _coerce(resolved.get(f.name), data[f.name])
        return cls(**kwargs)
    return data


def _resolve_field_types(cls: type) -> dict[str, Any]:
    import typing

    try:
        return typing.get_type_hints(cls)
    except Exception:  # noqa: BLE001 - string annotations w/ fwd refs
        return {}


def _coerce(hint: Any, value: Any) -> Any:
    import typing

    if value is None:
        return None
    if isinstance(value, dict) and "__b" in value:
        return base64.b64decode(value["__b"])
    if isinstance(value, dict) and "__t" in value:
        return cmttime.Timestamp(*value["__t"])
    origin = typing.get_origin(hint)
    if origin in (list, tuple):
        (inner,) = typing.get_args(hint) or (None,)
        return [_coerce(inner, v) for v in value]
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _from_jsonable(hint, value)
        if issubclass(hint, enum.Enum):
            return hint(value)
    return value


def _frame(body: dict) -> bytes:
    raw = json.dumps(body, separators=(",", ":")).encode()
    return struct.pack(">I", len(raw)) + raw


def _read_frame(rfile) -> dict:
    hdr = rfile.read(4)
    if len(hdr) < 4:
        raise EOFError("connection closed")
    (n,) = struct.unpack(">I", hdr)
    if n > 64 * 1024 * 1024:
        raise ValueError("frame too large")
    raw = rfile.read(n)
    if len(raw) < n:
        raise EOFError("truncated frame")
    return json.loads(raw)


async def _read_frame_async(reader) -> dict:
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack(">I", hdr)
    if n > 64 * 1024 * 1024:
        raise ValueError("frame too large")
    raw = await reader.readexactly(n)
    return json.loads(raw)


def encode_request(method: str, req: Any) -> bytes:
    return _frame({"m": method, "r": _to_jsonable(req)})


def decode_request(rfile) -> tuple[str, Any]:
    return _decode_request_body(_read_frame(rfile))


def encode_response(method: str, resp: Any) -> bytes:
    return _frame({"m": method, "r": _to_jsonable(resp)})


def encode_exception(message: str) -> bytes:
    return _frame({"m": "exception", "r": message})


def decode_response(rfile) -> tuple[str, Any]:
    return _decode_response_body(_read_frame(rfile))


def _decode_request_body(body: dict) -> tuple[str, Any]:
    method = body["m"]
    cls = _REQUEST_TYPES.get(method)
    if cls is None:
        raise ValueError(f"unknown ABCI method {method!r}")
    return method, _from_jsonable(cls, body.get("r") or {})


def _decode_response_body(body: dict) -> tuple[str, Any]:
    method = body["m"]
    if method == "exception":
        return method, body.get("r")
    cls = _RESPONSE_TYPES.get(method)
    if cls is None:
        raise ValueError(f"unknown ABCI response {method!r}")
    return method, _from_jsonable(cls, body.get("r") or {})


async def decode_request_async(reader) -> tuple[str, Any]:
    return _decode_request_body(await _read_frame_async(reader))


async def decode_response_async(reader) -> tuple[str, Any]:
    return _decode_response_body(await _read_frame_async(reader))
