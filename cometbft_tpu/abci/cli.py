"""abci-cli: the standalone ABCI conformance/debug console.

Reference: abci/cmd/abci-cli/abci-cli.go — a client for exercising any ABCI
server (echo/info/query/check_tx/finalize_block/commit/proposals) plus a
built-in kvstore server, an interactive console, and batch mode over stdin.
Run as `python -m cometbft_tpu.abci.cli ...`; speaks the reference's
varint-delimited proto wire by default (--wire json for the framework
frame), so it drives reference apps and this framework's apps alike.

Tx arguments accept "0x"-prefixed hex or raw strings (abci-cli.go's
stringOrHexToBytes).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import shlex
import sys

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import ClientError, SocketClient

DEFAULT_ADDR = "tcp://127.0.0.1:26658"


def _arg_bytes(s: str) -> bytes:
    if s.startswith("0x") or s.startswith("0X"):
        return bytes.fromhex(s[2:])
    if len(s) >= 2 and s[0] == s[-1] and s[0] in "'\"":
        s = s[1:-1]
    return s.encode()


def _print_resp(resp) -> None:
    import base64
    import dataclasses
    import enum

    def enc(v):
        if isinstance(v, bytes):
            return {"hex": v.hex().upper(), "str": v.decode("utf-8", "replace")} if v else ""
        if isinstance(v, enum.Enum):
            return v.name
        if dataclasses.is_dataclass(v):
            return {f.name: enc(getattr(v, f.name))
                    for f in dataclasses.fields(v)}
        if isinstance(v, list):
            return [enc(x) for x in v]
        if hasattr(v, "seconds"):
            return {"seconds": v.seconds, "nanos": v.nanos}
        return v

    try:
        print(json.dumps(enc(resp), indent=1))
    except TypeError:
        print(resp)


async def _run_command(cli, cmd: str, args: list[str]) -> None:
    if cmd == "echo":
        resp = await cli.echo(args[0] if args else "")
    elif cmd == "info":
        resp = await cli.info(abci.RequestInfo(version="abci-cli"))
    elif cmd == "query":
        path = ""
        data = b""
        rest = list(args)
        while rest:
            a = rest.pop(0)
            if a == "--path":
                path = rest.pop(0)
            else:
                data = _arg_bytes(a)
        resp = await cli.query(abci.RequestQuery(path=path, data=data))
    elif cmd == "check_tx":
        resp = await cli.check_tx(abci.RequestCheckTx(tx=_arg_bytes(args[0])))
    elif cmd == "finalize_block":
        resp = await cli.finalize_block(abci.RequestFinalizeBlock(
            txs=[_arg_bytes(a) for a in args]))
    elif cmd == "prepare_proposal":
        resp = await cli.prepare_proposal(abci.RequestPrepareProposal(
            max_tx_bytes=1 << 22, txs=[_arg_bytes(a) for a in args]))
    elif cmd == "process_proposal":
        resp = await cli.process_proposal(abci.RequestProcessProposal(
            txs=[_arg_bytes(a) for a in args]))
    elif cmd == "commit":
        resp = await cli.commit(abci.RequestCommit())
    else:
        print(f"unknown command {cmd!r} "
              "(echo/info/query/check_tx/finalize_block/prepare_proposal/"
              "process_proposal/commit)", file=sys.stderr)
        return
    _print_resp(resp)


async def _console(cli, lines) -> None:
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = shlex.split(line)
        if parts[0] in ("quit", "exit"):
            return
        try:
            await _run_command(cli, parts[0], parts[1:])
        except ClientError as e:
            print(f"error: {e}", file=sys.stderr)


def _stdin_lines():
    if sys.stdin.isatty():
        while True:
            try:
                yield input("> ")
            except EOFError:
                return
    else:
        yield from sys.stdin


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="abci-cli", description=__doc__)
    p.add_argument("--address", default=DEFAULT_ADDR,
                   help=f"ABCI server address (default {DEFAULT_ADDR})")
    p.add_argument("--wire", choices=("proto", "json"), default="proto",
                   help="wire format: reference proto (default) or "
                        "framework json")
    p.add_argument("command", help="echo|info|query|check_tx|finalize_block|"
                                   "prepare_proposal|process_proposal|commit|"
                                   "console|batch|kvstore")
    # REMAINDER: command-local flags like `query --path /store k` must not
    # be eaten by this parser
    p.add_argument("args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)

    if ns.command == "kvstore":
        # built-in server, as in the reference (abci-cli kvstore); a
        # grpc:// address serves the tendermint.abci.ABCI gRPC service
        from cometbft_tpu.abci.kvstore import KVStoreApplication

        if ns.address.startswith("grpc://"):
            import time as _time

            from cometbft_tpu.abci.grpc import serve_grpc

            server, bound = serve_grpc(KVStoreApplication(), ns.address)
            print(f"abci-cli kvstore (grpc) listening on {bound}",
                  file=sys.stderr)
            try:
                while True:
                    _time.sleep(3600)
            except KeyboardInterrupt:
                server.stop(None)
            return 0

        from cometbft_tpu.abci.server import ABCIServer

        async def serve():
            srv = ABCIServer(KVStoreApplication(), ns.address)
            await srv.start()
            print(f"abci-cli kvstore listening on {srv.bound_addr()}",
                  file=sys.stderr)
            try:
                while True:
                    await asyncio.sleep(3600)
            finally:
                await srv.stop()

        try:
            asyncio.run(serve())
        except KeyboardInterrupt:
            pass
        return 0

    async def run():
        cli = SocketClient(ns.address, wire=ns.wire)
        try:
            if ns.command in ("console", "batch"):
                await _console(cli, _stdin_lines())
            else:
                await _run_command(cli, ns.command, ns.args)
        finally:
            await cli.close()

    try:
        asyncio.run(run())
    except ClientError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except (ConnectionError, OSError) as e:
        print(f"connection failed: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
