"""gogoproto wire codec for the ABCI boundary — reference interop.

This is the protobuf analog of codec.py's framework-native JSON frames: the
exact varint-length-delimited `tendermint.abci.Request`/`Response` oneof
encoding the reference speaks on its ABCI socket
(abci/client/socket_client.go:1-60 + libs/protoio/writer.go:93), hand-rolled
over utils/protobuf. Field numbers and wire rules follow
proto/tendermint/abci/types.proto:43-60 (Request oneof), :199-221 (Response
oneof) and the embedded tendermint.types / tendermint.crypto messages;
gogoproto's non-nullable message fields (always emitted) and stdtime/
stdduration encodings are preserved so the bytes match the reference's
generated marshallers. With this codec the reference's own kvstore app (or
any existing ABCI app) can serve this node, and this framework's apps can
serve a reference node.

Byte-exactness is asserted in tests/test_abci_proto_wire.py against
python-protobuf bindings compiled at test time from an independently
authored schema with the same field numbers.
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci
from cometbft_tpu.types.params import (
    ABCIParams,
    BlockParams,
    ConsensusParamsUpdate,
    EvidenceParams,
    ValidatorParams,
    VersionParams,
)
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb

MAX_MSG_SIZE = 64 * 1024 * 1024  # reference: abci/types/messages.go limits

# ---------------------------------------------------------------------------
# oneof tables (types.proto:43-60 / 199-221)
# ---------------------------------------------------------------------------

REQUEST_FIELDS = {
    "echo": 1, "flush": 2, "info": 3, "init_chain": 5, "query": 6,
    "check_tx": 8, "commit": 11, "list_snapshots": 12, "offer_snapshot": 13,
    "load_snapshot_chunk": 14, "apply_snapshot_chunk": 15,
    "prepare_proposal": 16, "process_proposal": 17, "extend_vote": 18,
    "verify_vote_extension": 19, "finalize_block": 20,
}
RESPONSE_FIELDS = {
    "exception": 1, "echo": 2, "flush": 3, "info": 4, "init_chain": 6,
    "query": 7, "check_tx": 9, "commit": 12, "list_snapshots": 13,
    "offer_snapshot": 14, "load_snapshot_chunk": 15,
    "apply_snapshot_chunk": 16, "prepare_proposal": 17,
    "process_proposal": 18, "extend_vote": 19, "verify_vote_extension": 20,
    "finalize_block": 21,
}
_REQ_BY_FIELD = {v: k for k, v in REQUEST_FIELDS.items()}
_RESP_BY_FIELD = {v: k for k, v in RESPONSE_FIELDS.items()}

_MISBEHAVIOR_TYPES = {"UNKNOWN": 0, "DUPLICATE_VOTE": 1, "LIGHT_CLIENT_ATTACK": 2}
_MISBEHAVIOR_NAMES = {v: k for k, v in _MISBEHAVIOR_TYPES.items()}

# tendermint.crypto.PublicKey oneof (crypto/keys.proto:9-17); sr25519 rides
# field 3 — a documented framework extension (the reference dropped sr25519
# from the oneof; interop peers that lack it reject such updates anyway)
_PUBKEY_FIELDS = {"ed25519": 1, "secp256k1": 2, "sr25519": 3}
_PUBKEY_NAMES = {v: k for k, v in _PUBKEY_FIELDS.items()}


# ---------------------------------------------------------------------------
# shared sub-messages
# ---------------------------------------------------------------------------


def _ts(t: cmttime.Timestamp | None) -> bytes:
    if t is None:
        return b""
    return pb.timestamp_bytes(t.seconds, t.nanos)


def _dec_ts(data: bytes) -> cmttime.Timestamp:
    r = pb.Reader(data)
    secs = nanos = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            secs = r.read_varint_i64()
        elif f == 2:
            nanos = r.read_varint_i64()
        else:
            r.skip(w)
    return cmttime.Timestamp(secs, nanos)


def _duration(ns: int) -> bytes:
    # truncation toward zero so seconds and nanos share a sign (gogoproto /
    # protobuf Duration rule: -1.5s is seconds=-1, nanos=-500000000, never
    # the mixed-sign pair Python floor division would produce)
    secs, nanos = divmod(abs(ns), 1_000_000_000)
    if ns < 0:
        secs, nanos = -secs, -nanos
    w = pb.Writer()
    w.varint_i64(1, secs)
    w.varint_i64(2, nanos)
    return w.output()


def _dec_duration(data: bytes) -> int:
    r = pb.Reader(data)
    secs = nanos = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            secs = r.read_varint_i64()
        elif f == 2:
            nanos = r.read_varint_i64()
        else:
            r.skip(w)
    return secs * 1_000_000_000 + nanos


def _enc_validator(address: bytes, power: int) -> bytes:
    w = pb.Writer()
    w.bytes(1, address)
    w.varint_i64(3, power)
    return w.output()


def _dec_validator(data: bytes) -> tuple[bytes, int]:
    r = pb.Reader(data)
    addr, power = b"", 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            addr = r.read_bytes()
        elif f == 3:
            power = r.read_varint_i64()
        else:
            r.skip(w)
    return addr, power


def _enc_vote_info(v: abci.VoteInfo) -> bytes:
    w = pb.Writer()
    w.message(1, _enc_validator(v.validator_address, v.validator_power),
              always=True)
    w.uvarint(3, int(v.block_id_flag))
    return w.output()


def _dec_vote_info(data: bytes) -> abci.VoteInfo:
    r = pb.Reader(data)
    addr, power, flag = b"", 0, 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            addr, power = _dec_validator(r.read_bytes())
        elif f == 3:
            flag = r.read_uvarint()
        else:
            r.skip(w)
    return abci.VoteInfo(validator_address=addr, validator_power=power,
                         block_id_flag=flag)


def _enc_ext_vote_info(v: abci.ExtendedVoteInfo) -> bytes:
    w = pb.Writer()
    w.message(1, _enc_validator(v.validator_address, v.validator_power),
              always=True)
    w.bytes(3, v.vote_extension)
    w.bytes(4, v.extension_signature)
    w.uvarint(5, int(v.block_id_flag))
    return w.output()


def _dec_ext_vote_info(data: bytes) -> abci.ExtendedVoteInfo:
    r = pb.Reader(data)
    addr, power, ext, sig, flag = b"", 0, b"", b"", 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            addr, power = _dec_validator(r.read_bytes())
        elif f == 3:
            ext = r.read_bytes()
        elif f == 4:
            sig = r.read_bytes()
        elif f == 5:
            flag = r.read_uvarint()
        else:
            r.skip(w)
    return abci.ExtendedVoteInfo(
        validator_address=addr, validator_power=power, block_id_flag=flag,
        vote_extension=ext, extension_signature=sig)


def _enc_commit_info(c: abci.CommitInfo) -> bytes:
    w = pb.Writer()
    w.varint_i64(1, c.round_)
    for v in c.votes:
        w.message(2, _enc_vote_info(v), always=True)
    return w.output()


def _dec_commit_info(data: bytes) -> abci.CommitInfo:
    r = pb.Reader(data)
    out = abci.CommitInfo(0)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.round_ = r.read_varint_i64()
        elif f == 2:
            out.votes.append(_dec_vote_info(r.read_bytes()))
        else:
            r.skip(w)
    return out


def _enc_ext_commit_info(c: abci.ExtendedCommitInfo) -> bytes:
    w = pb.Writer()
    w.varint_i64(1, c.round_)
    for v in c.votes:
        w.message(2, _enc_ext_vote_info(v), always=True)
    return w.output()


def _dec_ext_commit_info(data: bytes) -> abci.ExtendedCommitInfo:
    r = pb.Reader(data)
    out = abci.ExtendedCommitInfo(0)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.round_ = r.read_varint_i64()
        elif f == 2:
            out.votes.append(_dec_ext_vote_info(r.read_bytes()))
        else:
            r.skip(w)
    return out


def _enc_misbehavior(m: abci.Misbehavior) -> bytes:
    w = pb.Writer()
    w.uvarint(1, _MISBEHAVIOR_TYPES.get(m.type_, 0))
    w.message(2, _enc_validator(m.validator_address, m.validator_power),
              always=True)
    w.varint_i64(3, m.height)
    w.message(4, _ts(m.time), always=True)
    w.varint_i64(5, m.total_voting_power)
    return w.output()


def _dec_misbehavior(data: bytes) -> abci.Misbehavior:
    r = pb.Reader(data)
    kind, addr, power, height, t, tvp = 0, b"", 0, 0, cmttime.Timestamp.zero(), 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            kind = r.read_uvarint()
        elif f == 2:
            addr, power = _dec_validator(r.read_bytes())
        elif f == 3:
            height = r.read_varint_i64()
        elif f == 4:
            t = _dec_ts(r.read_bytes())
        elif f == 5:
            tvp = r.read_varint_i64()
        else:
            r.skip(w)
    return abci.Misbehavior(
        type_=_MISBEHAVIOR_NAMES.get(kind, "UNKNOWN"), validator_address=addr,
        validator_power=power, height=height, time=t, total_voting_power=tvp)


def _enc_snapshot(s: abci.Snapshot) -> bytes:
    w = pb.Writer()
    w.uvarint(1, s.height)
    w.uvarint(2, s.format_)
    w.uvarint(3, s.chunks)
    w.bytes(4, s.hash)
    w.bytes(5, s.metadata)
    return w.output()


def _dec_snapshot(data: bytes) -> abci.Snapshot:
    r = pb.Reader(data)
    s = abci.Snapshot(height=0, format_=0, chunks=0, hash=b"")
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            s.height = r.read_uvarint()
        elif f == 2:
            s.format_ = r.read_uvarint()
        elif f == 3:
            s.chunks = r.read_uvarint()
        elif f == 4:
            s.hash = r.read_bytes()
        elif f == 5:
            s.metadata = r.read_bytes()
        else:
            r.skip(w)
    return s


def _enc_validator_update(u: abci.ValidatorUpdate) -> bytes:
    pk = pb.Writer()
    pk.bytes(_PUBKEY_FIELDS.get(u.pub_key_type, 1), u.pub_key_bytes,
             always=True)
    w = pb.Writer()
    w.message(1, pk.output(), always=True)
    w.varint_i64(2, u.power)
    return w.output()


def _dec_validator_update(data: bytes) -> abci.ValidatorUpdate:
    r = pb.Reader(data)
    kt, kb, power = "ed25519", b"", 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            pk = pb.Reader(r.read_bytes())
            while not pk.at_end():
                pf, pw = pk.read_tag()
                if pf in _PUBKEY_NAMES:
                    kt = _PUBKEY_NAMES[pf]
                    kb = pk.read_bytes()
                else:
                    pk.skip(pw)
        elif f == 2:
            power = r.read_varint_i64()
        else:
            r.skip(w)
    return abci.ValidatorUpdate(pub_key_type=kt, pub_key_bytes=kb, power=power)


# -- tendermint.types.ConsensusParams (types/params.proto:13-18) ------------


def _enc_consensus_params(p) -> bytes | None:
    """Accepts ConsensusParams or ConsensusParamsUpdate (sections may be
    None); returns None for a nil params object."""
    if p is None:
        return None
    w = pb.Writer()
    b = getattr(p, "block", None)
    if b is not None:
        bw = pb.Writer()
        bw.varint_i64(1, b.max_bytes)
        bw.varint_i64(2, b.max_gas)
        w.message(1, bw.output(), always=True)
    e = getattr(p, "evidence", None)
    if e is not None:
        ew = pb.Writer()
        ew.varint_i64(1, e.max_age_num_blocks)
        ew.message(2, _duration(e.max_age_duration_ns), always=True)
        ew.varint_i64(3, e.max_bytes)
        w.message(2, ew.output(), always=True)
    v = getattr(p, "validator", None)
    if v is not None:
        vw = pb.Writer()
        for t in v.pub_key_types:
            vw.string(1, t, always=True)
        w.message(3, vw.output(), always=True)
    ver = getattr(p, "version", None)
    if ver is not None:
        vw = pb.Writer()
        vw.uvarint(1, ver.app)
        w.message(4, vw.output(), always=True)
    a = getattr(p, "abci", None)
    if a is not None:
        aw = pb.Writer()
        aw.varint_i64(1, a.vote_extensions_enable_height)
        w.message(5, aw.output(), always=True)
    return w.output()


def _dec_consensus_params(data: bytes) -> ConsensusParamsUpdate:
    out = ConsensusParamsUpdate()
    r = pb.Reader(data)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            b = pb.Reader(r.read_bytes())
            bp = BlockParams()
            while not b.at_end():
                bf, bw = b.read_tag()
                if bf == 1:
                    bp.max_bytes = b.read_varint_i64()
                elif bf == 2:
                    bp.max_gas = b.read_varint_i64()
                else:
                    b.skip(bw)
            out.block = bp
        elif f == 2:
            e = pb.Reader(r.read_bytes())
            ep = EvidenceParams()
            while not e.at_end():
                ef, ew = e.read_tag()
                if ef == 1:
                    ep.max_age_num_blocks = e.read_varint_i64()
                elif ef == 2:
                    ep.max_age_duration_ns = _dec_duration(e.read_bytes())
                elif ef == 3:
                    ep.max_bytes = e.read_varint_i64()
                else:
                    e.skip(ew)
            out.evidence = ep
        elif f == 3:
            v = pb.Reader(r.read_bytes())
            types = []
            while not v.at_end():
                vf, vw = v.read_tag()
                if vf == 1:
                    types.append(v.read_bytes().decode())
                else:
                    v.skip(vw)
            out.validator = ValidatorParams(pub_key_types=types)
        elif f == 4:
            v = pb.Reader(r.read_bytes())
            vp = VersionParams()
            while not v.at_end():
                vf, vw = v.read_tag()
                if vf == 1:
                    vp.app = v.read_uvarint()
                else:
                    v.skip(vw)
            out.version = vp
        elif f == 5:
            a = pb.Reader(r.read_bytes())
            ap = ABCIParams()
            while not a.at_end():
                af, aw = a.read_tag()
                if af == 1:
                    ap.vote_extensions_enable_height = a.read_varint_i64()
                else:
                    a.skip(aw)
            out.abci = ap
        else:
            r.skip(w)
    return out


def _enc_event(e: abci.Event) -> bytes:
    w = pb.Writer()
    w.string(1, e.type_)
    for a in e.attributes:
        aw = pb.Writer()
        aw.string(1, a.key)
        aw.string(2, a.value)
        aw.bool(3, a.index)
        w.message(2, aw.output(), always=True)
    return w.output()


def _dec_event(data: bytes) -> abci.Event:
    r = pb.Reader(data)
    out = abci.Event(type_="")
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.type_ = r.read_bytes().decode()
        elif f == 2:
            a = pb.Reader(r.read_bytes())
            attr = abci.EventAttribute(key="", value="", index=False)
            while not a.at_end():
                af, aw = a.read_tag()
                if af == 1:
                    attr.key = a.read_bytes().decode()
                elif af == 2:
                    attr.value = a.read_bytes().decode()
                elif af == 3:
                    attr.index = bool(a.read_uvarint())
                else:
                    a.skip(aw)
            out.attributes.append(attr)
        else:
            r.skip(w)
    return out


def _enc_tx_result_fields(w: pb.Writer, t) -> None:
    """Shared shape of ResponseCheckTx / ExecTxResult (fields 1-8)."""
    w.uvarint(1, t.code)
    w.bytes(2, t.data)
    w.string(3, t.log)
    w.string(4, t.info)
    w.varint_i64(5, t.gas_wanted)
    w.varint_i64(6, t.gas_used)
    for e in t.events:
        w.message(7, _enc_event(e), always=True)
    w.string(8, t.codespace)


def _dec_tx_result_fields(r: pb.Reader, out) -> None:
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.code = r.read_uvarint()
        elif f == 2:
            out.data = r.read_bytes()
        elif f == 3:
            out.log = r.read_bytes().decode()
        elif f == 4:
            out.info = r.read_bytes().decode()
        elif f == 5:
            out.gas_wanted = r.read_varint_i64()
        elif f == 6:
            out.gas_used = r.read_varint_i64()
        elif f == 7:
            out.events.append(_dec_event(r.read_bytes()))
        elif f == 8:
            out.codespace = r.read_bytes().decode()
        else:
            r.skip(w)


def _enc_proof_ops(ops: list) -> bytes | None:
    """tendermint.crypto.ProofOps: repeated ProofOp {type=1, key=2, data=3};
    elements may be objects with (type_, key, data) or 3-tuples."""
    if not ops:
        return None
    w = pb.Writer()
    for op in ops:
        if isinstance(op, tuple):
            t, k, d = op
        else:
            t, k, d = op.type_, op.key, op.data
        ow = pb.Writer()
        ow.string(1, t)
        ow.bytes(2, k)
        ow.bytes(3, d)
        w.message(1, ow.output(), always=True)
    return w.output()


def _dec_proof_ops(data: bytes) -> list:
    out = []
    r = pb.Reader(data)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            o = pb.Reader(r.read_bytes())
            t, k, d = "", b"", b""
            while not o.at_end():
                of, ow = o.read_tag()
                if of == 1:
                    t = o.read_bytes().decode()
                elif of == 2:
                    k = o.read_bytes()
                elif of == 3:
                    d = o.read_bytes()
                else:
                    o.skip(ow)
            out.append((t, k, d))
        else:
            r.skip(w)
    return out


# ---------------------------------------------------------------------------
# request bodies
# ---------------------------------------------------------------------------


def _enc_req_echo(q: abci.RequestEcho) -> bytes:
    return pb.Writer().string(1, q.message).output()


def _enc_req_flush(q) -> bytes:
    return b""


def _enc_req_info(q: abci.RequestInfo) -> bytes:
    w = pb.Writer()
    w.string(1, q.version)
    w.uvarint(2, q.block_version)
    w.uvarint(3, q.p2p_version)
    w.string(4, q.abci_version)
    return w.output()


def _enc_req_init_chain(q: abci.RequestInitChain) -> bytes:
    w = pb.Writer()
    w.message(1, _ts(q.time), always=True)
    w.string(2, q.chain_id)
    w.message(3, _enc_consensus_params(q.consensus_params))
    for u in q.validators:
        w.message(4, _enc_validator_update(u), always=True)
    w.bytes(5, q.app_state_bytes)
    w.varint_i64(6, q.initial_height)
    return w.output()


def _enc_req_query(q: abci.RequestQuery) -> bytes:
    w = pb.Writer()
    w.bytes(1, q.data)
    w.string(2, q.path)
    w.varint_i64(3, q.height)
    w.bool(4, q.prove)
    return w.output()


def _enc_req_check_tx(q: abci.RequestCheckTx) -> bytes:
    w = pb.Writer()
    w.bytes(1, q.tx)
    w.uvarint(2, int(q.type_))
    return w.output()


def _enc_req_offer_snapshot(q: abci.RequestOfferSnapshot) -> bytes:
    w = pb.Writer()
    if q.snapshot is not None:
        w.message(1, _enc_snapshot(q.snapshot), always=True)
    w.bytes(2, q.app_hash)
    return w.output()


def _enc_req_load_snapshot_chunk(q: abci.RequestLoadSnapshotChunk) -> bytes:
    w = pb.Writer()
    w.uvarint(1, q.height)
    w.uvarint(2, q.format_)
    w.uvarint(3, q.chunk)
    return w.output()


def _enc_req_apply_snapshot_chunk(q: abci.RequestApplySnapshotChunk) -> bytes:
    w = pb.Writer()
    w.uvarint(1, q.index)
    w.bytes(2, q.chunk)
    w.string(3, q.sender)
    return w.output()


def _enc_req_prepare_proposal(q: abci.RequestPrepareProposal) -> bytes:
    w = pb.Writer()
    w.varint_i64(1, q.max_tx_bytes)
    for tx in q.txs:
        w.bytes(2, tx, always=True)
    w.message(3, _enc_ext_commit_info(q.local_last_commit), always=True)
    for m in q.misbehavior:
        w.message(4, _enc_misbehavior(m), always=True)
    w.varint_i64(5, q.height)
    w.message(6, _ts(q.time), always=True)
    w.bytes(7, q.next_validators_hash)
    w.bytes(8, q.proposer_address)
    return w.output()


def _enc_req_process_proposal(q: abci.RequestProcessProposal) -> bytes:
    w = pb.Writer()
    for tx in q.txs:
        w.bytes(1, tx, always=True)
    w.message(2, _enc_commit_info(q.proposed_last_commit), always=True)
    for m in q.misbehavior:
        w.message(3, _enc_misbehavior(m), always=True)
    w.bytes(4, q.hash)
    w.varint_i64(5, q.height)
    w.message(6, _ts(q.time), always=True)
    w.bytes(7, q.next_validators_hash)
    w.bytes(8, q.proposer_address)
    return w.output()


def _enc_req_extend_vote(q: abci.RequestExtendVote) -> bytes:
    w = pb.Writer()
    w.bytes(1, q.hash)
    w.varint_i64(2, q.height)
    w.message(3, _ts(q.time), always=True)
    for tx in q.txs:
        w.bytes(4, tx, always=True)
    w.message(5, _enc_commit_info(q.proposed_last_commit), always=True)
    for m in q.misbehavior:
        w.message(6, _enc_misbehavior(m), always=True)
    w.bytes(7, q.next_validators_hash)
    w.bytes(8, q.proposer_address)
    return w.output()


def _enc_req_verify_vote_extension(q: abci.RequestVerifyVoteExtension) -> bytes:
    w = pb.Writer()
    w.bytes(1, q.hash)
    w.bytes(2, q.validator_address)
    w.varint_i64(3, q.height)
    w.bytes(4, q.vote_extension)
    return w.output()


def _enc_req_finalize_block(q: abci.RequestFinalizeBlock) -> bytes:
    w = pb.Writer()
    for tx in q.txs:
        w.bytes(1, tx, always=True)
    w.message(2, _enc_commit_info(q.decided_last_commit), always=True)
    for m in q.misbehavior:
        w.message(3, _enc_misbehavior(m), always=True)
    w.bytes(4, q.hash)
    w.varint_i64(5, q.height)
    w.message(6, _ts(q.time), always=True)
    w.bytes(7, q.next_validators_hash)
    w.bytes(8, q.proposer_address)
    return w.output()


_REQ_ENCODERS = {
    "echo": _enc_req_echo,
    "flush": _enc_req_flush,
    "info": _enc_req_info,
    "init_chain": _enc_req_init_chain,
    "query": _enc_req_query,
    "check_tx": _enc_req_check_tx,
    "commit": lambda q: b"",
    "list_snapshots": lambda q: b"",
    "offer_snapshot": _enc_req_offer_snapshot,
    "load_snapshot_chunk": _enc_req_load_snapshot_chunk,
    "apply_snapshot_chunk": _enc_req_apply_snapshot_chunk,
    "prepare_proposal": _enc_req_prepare_proposal,
    "process_proposal": _enc_req_process_proposal,
    "extend_vote": _enc_req_extend_vote,
    "verify_vote_extension": _enc_req_verify_vote_extension,
    "finalize_block": _enc_req_finalize_block,
}


def _dec_req_echo(data: bytes) -> abci.RequestEcho:
    r = pb.Reader(data)
    out = abci.RequestEcho()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.message = r.read_bytes().decode()
        else:
            r.skip(w)
    return out


def _dec_req_info(data: bytes) -> abci.RequestInfo:
    r = pb.Reader(data)
    out = abci.RequestInfo()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.version = r.read_bytes().decode()
        elif f == 2:
            out.block_version = r.read_uvarint()
        elif f == 3:
            out.p2p_version = r.read_uvarint()
        elif f == 4:
            out.abci_version = r.read_bytes().decode()
        else:
            r.skip(w)
    return out


def _dec_req_init_chain(data: bytes) -> abci.RequestInitChain:
    r = pb.Reader(data)
    out = abci.RequestInitChain(initial_height=0)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.time = _dec_ts(r.read_bytes())
        elif f == 2:
            out.chain_id = r.read_bytes().decode()
        elif f == 3:
            out.consensus_params = _dec_consensus_params(r.read_bytes())
        elif f == 4:
            out.validators.append(_dec_validator_update(r.read_bytes()))
        elif f == 5:
            out.app_state_bytes = r.read_bytes()
        elif f == 6:
            out.initial_height = r.read_varint_i64()
        else:
            r.skip(w)
    return out


def _dec_req_query(data: bytes) -> abci.RequestQuery:
    r = pb.Reader(data)
    out = abci.RequestQuery()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.data = r.read_bytes()
        elif f == 2:
            out.path = r.read_bytes().decode()
        elif f == 3:
            out.height = r.read_varint_i64()
        elif f == 4:
            out.prove = bool(r.read_uvarint())
        else:
            r.skip(w)
    return out


def _dec_req_check_tx(data: bytes) -> abci.RequestCheckTx:
    r = pb.Reader(data)
    out = abci.RequestCheckTx()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.tx = r.read_bytes()
        elif f == 2:
            out.type_ = abci.CheckTxType(r.read_uvarint())
        else:
            r.skip(w)
    return out


def _dec_req_offer_snapshot(data: bytes) -> abci.RequestOfferSnapshot:
    r = pb.Reader(data)
    out = abci.RequestOfferSnapshot()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.snapshot = _dec_snapshot(r.read_bytes())
        elif f == 2:
            out.app_hash = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_req_load_snapshot_chunk(data: bytes) -> abci.RequestLoadSnapshotChunk:
    r = pb.Reader(data)
    out = abci.RequestLoadSnapshotChunk()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.height = r.read_uvarint()
        elif f == 2:
            out.format_ = r.read_uvarint()
        elif f == 3:
            out.chunk = r.read_uvarint()
        else:
            r.skip(w)
    return out


def _dec_req_apply_snapshot_chunk(data: bytes) -> abci.RequestApplySnapshotChunk:
    r = pb.Reader(data)
    out = abci.RequestApplySnapshotChunk()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.index = r.read_uvarint()
        elif f == 2:
            out.chunk = r.read_bytes()
        elif f == 3:
            out.sender = r.read_bytes().decode()
        else:
            r.skip(w)
    return out


def _dec_req_prepare_proposal(data: bytes) -> abci.RequestPrepareProposal:
    r = pb.Reader(data)
    out = abci.RequestPrepareProposal()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.max_tx_bytes = r.read_varint_i64()
        elif f == 2:
            out.txs.append(r.read_bytes())
        elif f == 3:
            out.local_last_commit = _dec_ext_commit_info(r.read_bytes())
        elif f == 4:
            out.misbehavior.append(_dec_misbehavior(r.read_bytes()))
        elif f == 5:
            out.height = r.read_varint_i64()
        elif f == 6:
            out.time = _dec_ts(r.read_bytes())
        elif f == 7:
            out.next_validators_hash = r.read_bytes()
        elif f == 8:
            out.proposer_address = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_req_process_proposal(data: bytes) -> abci.RequestProcessProposal:
    r = pb.Reader(data)
    out = abci.RequestProcessProposal()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.txs.append(r.read_bytes())
        elif f == 2:
            out.proposed_last_commit = _dec_commit_info(r.read_bytes())
        elif f == 3:
            out.misbehavior.append(_dec_misbehavior(r.read_bytes()))
        elif f == 4:
            out.hash = r.read_bytes()
        elif f == 5:
            out.height = r.read_varint_i64()
        elif f == 6:
            out.time = _dec_ts(r.read_bytes())
        elif f == 7:
            out.next_validators_hash = r.read_bytes()
        elif f == 8:
            out.proposer_address = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_req_extend_vote(data: bytes) -> abci.RequestExtendVote:
    r = pb.Reader(data)
    out = abci.RequestExtendVote()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.hash = r.read_bytes()
        elif f == 2:
            out.height = r.read_varint_i64()
        elif f == 3:
            out.time = _dec_ts(r.read_bytes())
        elif f == 4:
            out.txs.append(r.read_bytes())
        elif f == 5:
            out.proposed_last_commit = _dec_commit_info(r.read_bytes())
        elif f == 6:
            out.misbehavior.append(_dec_misbehavior(r.read_bytes()))
        elif f == 7:
            out.next_validators_hash = r.read_bytes()
        elif f == 8:
            out.proposer_address = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_req_verify_vote_extension(data: bytes) -> abci.RequestVerifyVoteExtension:
    r = pb.Reader(data)
    out = abci.RequestVerifyVoteExtension()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.hash = r.read_bytes()
        elif f == 2:
            out.validator_address = r.read_bytes()
        elif f == 3:
            out.height = r.read_varint_i64()
        elif f == 4:
            out.vote_extension = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_req_finalize_block(data: bytes) -> abci.RequestFinalizeBlock:
    r = pb.Reader(data)
    out = abci.RequestFinalizeBlock()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.txs.append(r.read_bytes())
        elif f == 2:
            out.decided_last_commit = _dec_commit_info(r.read_bytes())
        elif f == 3:
            out.misbehavior.append(_dec_misbehavior(r.read_bytes()))
        elif f == 4:
            out.hash = r.read_bytes()
        elif f == 5:
            out.height = r.read_varint_i64()
        elif f == 6:
            out.time = _dec_ts(r.read_bytes())
        elif f == 7:
            out.next_validators_hash = r.read_bytes()
        elif f == 8:
            out.proposer_address = r.read_bytes()
        else:
            r.skip(w)
    return out


_REQ_DECODERS = {
    "echo": _dec_req_echo,
    "flush": lambda d: abci.RequestFlush(),
    "info": _dec_req_info,
    "init_chain": _dec_req_init_chain,
    "query": _dec_req_query,
    "check_tx": _dec_req_check_tx,
    "commit": lambda d: abci.RequestCommit(),
    "list_snapshots": lambda d: abci.RequestListSnapshots(),
    "offer_snapshot": _dec_req_offer_snapshot,
    "load_snapshot_chunk": _dec_req_load_snapshot_chunk,
    "apply_snapshot_chunk": _dec_req_apply_snapshot_chunk,
    "prepare_proposal": _dec_req_prepare_proposal,
    "process_proposal": _dec_req_process_proposal,
    "extend_vote": _dec_req_extend_vote,
    "verify_vote_extension": _dec_req_verify_vote_extension,
    "finalize_block": _dec_req_finalize_block,
}


# ---------------------------------------------------------------------------
# response bodies
# ---------------------------------------------------------------------------


def _enc_resp_info(p: abci.ResponseInfo) -> bytes:
    w = pb.Writer()
    w.string(1, p.data)
    w.string(2, p.version)
    w.uvarint(3, p.app_version)
    w.varint_i64(4, p.last_block_height)
    w.bytes(5, p.last_block_app_hash)
    return w.output()


def _enc_resp_init_chain(p: abci.ResponseInitChain) -> bytes:
    w = pb.Writer()
    w.message(1, _enc_consensus_params(p.consensus_params))
    for u in p.validators:
        w.message(2, _enc_validator_update(u), always=True)
    w.bytes(3, p.app_hash)
    return w.output()


def _enc_resp_query(p: abci.ResponseQuery) -> bytes:
    w = pb.Writer()
    w.uvarint(1, p.code)
    w.string(3, p.log)
    w.string(4, p.info)
    w.varint_i64(5, p.index)
    w.bytes(6, p.key)
    w.bytes(7, p.value)
    w.message(8, _enc_proof_ops(p.proof_ops))
    w.varint_i64(9, p.height)
    w.string(10, p.codespace)
    return w.output()


def _enc_resp_check_tx(p: abci.ResponseCheckTx) -> bytes:
    w = pb.Writer()
    _enc_tx_result_fields(w, p)
    return w.output()


def _enc_resp_commit(p: abci.ResponseCommit) -> bytes:
    return pb.Writer().varint_i64(3, p.retain_height).output()


def _enc_resp_list_snapshots(p: abci.ResponseListSnapshots) -> bytes:
    w = pb.Writer()
    for s in p.snapshots:
        w.message(1, _enc_snapshot(s), always=True)
    return w.output()


def _enc_resp_apply_snapshot_chunk(p: abci.ResponseApplySnapshotChunk) -> bytes:
    w = pb.Writer()
    w.uvarint(1, int(p.result))
    if p.refetch_chunks:  # packed repeated uint32
        body = b"".join(pb.encode_uvarint(c) for c in p.refetch_chunks)
        w.bytes(2, body, always=True)
    for s in p.reject_senders:
        w.string(3, s, always=True)
    return w.output()


def _enc_resp_finalize_block(p: abci.ResponseFinalizeBlock) -> bytes:
    w = pb.Writer()
    for e in p.events:
        w.message(1, _enc_event(e), always=True)
    for t in p.tx_results:
        tw = pb.Writer()
        _enc_tx_result_fields(tw, t)
        w.message(2, tw.output(), always=True)
    for u in p.validator_updates:
        w.message(3, _enc_validator_update(u), always=True)
    w.message(4, _enc_consensus_params(p.consensus_param_updates))
    w.bytes(5, p.app_hash)
    return w.output()


_RESP_ENCODERS = {
    "exception": lambda p: pb.Writer().string(1, p if isinstance(p, str) else str(p)).output(),
    "echo": lambda p: pb.Writer().string(1, p.message).output(),
    "flush": lambda p: b"",
    "info": _enc_resp_info,
    "init_chain": _enc_resp_init_chain,
    "query": _enc_resp_query,
    "check_tx": _enc_resp_check_tx,
    "commit": _enc_resp_commit,
    "list_snapshots": _enc_resp_list_snapshots,
    "offer_snapshot": lambda p: pb.Writer().uvarint(1, int(p.result)).output(),
    "load_snapshot_chunk": lambda p: pb.Writer().bytes(1, p.chunk).output(),
    "apply_snapshot_chunk": _enc_resp_apply_snapshot_chunk,
    "prepare_proposal": lambda p: _enc_repeated_bytes(1, p.txs),
    "process_proposal": lambda p: pb.Writer().uvarint(1, int(p.status)).output(),
    "extend_vote": lambda p: pb.Writer().bytes(1, p.vote_extension).output(),
    "verify_vote_extension": lambda p: pb.Writer().uvarint(1, int(p.status)).output(),
    "finalize_block": _enc_resp_finalize_block,
}


def _enc_repeated_bytes(field: int, items: list[bytes]) -> bytes:
    w = pb.Writer()
    for b in items:
        w.bytes(field, b, always=True)
    return w.output()


def _dec_resp_info(data: bytes) -> abci.ResponseInfo:
    r = pb.Reader(data)
    out = abci.ResponseInfo()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.data = r.read_bytes().decode()
        elif f == 2:
            out.version = r.read_bytes().decode()
        elif f == 3:
            out.app_version = r.read_uvarint()
        elif f == 4:
            out.last_block_height = r.read_varint_i64()
        elif f == 5:
            out.last_block_app_hash = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_resp_init_chain(data: bytes) -> abci.ResponseInitChain:
    r = pb.Reader(data)
    out = abci.ResponseInitChain()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.consensus_params = _dec_consensus_params(r.read_bytes())
        elif f == 2:
            out.validators.append(_dec_validator_update(r.read_bytes()))
        elif f == 3:
            out.app_hash = r.read_bytes()
        else:
            r.skip(w)
    return out


def _dec_resp_query(data: bytes) -> abci.ResponseQuery:
    r = pb.Reader(data)
    out = abci.ResponseQuery()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.code = r.read_uvarint()
        elif f == 3:
            out.log = r.read_bytes().decode()
        elif f == 4:
            out.info = r.read_bytes().decode()
        elif f == 5:
            out.index = r.read_varint_i64()
        elif f == 6:
            out.key = r.read_bytes()
        elif f == 7:
            out.value = r.read_bytes()
        elif f == 8:
            out.proof_ops = _dec_proof_ops(r.read_bytes())
        elif f == 9:
            out.height = r.read_varint_i64()
        elif f == 10:
            out.codespace = r.read_bytes().decode()
        else:
            r.skip(w)
    return out


def _dec_resp_check_tx(data: bytes) -> abci.ResponseCheckTx:
    out = abci.ResponseCheckTx()
    _dec_tx_result_fields(pb.Reader(data), out)
    return out


def _dec_resp_commit(data: bytes) -> abci.ResponseCommit:
    r = pb.Reader(data)
    out = abci.ResponseCommit()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 3:
            out.retain_height = r.read_varint_i64()
        else:
            r.skip(w)
    return out


def _dec_resp_list_snapshots(data: bytes) -> abci.ResponseListSnapshots:
    r = pb.Reader(data)
    out = abci.ResponseListSnapshots()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.snapshots.append(_dec_snapshot(r.read_bytes()))
        else:
            r.skip(w)
    return out


def _dec_resp_apply_snapshot_chunk(data: bytes) -> abci.ResponseApplySnapshotChunk:
    r = pb.Reader(data)
    out = abci.ResponseApplySnapshotChunk()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.result = abci.ApplySnapshotChunkResult(r.read_uvarint())
        elif f == 2:
            if w == 2:  # packed
                inner = pb.Reader(r.read_bytes())
                while not inner.at_end():
                    out.refetch_chunks.append(inner.read_uvarint())
            else:
                out.refetch_chunks.append(r.read_uvarint())
        elif f == 3:
            out.reject_senders.append(r.read_bytes().decode())
        else:
            r.skip(w)
    return out


def _dec_resp_prepare_proposal(data: bytes) -> abci.ResponsePrepareProposal:
    r = pb.Reader(data)
    out = abci.ResponsePrepareProposal()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.txs.append(r.read_bytes())
        else:
            r.skip(w)
    return out


def _dec_resp_finalize_block(data: bytes) -> abci.ResponseFinalizeBlock:
    r = pb.Reader(data)
    out = abci.ResponseFinalizeBlock()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.events.append(_dec_event(r.read_bytes()))
        elif f == 2:
            t = abci.ExecTxResult()
            _dec_tx_result_fields(pb.Reader(r.read_bytes()), t)
            out.tx_results.append(t)
        elif f == 3:
            out.validator_updates.append(_dec_validator_update(r.read_bytes()))
        elif f == 4:
            out.consensus_param_updates = _dec_consensus_params(r.read_bytes())
        elif f == 5:
            out.app_hash = r.read_bytes()
        else:
            r.skip(w)
    return out


_RESP_DECODERS = {
    "exception": lambda d: _dec_exception(d),
    "echo": lambda d: _dec_resp_echo(d),
    "flush": lambda d: abci.ResponseFlush(),
    "info": _dec_resp_info,
    "init_chain": _dec_resp_init_chain,
    "query": _dec_resp_query,
    "check_tx": _dec_resp_check_tx,
    "commit": _dec_resp_commit,
    "list_snapshots": _dec_resp_list_snapshots,
    "offer_snapshot": lambda d: abci.ResponseOfferSnapshot(
        result=abci.OfferSnapshotResult(_dec_single_uvarint(d, 1))),
    "load_snapshot_chunk": lambda d: abci.ResponseLoadSnapshotChunk(
        chunk=_dec_single_bytes(d, 1)),
    "apply_snapshot_chunk": _dec_resp_apply_snapshot_chunk,
    "prepare_proposal": _dec_resp_prepare_proposal,
    "process_proposal": lambda d: abci.ResponseProcessProposal(
        status=abci.ProposalStatus(_dec_single_uvarint(d, 1))),
    "extend_vote": lambda d: abci.ResponseExtendVote(
        vote_extension=_dec_single_bytes(d, 1)),
    "verify_vote_extension": lambda d: abci.ResponseVerifyVoteExtension(
        status=abci.VerifyStatus(_dec_single_uvarint(d, 1))),
    "finalize_block": _dec_resp_finalize_block,
}


def _dec_exception(data: bytes) -> str:
    r = pb.Reader(data)
    msg = ""
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            msg = r.read_bytes().decode()
        else:
            r.skip(w)
    return msg


def _dec_resp_echo(data: bytes) -> abci.ResponseEcho:
    r = pb.Reader(data)
    out = abci.ResponseEcho()
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            out.message = r.read_bytes().decode()
        else:
            r.skip(w)
    return out


def _dec_single_uvarint(data: bytes, field: int) -> int:
    r = pb.Reader(data)
    v = 0
    while not r.at_end():
        f, w = r.read_tag()
        if f == field:
            v = r.read_uvarint()
        else:
            r.skip(w)
    return v


def _dec_single_bytes(data: bytes, field: int) -> bytes:
    r = pb.Reader(data)
    v = b""
    while not r.at_end():
        f, w = r.read_tag()
        if f == field:
            v = r.read_bytes()
        else:
            r.skip(w)
    return v


# ---------------------------------------------------------------------------
# Request / Response oneof wrappers + varint-delimited framing
# ---------------------------------------------------------------------------


def encode_request(method: str, req) -> bytes:
    """-> varint-delimited `Request` (the reference's WriteMsg bytes)."""
    field = REQUEST_FIELDS.get(method)
    if field is None:
        raise ValueError(f"unknown ABCI method {method!r}")
    body = _REQ_ENCODERS[method](req)
    w = pb.Writer()
    w.bytes(field, body, always=True)
    return pb.marshal_delimited(w.output())


def encode_response(method: str, resp) -> bytes:
    field = RESPONSE_FIELDS.get(method)
    if field is None:
        raise ValueError(f"unknown ABCI response {method!r}")
    body = _RESP_ENCODERS[method](resp)
    w = pb.Writer()
    w.bytes(field, body, always=True)
    return pb.marshal_delimited(w.output())


def encode_exception(message: str) -> bytes:
    return encode_response("exception", message)


def _decode_oneof(data: bytes, by_field: dict, decoders: dict, kind: str):
    r = pb.Reader(data)
    if r.at_end():
        raise ValueError(f"empty ABCI {kind}")
    f, w = r.read_tag()
    method = by_field.get(f)
    if method is None:
        raise ValueError(f"unknown ABCI {kind} oneof field {f}")
    if w != 2:
        raise ValueError(f"bad wire type {w} for ABCI {kind} oneof")
    return method, decoders[method](r.read_bytes())


def decode_request_bytes(data: bytes):
    return _decode_oneof(data, _REQ_BY_FIELD, _REQ_DECODERS, "request")


def decode_response_bytes(data: bytes):
    return _decode_oneof(data, _RESP_BY_FIELD, _RESP_DECODERS, "response")


async def read_delimited_async(reader, first_byte: bytes = b"",
                               max_size: int = MAX_MSG_SIZE) -> bytes:
    """Read one varint-length-delimited message from any object with an
    async readexactly() (libs/protoio/reader.go semantics). first_byte: a
    prefix byte the caller already consumed (the server's wire
    autodetector). Shared by the ABCI socket and the p2p secret-connection
    handshake — the single implementation of this framing."""
    n = 0
    shift = 0
    pre = first_byte
    while True:
        if pre:
            b, pre = pre, b""
        else:
            b = await reader.readexactly(1)
        if shift == 63 and b[0] > 1:
            raise ValueError("varint length prefix overflows uint64")
        n |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("varint length prefix too long")
    if n > max_size:
        raise ValueError(f"message of {n} bytes exceeds {max_size}")
    return await reader.readexactly(n)


async def decode_request_async(reader):
    return decode_request_bytes(await read_delimited_async(reader))


async def decode_response_async(reader):
    return decode_response_bytes(await read_delimited_async(reader))
