"""ABCI 2.0 — the application boundary (reference: abci/).

The replicated state machine is EXTERNAL to the consensus engine; everything
the framework knows of app state is the AppHash and the responses to these
17 methods (abci/types/application.go:9-35). Subpackages:

  types.py    request/response dataclasses + Application ABC + BaseApplication
  client.py   client abstraction: local (in-proc) and socket transports
  server.py   socket server hosting an Application out-of-process
  kvstore.py  the example app (abci/example/kvstore) used by tests/harness
"""

from cometbft_tpu.abci.types import (  # noqa: F401
    Application,
    BaseApplication,
    CODE_TYPE_OK,
)
