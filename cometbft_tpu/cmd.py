"""Command-line interface.

Reference: cmd/cometbft/main.go:16-46 (cobra command tree). argparse is
the idiomatic Python analog. Commands:

  init        write config.toml, genesis.json, node + validator keys
  start       run a node from the home dir
  testnet     generate N validator home dirs wired as persistent peers
  show-node-id
  show-validator
  version

Env: CMT_HOME overrides --home (main.go:48 env prefix analog).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

from cometbft_tpu.version import CMTSemVer as VERSION


def _home(args) -> str:
    return args.home or os.environ.get("CMT_HOME", os.path.expanduser("~/.cometbft_tpu"))


def cmd_init(args) -> int:
    from cometbft_tpu.node import init_files

    home = _home(args)
    init_files(home, chain_id=args.chain_id, moniker=args.moniker)
    print(f"Initialized node home at {home}")
    return 0


def cmd_start(args) -> int:
    import faulthandler

    from cometbft_tpu.config import Config
    from cometbft_tpu.node import Node

    # stack dump on demand (SIGUSR1) — the operator analog of the
    # reference's pprof goroutine dump (cmd/cometbft/commands/debug)
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    home = _home(args)
    config = Config.load(home)
    if args.proxy_app:
        config.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        config.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        config.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        config.p2p.persistent_peers = args.persistent_peers
    if args.crypto_backend:
        config.crypto.backend = args.crypto_backend
    if args.log_level:
        config.base.log_level = args.log_level

    async def run():
        node = Node(config)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await node.start()
        node.logger.info("node started", node_id=node.node_key.id(),
                         chain=node.genesis_doc.chain_id)
        await stop.wait()
        node.logger.info("shutting down")
        await node.stop()

    asyncio.run(run())
    return 0


def cmd_testnet(args) -> int:
    """cmd/cometbft/commands/testnet.go: N validator homes under --o, each
    with the full genesis and persistent_peers pointing at the others."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.node import init_files
    from cometbft_tpu.p2p.key import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
    from cometbft_tpu.utils import cmttime

    n = args.v
    out = args.o
    chain_id = args.chain_id or f"chain-{os.urandom(3).hex()}"
    homes = [os.path.join(out, f"node{i}") for i in range(n)]
    pvs, node_keys = [], []
    for home in homes:
        cfg = Config(home=home)
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pvs.append(FilePV.load_or_generate(
            cfg.priv_validator_key_path(), cfg.priv_validator_state_path()))
        node_keys.append(NodeKey.load_or_gen(cfg.node_key_path()))

    gdoc = GenesisDoc(
        genesis_time=cmttime.canonical_now_ms(),
        chain_id=chain_id,
        validators=[
            GenesisValidator(
                address=pv.get_pub_key().address(),
                pub_key=pv.get_pub_key(),
                power=1,
                name=f"node{i}",
            )
            for i, pv in enumerate(pvs)
        ],
    )
    gdoc.validate_and_complete()

    base_p2p, base_rpc = args.starting_port, args.starting_port + 1000
    addrs = [
        f"{node_keys[i].id()}@127.0.0.1:{base_p2p + i}" for i in range(n)
    ]
    for i, home in enumerate(homes):
        cfg = Config(home=home)
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_p2p + i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_rpc + i}"
        cfg.p2p.persistent_peers = ",".join(a for j, a in enumerate(addrs) if j != i)
        # N processes sharing one host cannot share one TPU chip; local
        # testnets verify on CPU (flip per-node for a real multi-host net)
        cfg.crypto.backend = "cpu"
        cfg.save()
        with open(cfg.genesis_path(), "w") as f:
            f.write(gdoc.to_json())
    print(f"Successfully initialized {n} node directories under {out} (chain {chain_id})")
    return 0


def cmd_show_node_id(args) -> int:
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey

    cfg = Config.load(_home(args))
    print(NodeKey.load_or_gen(cfg.node_key_path()).id())
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from cometbft_tpu.config import Config
    from cometbft_tpu.privval.file_pv import FilePV

    cfg = Config.load(_home(args))
    pv = FilePV.load_or_generate(
        cfg.priv_validator_key_path(), cfg.priv_validator_state_path())
    pk = pv.get_pub_key()
    print(json.dumps({"type": pk.type_(),
                      "value": base64.b64encode(pk.bytes_()).decode()}))
    return 0


def _reset_file_pv(key_file: str, state_file: str) -> None:
    """Reference resetFilePV (commands/reset.go:100-118): if the key file
    exists, zero the sign-state only (the key survives); otherwise generate
    a fresh validator."""
    from cometbft_tpu.privval.file_pv import FilePV, _LastSignState

    os.makedirs(os.path.dirname(state_file) or ".", exist_ok=True)
    if os.path.exists(key_file):
        pv = FilePV.load(key_file, "")
        pv.state_file = state_file
        pv.last_sign_state = _LastSignState()
        pv._save_state()
        print(f"Reset private validator file to genesis state: {state_file}")
    else:
        os.makedirs(os.path.dirname(key_file) or ".", exist_ok=True)
        pv = FilePV.generate(key_file, state_file)
        pv._save_state()
        print(f"Generated private validator file: {key_file}")


def _reset_state(cfg) -> None:
    """Remove databases + WAL (commands/reset.go resetState)."""
    import shutil

    db_dir = cfg._abs(cfg.base.db_dir)
    for name in ("blockstore", "state", "tx_index", "evidence", "light"):
        p = cfg.db_path(name)
        # sqlite runs journal_mode=WAL (store/db.py): a stale -wal/-shm
        # sidecar next to a freshly created empty db corrupts it on replay,
        # so the sidecars must go with the main file
        for f in (p, p + "-wal", p + "-shm"):
            if os.path.exists(f):
                os.remove(f)
                print(f"Removed {f}")
    wal = cfg.wal_path()
    if os.path.isdir(wal):
        shutil.rmtree(wal, ignore_errors=True)
        print(f"Removed WAL {wal}")
    os.makedirs(db_dir, exist_ok=True)


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go:20-40 — remove all data, reset privval state,
    drop the address book (unless --keep-addr-book)."""
    from cometbft_tpu.config import Config

    cfg = Config.load(_home(args))
    _reset_state(cfg)
    if not args.keep_addr_book:
        ab = cfg._abs(cfg.p2p.addr_book_file)
        if os.path.exists(ab):
            os.remove(ab)
            print(f"Removed address book {ab}")
    else:
        print("The address book remains intact")
    _reset_file_pv(cfg.priv_validator_key_path(),
                   cfg.priv_validator_state_path())
    return 0


def cmd_reset_state(args) -> int:
    from cometbft_tpu.config import Config

    _reset_state(Config.load(_home(args)))
    return 0


def cmd_reset_priv_validator(args) -> int:
    from cometbft_tpu.config import Config

    cfg = Config.load(_home(args))
    _reset_file_pv(cfg.priv_validator_key_path(),
                   cfg.priv_validator_state_path())
    return 0


def cmd_gen_validator(_args) -> int:
    """commands/gen_validator.go — print a fresh validator key doc."""
    import base64

    from cometbft_tpu.privval.file_pv import FilePV

    pv = FilePV.generate()
    pub = pv.priv_key.pub_key()
    print(json.dumps({
        "address": pub.address().hex().upper(),
        "pub_key": {"type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(pub.bytes_()).decode()},
        "priv_key": {"type": "tendermint/PrivKeyEd25519",
                     "value": base64.b64encode(pv.priv_key.bytes_()).decode()},
    }, indent=2))
    return 0


def cmd_gen_node_key(args) -> int:
    """commands/gen_node_key.go — write node_key.json (if absent) and print
    the node ID."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.p2p.key import NodeKey

    cfg = Config.load(_home(args))
    path = cfg.node_key_path()
    if os.path.exists(path):
        print(f"node key already exists at {path}", file=sys.stderr)
        return 1
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    print(NodeKey.load_or_gen(path).id())
    return 0


def cmd_compact_db(args) -> int:
    """commands/compact.go analog: force-compact the sqlite stores of a
    STOPPED node (VACUUM reclaims pruned heights' pages)."""
    import sqlite3

    from cometbft_tpu.config import Config

    cfg = Config.load(_home(args))
    if cfg.base.db_backend not in ("sqlite", "goleveldb", ""):
        print(f"compaction not supported for backend {cfg.base.db_backend}",
              file=sys.stderr)
        return 1
    for name in ("blockstore", "state", "tx_index", "evidence", "light"):
        p = cfg.db_path(name)
        if not os.path.exists(p):
            continue
        before = os.path.getsize(p)
        conn = sqlite3.connect(p)
        try:
            conn.execute("VACUUM")
            conn.commit()
        finally:
            conn.close()
        print(f"compacted {name}: {before} -> {os.path.getsize(p)} bytes")
    return 0


def cmd_rollback(args) -> int:
    """cmd/cometbft/commands/rollback.go: revert state (and optionally the
    block) by one height so the app can re-run the last block."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.state.rollback import rollback
    from cometbft_tpu.state.store import StateStore
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.store.db import open_db

    cfg = Config.load(_home(args))
    block_store = BlockStore(open_db(
        cfg.base.db_backend, cfg.db_path("blockstore"),
        checksum=cfg.storage.checksum))
    state_store = StateStore(open_db(
        cfg.base.db_backend, cfg.db_path("state"),
        checksum=cfg.storage.checksum))
    height, app_hash = rollback(block_store, state_store,
                                remove_block=args.hard)
    print(f"Rolled back state to height {height} and hash {app_hash.hex().upper()}")
    return 0


def cmd_wal_repair(args) -> int:
    """Repair a mid-group-corrupted consensus WAL on a STOPPED node (the
    knob consensus/wal.py's WALCorruptionError names): the damaged chunk
    keeps its good prefix (original preserved as <chunk>.corrupt), later
    chunks are quarantined, and the node recovers the gap over
    handshake/blocksync. A clean WAL is a no-op."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.consensus.wal import WAL

    cfg = Config.load(_home(args))
    wal = WAL(os.path.join(cfg.wal_path(), "wal"))
    try:
        report = wal.repair()
    finally:
        wal.close()
    if report.corrupt_chunk is None:
        print("WAL is clean; nothing to repair")
        return 0
    print(f"quarantined corruption in {report.corrupt_chunk} at byte "
          f"offset {report.offset} ({report.truncated_bytes} bytes "
          f"dropped; original kept as "
          f"{os.path.basename(report.corrupt_chunk)}.corrupt)")
    for q in report.quarantined:
        print(f"quarantined unreplayable later chunk {q} -> "
              f"{os.path.basename(q)}.quarantined")
    print("the node will recover the dropped records over "
          "handshake/blocksync at next boot")
    return 0


def cmd_inspect(args) -> int:
    """inspect/inspect.go: serve the data-backed subset of the RPC (status,
    block, blockchain, validators, tx lookups) over a STOPPED node's stores
    — consensus and p2p never start, so a crashed node can be examined
    without running it."""
    from cometbft_tpu.config import Config
    from cometbft_tpu.node.inspect import run_inspect

    cfg = Config.load(_home(args))
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    asyncio.run(run_inspect(cfg))
    return 0


def cmd_light(args) -> int:
    """cmd/cometbft/commands/light.go:30-150: run the verified light-client
    RPC proxy against a primary + witnesses."""
    from cometbft_tpu import light
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.light.rpc_provider import RPCProvider
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.store import MemDB

    chain_id = args.chain_id
    primary = RPCProvider(chain_id, args.primary)
    witnesses = [RPCProvider(chain_id, w)
                 for w in args.witness.split(",") if w]
    store = LightStore(MemDB())

    async def run():
        client = light.Client(
            chain_id,
            light.TrustOptions(
                period_ns=int(args.trusting_period * 1e9),
                height=args.trusted_height,
                hash_=bytes.fromhex(args.trusted_hash),
            ),
            primary, witnesses, store,
        )
        proxy = LightProxy(client, args.primary, args.laddr)
        await proxy.start()
        print(f"light proxy for {chain_id} listening on {proxy.bound_addr} "
              f"(primary {args.primary}, {len(witnesses)} witnesses)")
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await proxy.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_debug(args) -> int:
    """cmd/cometbft/commands/debug/debug.go:22-80 'debug dump': capture an
    operator bundle from a RUNNING node — status, consensus round state
    (own + peers), net info, and the node config — into a tar.gz for
    offline analysis. (Process stacks: send SIGUSR1 to the node, which
    registers a faulthandler dump — see cmd_start.)"""
    import io
    import tarfile
    import time as _time
    import urllib.request

    base = args.rpc_laddr.removeprefix("tcp://")
    if not base.startswith("http"):
        base = "http://" + base

    def get(route: str) -> bytes:
        with urllib.request.urlopen(f"{base}/{route}", timeout=10) as r:
            return r.read()

    out = args.output or f"cometbft-debug-{int(_time.time())}.tar.gz"
    with tarfile.open(out, "w:gz") as tar:
        for name, route in (
            ("status.json", "status"),
            ("consensus_state.json", "consensus_state"),
            ("dump_consensus_state.json", "dump_consensus_state"),
            ("net_info.json", "net_info"),
        ):
            try:
                data = get(route)
            except Exception as e:  # noqa: BLE001 - capture what we can
                data = json.dumps({"error": str(e)}).encode()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(data))
        cfg_path = os.path.join(_home(args), "config", "config.toml")
        if os.path.exists(cfg_path):
            tar.add(cfg_path, arcname="config.toml")
        # live CPU profile + thread stacks via the node's pprof plane
        # (rpc.pprof_laddr; node/pprof.py) — skipped when not enabled
        if args.pprof_laddr:
            pbase = args.pprof_laddr.removeprefix("tcp://")
            if not pbase.startswith("http"):
                pbase = "http://" + pbase
            for name, route in (
                ("profile.txt",
                 f"debug/pprof/profile?seconds={args.profile_seconds}"
                 "&format=text"),
                ("stacks.txt", "debug/pprof/stacks"),
            ):
                try:
                    with urllib.request.urlopen(
                            f"{pbase}/{route}",
                            timeout=args.profile_seconds + 10) as r:
                        data = r.read()
                except Exception as e:  # noqa: BLE001 - capture what we can
                    data = f"pprof fetch failed: {e}\n".encode()
                info = tarfile.TarInfo(name)
                info.size = len(data)
                info.mtime = int(_time.time())
                tar.addfile(info, io.BytesIO(data))
    print(f"wrote debug bundle {out}")
    return 0


def cmd_trace_dump(args) -> int:
    """Pull the verify-plane flight recorder off a RUNNING node (the
    `trace_dump` RPC route, libs/trace.py) and write a Perfetto-loadable
    Chrome trace-event file — open it at ui.perfetto.dev. Also prints the
    rolling wall-time attribution (stage shares, measured bytes-per-sig)
    and, with --slow, writes the slow-batch capture ring next to the
    trace. Requires instrumentation.tracing=true (or CBFT_TRACE=1) on
    the node, else the dump is empty."""
    import time as _time
    import urllib.parse
    import urllib.request

    base = args.rpc_laddr.removeprefix("tcp://")
    if not base.startswith("http"):
        base = "http://" + base
    q = urllib.parse.urlencode({"slow": "true"} if args.slow else {})
    url = f"{base}/trace_dump" + (f"?{q}" if q else "")
    with urllib.request.urlopen(url, timeout=30) as r:
        env = json.loads(r.read())
    if "error" in env and env["error"]:
        print(f"trace_dump failed: {env['error']}")
        return 1
    result = env.get("result", env)
    out = args.output or f"cometbft-trace-{int(_time.time())}.json"
    with open(out, "w") as f:
        json.dump(result["chrome_trace"], f)
    n_ev = len(result["chrome_trace"].get("traceEvents", []))
    print(f"wrote {out} ({n_ev} events; load at ui.perfetto.dev)")
    if not result.get("enabled", False):
        print("note: tracing is DISABLED on the node "
              "(instrumentation.tracing / CBFT_TRACE)")
    if result.get("spans_dropped"):
        print(f"ring dropped {result['spans_dropped']} oldest spans")
    print(json.dumps({"attribution": result.get("attribution", {})}))
    if args.slow:
        slow_out = out.removesuffix(".json") + "-slow.json"
        with open(slow_out, "w") as f:
            json.dump(result.get("slow_captures", []), f, indent=1)
        print(f"wrote {slow_out} "
              f"({len(result.get('slow_captures', []))} slow captures)")
    return 0


def cmd_netinfo(args) -> int:
    """Fleet wire-plane view: pull the `net_telemetry` route off every
    RPC endpoint in --endpoints (comma-separated; defaults to the single
    --rpc.laddr) and print one JSON document — per-node per-peer/
    per-channel accounting plus a fleet rollup (total wire bytes by
    channel, stall time, tunnel/link estimates). The single-pane answer
    to 'where do this net's wire bytes go'."""
    import urllib.request

    endpoints = [e for e in (args.endpoints or args.rpc_laddr).split(",") if e]
    nodes = []
    fleet_channels: dict = {}
    fleet = {"send_bytes": 0, "recv_bytes": 0, "send_msgs": 0,
             "recv_msgs": 0, "send_stall_seconds": 0.0, "n_peers": 0}
    for ep in endpoints:
        base = ep.removeprefix("tcp://")
        if not base.startswith("http"):
            base = "http://" + base
        try:
            with urllib.request.urlopen(f"{base}/net_telemetry",
                                        timeout=10) as r:
                env = json.loads(r.read())
            tel = env.get("result", env)
        except Exception as e:  # noqa: BLE001 - report reachability per node
            nodes.append({"endpoint": ep, "error": str(e)})
            continue
        nodes.append({"endpoint": ep, **tel})
        totals = tel.get("totals", {})
        for k in ("send_bytes", "recv_bytes", "send_msgs", "recv_msgs"):
            fleet[k] += totals.get(k, 0)
        fleet["send_stall_seconds"] += totals.get("send_stall_seconds", 0.0)
        fleet["n_peers"] += tel.get("n_peers", 0)
        for ch_id, ch in tel.get("channels", {}).items():
            agg = fleet_channels.setdefault(
                ch_id, {"send_bytes": 0, "recv_bytes": 0,
                        "send_msgs": 0, "recv_msgs": 0})
            for k in agg:
                agg[k] += ch.get(k, 0)
    fleet["send_stall_seconds"] = round(fleet["send_stall_seconds"], 6)
    print(json.dumps({
        "nodes": nodes,
        "fleet": {**fleet, "channels": fleet_channels,
                  "nodes_reporting": sum(1 for n in nodes
                                         if "error" not in n)},
    }, indent=None if args.compact else 1))
    return 0 if all("error" not in n for n in nodes) else 1


def cmd_heightline(args) -> int:
    """Fleet consensus anatomy: pull the `consensus_timeline` route off
    every RPC endpoint in --endpoints (defaults to the single
    --rpc.laddr), fuse the per-node rings onto one skew-corrected clock
    axis (consensus/timeline.aggregate) and print per-height phase
    anatomy — propose -> prevote-quorum -> precommit-quorum -> commit ->
    apply durations, per-node proposal propagation, the straggler and
    the slowest vote link — plus the fleet summary. --trace additionally
    writes a Perfetto-loadable Chrome trace of the fused timeline."""
    import urllib.parse
    import urllib.request

    from cometbft_tpu.consensus import timeline
    from cometbft_tpu.libs import trace as cmttrace

    endpoints = [e for e in (args.endpoints or args.rpc_laddr).split(",") if e]
    q = urllib.parse.urlencode(
        {k: v for k, v in (("min_height", args.min_height),
                           ("limit", args.limit)) if v})
    docs, errors = [], []
    for ep in endpoints:
        base = ep.removeprefix("tcp://")
        if not base.startswith("http"):
            base = "http://" + base
        url = f"{base}/consensus_timeline" + (f"?{q}" if q else "")
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                env = json.loads(r.read())
            doc = env.get("result", env)
        except Exception as e:  # noqa: BLE001 - report reachability per node
            errors.append({"endpoint": ep, "error": str(e)})
            continue
        doc["endpoint"] = ep
        docs.append(doc)
    agg = timeline.aggregate(docs)
    disabled = [d.get("moniker") or d.get("node_id", "")
                for d in docs if not d.get("enabled", False)]
    if args.json:
        print(json.dumps({"aggregate": agg, "errors": errors,
                          "timeline_disabled": disabled},
                         indent=None if args.compact else 1))
    else:
        s = agg["summary"]
        print(f"heightline: {s.get('heights', 0)} heights across "
              f"{len(agg.get('offsets_ms', {}))} nodes "
              f"(ref {agg.get('ref', '')!r})")
        for nid, off in sorted((agg.get("offsets_ms") or {}).items()):
            print(f"  clock offset {nid}: {off:+.3f} ms")
        for rec in agg["heights"]:
            parts = []
            for phase in timeline.PHASES:
                p = (rec["phases"] or {}).get(phase)
                parts.append(f"{phase}={p['max_ms']:.1f}ms"
                             if p else f"{phase}=?")
            line = f"  h{rec['height']}: " + " ".join(parts)
            if rec.get("straggler"):
                lag = rec["proposal_propagation_ms"].get(rec["straggler"])
                line += f"  straggler={rec['straggler']} ({lag:.1f}ms)"
            link = rec.get("slowest_link")
            if link:
                line += (f"  slowest_link={link['from']}->{link['to']} "
                         f"({link['lag_ms']:.1f}ms)")
            print(line)
        if s:
            print(f"  phase_total_ms={s.get('phase_total_ms')}  "
                  f"propagation p50={s.get('proposal_propagation_p50_ms')} "
                  f"p99={s.get('proposal_propagation_p99_ms')}  "
                  f"top_straggler={s.get('top_straggler')}")
        for e in errors:
            print(f"  unreachable {e['endpoint']}: {e['error']}")
    if disabled:
        print("note: timeline DISABLED on "
              + ", ".join(disabled)
              + " (instrumentation.timeline / CBFT_TIMELINE)")
    if args.trace:
        n_ev = cmttrace.write_chrome_trace(
            args.trace, timeline.chrome_spans(agg, docs))
        print(f"wrote {args.trace} ({n_ev} events; load at ui.perfetto.dev)")
    return 0 if docs and not errors else 1


def cmd_loadtime(args) -> int:
    """test/loadtime analog: 'run' drives stamped-tx load at RPC
    endpoints; 'report' recomputes per-tx latency from committed blocks."""
    from cometbft_tpu import loadtime

    if args.mode == "run":
        endpoints = [e for e in args.endpoints.split(",") if e]
        exp_id, res = asyncio.run(loadtime.generate_load(
            endpoints, rate=args.rate, duration=args.duration,
            size=args.size, method=args.method))
        print(json.dumps({
            "experiment_id": exp_id, "sent": res.sent,
            "accepted": res.accepted, "rejected": res.rejected,
            "errors": res.errors,
        }))
        return 0
    # report
    if args.endpoints:
        url = args.endpoints.split(",")[0]
        blocks = loadtime.blocks_from_rpc(url)
    else:
        from cometbft_tpu.config import Config
        from cometbft_tpu.store import BlockStore
        from cometbft_tpu.store.db import open_db

        cfg = Config.load(_home(args))
        bs = BlockStore(open_db(cfg.base.db_backend,
                                cfg.db_path("blockstore"),
                                checksum=cfg.storage.checksum))
        blocks = loadtime.blocks_from_store(bs)
    reports = loadtime.report_from_blocks(blocks)
    for rep in reports.values():
        print(json.dumps(rep.stats()))
    return 0


def cmd_version(_args) -> int:
    print(VERSION)
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cometbft_tpu",
                                description="TPU-native BFT consensus engine")
    p.add_argument("--home", default=None, help="node home directory")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize a node home dir")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--moniker", default="node")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.persistent_peers", dest="persistent_peers", default="")
    sp.add_argument("--crypto.backend", dest="crypto_backend", default="",
                    choices=["", "cpu", "tpu", "auto"])
    sp.add_argument("--log_level", default="")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--v", type=int, default=4, help="number of validators")
    sp.add_argument("--o", default="./mytestnet", help="output directory")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("rollback", help="revert state by one height")
    sp.add_argument("--hard", action="store_true",
                    help="also remove the block at the rolled-back height")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser(
        "wal-repair",
        help="quarantine mid-group consensus-WAL corruption on a stopped "
             "node (the repair WALCorruptionError names)")
    sp.set_defaults(fn=cmd_wal_repair)

    sp = sub.add_parser("inspect", help="serve read-only RPC over a stopped node's data")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("light", help="verified light-client RPC proxy")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="primary node RPC URL")
    sp.add_argument("--witness", default="",
                    help="comma-separated witness RPC URLs")
    sp.add_argument("--trusted-height", type=int, required=True)
    sp.add_argument("--trusted-hash", required=True,
                    help="hex header hash at the trusted height")
    sp.add_argument("--trusting-period", type=float, default=168 * 3600,
                    help="seconds (default one week)")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888",
                    help="proxy listen address")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("debug", help="capture an operator debug bundle")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr",
                    default="tcp://127.0.0.1:26657")
    sp.add_argument("--pprof.laddr", dest="pprof_laddr", default="",
                    help="node's rpc.pprof_laddr; adds a live CPU profile "
                         "+ thread stacks to the bundle")
    sp.add_argument("--profile-seconds", type=int, default=5)
    sp.add_argument("--output", default="", help="output tar.gz path")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser(
        "trace-dump",
        help="pull the verify-plane flight recorder off a running node "
             "into a Perfetto-loadable trace file")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr",
                    default="tcp://127.0.0.1:26657")
    sp.add_argument("--output", default="", help="output .json path")
    sp.add_argument("--slow", action="store_true",
                    help="also write the slow-batch capture ring")
    sp.set_defaults(fn=cmd_trace_dump)

    sp = sub.add_parser(
        "netinfo",
        help="fleet wire-plane telemetry: per-peer/per-channel network "
             "accounting + live link models across RPC endpoints")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr",
                    default="tcp://127.0.0.1:26657")
    sp.add_argument("--endpoints", default="",
                    help="comma-separated RPC endpoints (overrides "
                         "--rpc.laddr; one net_telemetry pull each)")
    sp.add_argument("--compact", action="store_true",
                    help="single-line JSON output")
    sp.set_defaults(fn=cmd_netinfo)

    sp = sub.add_parser(
        "heightline",
        help="fleet consensus anatomy: skew-aligned per-height phase "
             "durations, proposal propagation, stragglers + slow links "
             "across RPC endpoints")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr",
                    default="tcp://127.0.0.1:26657")
    sp.add_argument("--endpoints", default="",
                    help="comma-separated RPC endpoints (overrides "
                         "--rpc.laddr; one consensus_timeline pull each)")
    sp.add_argument("--min-height", type=int, default=0)
    sp.add_argument("--limit", type=int, default=0,
                    help="newest N heights per node (0 = all retained)")
    sp.add_argument("--json", action="store_true",
                    help="print the raw aggregate as JSON")
    sp.add_argument("--compact", action="store_true",
                    help="single-line JSON output (with --json)")
    sp.add_argument("--trace", default="",
                    help="also write a Chrome trace of the fused "
                         "timeline to this path")
    sp.set_defaults(fn=cmd_heightline)

    sp = sub.add_parser("loadtime", help="tx load generator + latency report")
    sp.add_argument("mode", choices=["run", "report"])
    sp.add_argument("--endpoints", default="",
                    help="comma-separated RPC URLs (report falls back to "
                         "the node home's blockstore when empty)")
    sp.add_argument("--rate", type=float, default=100.0, help="tx/s")
    sp.add_argument("--duration", type=float, default=10.0, help="seconds")
    sp.add_argument("--size", type=int, default=256, help="tx bytes")
    sp.add_argument("--method", default="broadcast_tx_async",
                    choices=["broadcast_tx_async", "broadcast_tx_sync"])
    sp.set_defaults(fn=cmd_loadtime)

    sp = sub.add_parser(
        "unsafe-reset-all",
        help="(unsafe) remove all data, reset privval state, drop addrbook")
    sp.add_argument("--keep-addr-book", action="store_true",
                    help="keep the address book intact")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("reset-state", help="remove all the data and WAL")
    sp.set_defaults(fn=cmd_reset_state)

    sp = sub.add_parser(
        "unsafe-reset-priv-validator",
        help="(unsafe) reset this node's validator to genesis state")
    sp.set_defaults(fn=cmd_reset_priv_validator)

    sp = sub.add_parser("gen-validator",
                        help="generate and print a fresh validator keypair")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("gen-node-key",
                        help="generate node_key.json and print the node ID")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("compact-db",
                        help="force-compact a stopped node's sqlite stores")
    sp.set_defaults(fn=cmd_compact_db)

    sp = sub.add_parser("show-node-id")
    sp.set_defaults(fn=cmd_show_node_id)
    sp = sub.add_parser("show-validator")
    sp.set_defaults(fn=cmd_show_validator)
    sp = sub.add_parser("version")
    sp.set_defaults(fn=cmd_version)
    return p


if __name__ == "__main__":
    sys.exit(main())
