"""Synchronous in-process event switch (reference: libs/events/events.go).

The consensus state machine fires internal events (NewRoundStep, Vote, ...)
that the reactor consumes on the fast path, decoupled from the async pubsub
EventBus used for RPC subscribers (reference: consensus/state.go:129-131).
Callbacks run inline on the caller; they must be non-blocking.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable

Callback = Callable[[Any], None]


class EventSwitch:
    def __init__(self) -> None:
        # event -> listener_id -> callback
        self._listeners: dict[str, dict[str, Callback]] = defaultdict(dict)

    def add_listener(self, listener_id: str, event: str, cb: Callback) -> None:
        self._listeners[event][listener_id] = cb

    def remove_listener(self, listener_id: str, event: str | None = None) -> None:
        if event is not None:
            self._listeners.get(event, {}).pop(listener_id, None)
            return
        for cbs in self._listeners.values():
            cbs.pop(listener_id, None)

    def fire_event(self, event: str, data: Any = None) -> None:
        for cb in list(self._listeners.get(event, {}).values()):
            cb(data)

    # short alias used by the consensus hot path
    fire = fire_event
