"""Support runtime (reference: libs/ — SURVEY.md §2.5).

Host-side, idiomatic asyncio equivalents of the reference's 25 support
packages: service lifecycle, structured logging, event switch, pubsub,
bit arrays, WAL file groups, rate limiting, protoio framing.
"""
