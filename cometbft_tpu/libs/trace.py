"""Verify-plane flight recorder: structured span tracing with wall-time
attribution.

Every number on the bench trajectory so far (tunnel cap ~229k sigs/s,
blocksync device-busy-fraction 0.993) was *inferred* from aggregate
output; nothing in the node could say, for one batch or one consensus
height, how many microseconds went to staging vs host->device transfer vs
kernel compute vs result fetch vs queueing. This module is that
instrument — the software analog of how FPGA verification engines
instrument their offload pipelines to find the PCIe-vs-compute split
(arXiv:2112.02229) and how committee-consensus signature studies break
cost down per pipeline stage (arXiv:2302.00418).

Design constraints, in priority order:

  near-zero when off   `span()` returns a shared no-op after one module-
                       global bool read; nothing allocates, nothing locks.
                       Tier-1 asserts <3% overhead on a 1k-row verify.
  cheap when on        finished spans are plain dicts dropped into a
                       bounded ring buffer (preallocated list + atomic-
                       under-the-GIL monotonic counter); no I/O, no
                       serialization until an exporter asks.
  attributable         spans carry a stage category; on finish, a span's
                       SELF time (duration minus stage-categorized
                       descendants) is accounted into rolling per-stage
                       totals — the `attribution` section of crypto_health
                       and the number the mesh / reduced-send PRs are
                       judged against. Wire bytes ride the spans
                       (`add_bytes`) so bytes-per-sig is measured, not
                       estimated.
  exportable           Chrome trace-event JSON (Perfetto-loadable) via
                       chrome_trace(); served by the `trace_dump` RPC
                       route and the `trace-dump` CLI subcommand.
  post-mortem          root spans slower than `slow_ms` keep their full
                       span tree in a bounded capture ring — a slow batch
                       or height is examinable after the fact, and its
                       log lines correlate by trace/span id (libs/log.py
                       stamps them automatically).

Stage categories (the attribution model):

  queue      submit->dispatch wait in the verify scheduler
  stage      host staging: structural checks, hashing, packing
  transfer   host->device bytes (staged words, pubkey coordinate tables)
  challenge  challenge derivation (device SHA-512+Barrett, or the host-k
             fallback rungs of ops/challenge.py)
  compute    device dispatch / host-oracle verification
  fetch      device->host result bytes (reduced-fetch headers, payloads)
  resolve    mask decode, integrity checks, host re-checks, slicing

Overlap model (double-buffered dispatch): with two in-flight slots per
fault domain (ops/dispatch.DoubleBuffer) batch N's host->device transfer
runs WHILE batch N-1's kernel computes on another pool thread. Summing
both wall intervals would double-count the overlapped nanoseconds — the
transfer wasn't pipeline cost, it was hidden behind compute. So a
finishing transfer span bills only the part of its self time that did
NOT intersect device-busy (compute/challenge) intervals on OTHER
threads; the intersected part accumulates separately and is surfaced as
`h2d_overlap_us` / `h2d_overlap_fraction` = overlap/(transfer+overlap)
— the measured did-the-double-buffer-actually-overlap number.

Span parenting uses a contextvars.ContextVar, so nesting is correct per
thread AND per asyncio task with no explicit plumbing; `wrap_ctx()` hands
a context-carrying callable to thread pools (the kernel transfer/fetch
pools) so device-side spans stay in their batch's tree.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

# Stage categories counted by the attribution model. Spans with any other
# cat ("sched", "consensus", "sync", "mempool", "device", ...) appear in
# the trace but never in stage shares — they are containers, not stages.
STAGES = ("queue", "stage", "transfer", "challenge", "compute", "fetch",
          "resolve")

# device-busy categories for the h2d overlap model: a transfer span's
# nanoseconds that intersect one of these on ANOTHER thread bill as
# overlap, not transfer
_BUSY_CATS = ("challenge", "compute")

# finished busy intervals kept for the overlap window: must cover every
# transfer that could have overlapped a compute that already finished —
# a handful of in-flight batches, so a small ring is plenty
_BUSY_KEEP = 64


def _union_overlap_ns(t0: int, t1: int, intervals) -> int:
    """|[t0, t1] ∩ union(intervals)| in ns (intervals may overlap each
    other; they are clipped, merged, then summed)."""
    clipped = sorted((max(t0, a), min(t1, b)) for a, b in intervals
                     if b > t0 and a < t1)
    total = 0
    cur_a = cur_b = None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        elif b > cur_b:
            cur_b = b
    if cur_b is not None:
        total += cur_b - cur_a
    return total

_enabled = False  # module-global fast path: read before anything else

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "cbft_trace_span", default=None)


class Span:
    """One live span. Use as a context manager (the normal case) or via
    begin()/finish() for spans that outlive a single frame (the per-height
    consensus timeline). Attribute writes after finish are ignored."""

    __slots__ = ("id", "parent", "trace_id", "name", "cat", "t0", "t1",
                 "tid", "attrs", "bytes_tx", "bytes_rx", "_covered",
                 "_token", "_done")

    def __init__(self, id_: int, parent: Optional["Span"], name: str,
                 cat: str, attrs: dict, t0: int):
        self.id = id_
        self.parent = parent
        self.trace_id = parent.trace_id if parent is not None else id_
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = 0
        self.tid = threading.get_ident()
        self.attrs = attrs
        self.bytes_tx = 0
        self.bytes_rx = 0
        self._covered = 0  # ns of stage-categorized descendant time
        self._token = None
        self._done = False

    # ------------------------------------------------------------- attrs

    def set(self, **kv: Any) -> "Span":
        if not self._done:
            self.attrs.update(kv)
        return self

    def add_bytes(self, tx: int = 0, rx: int = 0) -> "Span":
        """Record wire bytes moved inside this span (host->device tx,
        device->host rx) — the measured-bytes-per-sig source."""
        if not self._done:
            self.bytes_tx += tx
            self.bytes_rx += rx
        return self

    # ------------------------------------------------------- context mgr

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        if self._token is not None:
            # entered via `with` (or bare __enter__): pop ourselves off
            # the context stack even when finish() is called directly —
            # a leaked token would silently reparent every later span
            try:
                _current.reset(self._token)
            except ValueError:
                pass  # finished from a different Context than entered
            self._token = None
        t = _T
        if t is not None:
            t._finish(self)


class _NopSpan:
    """The shared disabled-mode span: every method is a no-op returning
    self, so instrumented code needs no enabled checks of its own."""

    __slots__ = ()

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **kv: Any) -> "_NopSpan":
        return self

    def add_bytes(self, tx: int = 0, rx: int = 0) -> "_NopSpan":
        return self

    def finish(self) -> None:
        pass


_NOP = _NopSpan()


class Tracer:
    """Ring buffer + attribution accumulator + slow-batch capture ring.
    One per process (the verify plane is process-global); `clock` is
    injectable (ns) so tests run on a fake timeline."""

    def __init__(self, capacity: int = 65536, slow_ms: float = 250.0,
                 slow_captures: int = 32, clock=time.monotonic_ns):
        if capacity < 1:
            raise ValueError("trace capacity must be >= 1")
        self.capacity = capacity
        self.slow_ms = slow_ms
        self._clock = clock
        self._buf: list = [None] * capacity
        self._ctr = itertools.count()
        self._pos = 0  # next write index (== spans finished so far)
        self._ids = itertools.count(1)
        self.t_origin = clock()
        self._slow: deque = deque(maxlen=max(1, slow_captures))
        self._lock = threading.Lock()
        # rolling attribution: stage -> ns of SELF time, plus wire bytes
        # and signature rows (account() and stage-span finishes feed this)
        self._attr_ns = {s: 0 for s in STAGES}
        self._attr_rows = 0
        self._attr_tx = 0
        self._attr_rx = 0
        # h2d overlap model state: live device-busy spans (id -> (tid,
        # t0)), recently finished busy intervals (tid, t0, t1), and the
        # overlap accumulator (transfer ns hidden behind compute)
        self._open_busy: dict[int, tuple[int, int]] = {}
        self._done_busy: deque = deque(maxlen=_BUSY_KEEP)
        self._attr_overlap = 0

    # ------------------------------------------------------------- spans

    def start(self, name: str, cat: str, attrs: dict) -> Span:
        # clock FIRST: id allocation and the contextvar read are span-
        # creation overhead — stamping t0 before them bills that cost to
        # the new span instead of leaking it into the parent's uncovered
        # gap (per-batch coverage is an acceptance number)
        t0 = self._clock()
        s = Span(next(self._ids), _current.get(), name, cat, attrs, t0)
        if cat in _BUSY_CATS:
            # register live device-busy work for the overlap model; only
            # busy cats pay the lock here, the common span stays lock-free
            with self._lock:
                self._open_busy[s.id] = (s.tid, t0)
        return s

    def _finish(self, span: Span) -> None:
        # ring write FIRST, before t1 is read: the Span object itself
        # goes into the ring (rendered to a dict lazily by snapshot()),
        # so the bulk of finish bookkeeping is timed INSIDE the span
        # rather than leaking into the parent's uncovered gap — per-batch
        # coverage is an acceptance number, tracer self-time must not
        # erode it. The counter bump is atomic under the GIL; a torn
        # read during snapshot() costs at most one stale slot, never a
        # crash — the price of keeping the hot path lock-free.
        pos = next(self._ctr)
        self._buf[pos % self.capacity] = span
        self._pos = pos + 1
        counted = span.cat in STAGES
        instant = span.attrs.get("instant", False)
        parent = span.parent
        if counted:
            # rows are NOT read off span attrs here: many spans along one
            # batch's path describe the same rows. Leaf verification
            # sites mark theirs with `sig_rows`; everything else
            # annotates `rows` informationally.
            rows = span.attrs.get("sig_rows", 0)
            if not isinstance(rows, int):
                rows = 0
            # attribution is updated inline with the lock taken BEFORE t1
            # is read: lock acquisition and the dict updates are tracer
            # overhead that must be timed inside the span, not in the
            # parent's uncovered gap. The parent-coverage += rides the
            # same lock: siblings of one parent finish concurrently
            # (kernel pool threads vs the flush thread), and a lost
            # update there would double-count the child at the parent.
            with self._lock:
                span.t1 = self._clock()
                dur = 0 if instant else max(0, span.t1 - span.t0)
                self_ns = max(0, dur - span._covered)
                if span.cat in _BUSY_CATS:
                    self._open_busy.pop(span.id, None)
                    if dur:
                        self._done_busy.append((span.tid, span.t0, span.t1))
                elif span.cat == "transfer" and dur:
                    # overlapped h2d bills as overlap, not transfer: the
                    # busy set is live spans (busy through our t1) plus
                    # recently finished intervals, other threads only
                    ivals = [(b0, span.t1)
                             for (btid, b0) in self._open_busy.values()
                             if btid != span.tid]
                    ivals.extend(
                        (b0, b1) for (btid, b0, b1) in self._done_busy
                        if btid != span.tid)
                    ov = min(self_ns,
                             _union_overlap_ns(span.t0, span.t1, ivals))
                    self._attr_overlap += ov
                    self_ns -= ov
                self._attr_ns[span.cat] += self_ns
                self._attr_rows += rows
                self._attr_tx += span.bytes_tx
                self._attr_rx += span.bytes_rx
                if parent is not None and not parent._done:
                    # a counted span covers its full duration at the parent
                    parent._covered += dur
        else:
            span.t1 = self._clock()
            dur = 0 if instant else max(0, span.t1 - span.t0)
            if parent is not None and not parent._done and span._covered:
                # an uncounted container passes through what its children
                # covered
                with self._lock:
                    if not parent._done:
                        parent._covered += span._covered
        # instants (event()) are points, not intervals: the wall ns
        # between start and finish is tracer overhead, not span duration
        if instant:
            span.t1 = span.t0
        if parent is None and self.slow_ms >= 0:
            # a root may carry its own latency budget (consensus heights
            # include unavoidable protocol waits and would flood the
            # capture ring under the global default)
            budget_ms = span.attrs.get("slow_ms", self.slow_ms)
            if dur >= budget_ms * 1e6:
                self._capture_slow(span)

    def _render(self, span: Span) -> dict:
        dur = 0 if span.attrs.get("instant") \
            else max(0, span.t1 - span.t0)
        parent = span.parent
        return {
            "id": span.id,
            "parent_id": parent.id if parent is not None else None,
            "trace_id": span.trace_id,
            "name": span.name,
            "cat": span.cat,
            "t0_ns": span.t0 - self.t_origin,
            "dur_ns": dur,
            "tid": span.tid,
            "bytes_tx": span.bytes_tx,
            "bytes_rx": span.bytes_rx,
            "attrs": span.attrs,
        }

    def _capture_slow(self, root: Span) -> None:
        """A root span blew its latency budget: keep its full span tree
        (everything in the ring sharing its trace_id) for post-mortem.
        Filter on the raw Span objects first — rendering the whole ring
        to dicts per capture would cost tens of ms at full capacity."""
        tree = [self._render(s) for s in self._raw()
                if s.trace_id == root.trace_id]
        self._slow.append({
            "trace_id": root.trace_id,
            "root": root.name,
            "dur_ms": round(max(0, root.t1 - root.t0) / 1e6, 3),
            "attrs": root.attrs,
            "spans": tree,
        })

    # ------------------------------------------------------- attribution

    def account(self, stage: str, seconds: float, rows: int = 0,
                tx_bytes: int = 0, rx_bytes: int = 0) -> None:
        """Feed the rolling attribution directly (the scheduler accounts
        queue wait this way — queue time is an interval on the group, not
        a span on any one thread)."""
        ns = int(seconds * 1e9)
        with self._lock:
            self._attr_ns[stage] = self._attr_ns.get(stage, 0) + ns
            self._attr_rows += rows
            self._attr_tx += tx_bytes
            self._attr_rx += rx_bytes

    def attribution(self) -> dict:
        with self._lock:
            ns = dict(self._attr_ns)
            rows, tx, rx = self._attr_rows, self._attr_tx, self._attr_rx
            overlap = self._attr_overlap
        return _attribution_dict(ns, rows, tx, rx, overlap)

    def reset_attribution(self) -> None:
        with self._lock:
            self._attr_ns = {s: 0 for s in STAGES}
            self._attr_rows = 0
            self._attr_tx = 0
            self._attr_rx = 0
            self._attr_overlap = 0
            self._open_busy.clear()
            self._done_busy.clear()

    # ----------------------------------------------------------- reading

    def _raw(self) -> list:
        """Finished Span objects, oldest first (up to capacity)."""
        pos = self._pos
        if pos <= self.capacity:
            out = self._buf[:pos]
        else:
            i = pos % self.capacity
            out = self._buf[i:] + self._buf[:i]
        return [s for s in out if s is not None]

    def snapshot(self) -> list[dict]:
        """Finished spans, oldest first (up to capacity), rendered to
        plain dicts. A span caught mid-finish (ring slot written, t1 not
        yet stamped) renders with dur 0 — a torn read, not a crash."""
        return [self._render(s) for s in self._raw()]

    def dropped(self) -> int:
        return max(0, self._pos - self.capacity)

    def slow_captures(self) -> list[dict]:
        return list(self._slow)


_T: Optional[Tracer] = None
_cfg_lock = threading.Lock()


# ------------------------------------------------------------- public API


def span(name: str, cat: str = "", parent: Any = None, **attrs: Any):
    """Start a span (context manager). Near-free when tracing is off.
    `parent` overrides the contextvar parent — the consensus height
    timeline hands its begin()-span here so flush/commit spans join the
    height's tree even though the timeline outlives any one frame."""
    # snapshot _T: reset() flips _enabled then drops the tracer, and an
    # in-flight pool thread may pass the bool check just before — tracing
    # must degrade to a no-op, never AttributeError inside a verify batch
    t = _T
    if not _enabled or t is None:
        return _NOP
    s = t.start(name, cat, attrs)
    if isinstance(parent, Span):
        s.parent = parent
        s.trace_id = parent.trace_id
    return s


def begin(name: str, cat: str = "", **attrs: Any):
    """A span NOT bound to the calling frame's context (no contextvar
    touch): for timelines spanning many frames/tasks, e.g. one consensus
    height. Finish with .finish()."""
    t = _T
    if not _enabled or t is None:
        return _NOP
    s = t.start(name, cat, attrs)
    s.parent = None  # context-free: always a root
    s.trace_id = s.id
    return s


def event(name: str, cat: str = "", parent: Any = None, **attrs: Any) -> None:
    """An instant event (zero-duration span) — step transitions etc.
    `parent` joins the event to a begin()-timeline's tree (consensus round
    steps onto their height span)."""
    t = _T
    if not _enabled or t is None:
        return
    s = t.start(name, cat, attrs)
    if isinstance(parent, Span):
        s.parent = parent
        s.trace_id = parent.trace_id
    s.attrs["instant"] = True
    s.finish()


def account(stage: str, seconds: float, rows: int = 0,
            tx_bytes: int = 0, rx_bytes: int = 0) -> None:
    t = _T
    if _enabled and t is not None:
        t.account(stage, seconds, rows=rows, tx_bytes=tx_bytes,
                  rx_bytes=rx_bytes)


def add_bytes(tx: int = 0, rx: int = 0) -> None:
    """Record wire bytes against the active span (or straight into the
    rolling totals when no span is active) — lets deep transfer sites
    (the pubkey-coordinate upload inside PubKeyCache.stage) report bytes
    without threading a span handle through."""
    t = _T
    if not _enabled or t is None:
        return
    s = _current.get()
    if s is not None:
        s.add_bytes(tx=tx, rx=rx)
    else:
        t.account("transfer", 0.0, tx_bytes=tx, rx_bytes=rx)


def enabled() -> bool:
    return _enabled


def current_ids() -> Optional[tuple[int, int]]:
    """(trace_id, span_id) of the active span, or None. The log-line
    correlation hook (libs/log.py) — must be cheap when disabled."""
    if not _enabled:
        return None
    s = _current.get()
    if s is None:
        return None
    return s.trace_id, s.id


def wrap_ctx(fn: Callable) -> Callable:
    """Carry the caller's trace context into a thread-pool worker so
    device-side spans (transfer/fetch on the kernel pools) stay inside
    their batch's span tree. Identity when tracing is off."""
    if not _enabled:
        return fn
    ctx = contextvars.copy_context()

    def run(*a, **kw):
        return ctx.run(fn, *a, **kw)

    return run


def configure(enabled: bool | None = None, capacity: int | None = None,
              slow_ms: float | None = None,
              slow_captures: int | None = None, clock=None) -> None:
    """(Re)configure the process tracer. Changing capacity rebuilds the
    ring (existing spans are dropped); toggling enabled keeps it."""
    global _enabled, _T
    if capacity is not None and capacity < 1:
        raise ValueError("trace capacity must be >= 1")
    with _cfg_lock:
        rebuild = _T is None or capacity is not None or clock is not None \
            or slow_captures is not None
        if rebuild:
            _T = Tracer(
                capacity=capacity or (_T.capacity if _T else 65536),
                slow_ms=slow_ms if slow_ms is not None
                else (_T.slow_ms if _T else 250.0),
                slow_captures=slow_captures
                if slow_captures is not None
                else (_T._slow.maxlen if _T else 32),
                clock=clock or time.monotonic_ns)
        elif slow_ms is not None:
            _T.slow_ms = slow_ms
        if enabled is not None:
            _enabled = enabled


def reset() -> None:
    """Drop all spans, captures, and attribution; disable. (Tests.)"""
    global _enabled, _T
    with _cfg_lock:
        _enabled = False
        _T = None


def snapshot() -> list[dict]:
    return _T.snapshot() if _T is not None else []


def dropped() -> int:
    return _T.dropped() if _T is not None else 0


def slow_captures() -> list[dict]:
    return _T.slow_captures() if _T is not None else []


def capacity() -> int:
    """Configured ring size (the default when no tracer is built yet) —
    lets callers that temporarily re-configure() restore the prior ring."""
    return _T.capacity if _T is not None else 65536


def slow_budget_ms() -> float:
    """The configured global slow-capture budget (roots layering extra
    allowance on top — the consensus height timeline — start from this)."""
    return _T.slow_ms if _T is not None else 250.0


def attribution() -> dict:
    """Rolling stage-share percentages + measured bytes-per-sig — the
    crypto_health `attribution` section."""
    if _T is None:
        return {"enabled": False}
    out = _T.attribution()
    out["enabled"] = _enabled
    return out


def reset_attribution() -> None:
    if _T is not None:
        _T.reset_attribution()


# --------------------------------------------------------- the model


def _attribution_dict(ns: dict, rows: int, tx: int, rx: int,
                      overlap_ns: int = 0) -> dict:
    total = sum(ns.get(s, 0) for s in STAGES)
    shares = {
        s: (round(ns.get(s, 0) / total, 4) if total else 0.0)
        for s in STAGES
    }
    # overlap is transfer time hidden behind compute on another thread:
    # already excluded from the transfer bill (and from total — it was
    # not pipeline cost), reported as the did-we-overlap fraction
    h2d = ns.get("transfer", 0) + overlap_ns
    return {
        "stage_us": {s: round(ns.get(s, 0) / 1e3, 1) for s in STAGES},
        "stage_share": shares,
        "total_us": round(total / 1e3, 1),
        "rows": rows,
        "wire_tx_bytes": tx,
        "wire_rx_bytes": rx,
        "bytes_per_sig_tx": round(tx / rows, 2) if rows else None,
        "bytes_per_sig_rx": round(rx / rows, 2) if rows else None,
        "h2d_overlap_us": round(overlap_ns / 1e3, 1),
        # 6 decimals: a real-but-thin overlap (host-heavy boxes dilute the
        # denominator with pubkey-staging wall time) must not read as 0.0
        "h2d_overlap_fraction": round(overlap_ns / h2d, 6) if h2d else 0.0,
    }


def attribution_of(spans: list[dict]) -> dict:
    """The wall-time attribution model applied to a span list (snapshot()
    records or a recorded fixture): per-stage SELF time — a stage span's
    duration minus its stage-categorized descendants — summed into stage
    shares, with wire bytes and signature rows totaled from the spans.
    The perf regression test replays a recorded trace through this and
    fails if the share math drifts."""
    by_id = {r["id"]: r for r in spans}
    covered: dict[int, int] = {}
    # the offline overlap model sees every busy interval up front
    busy_by_tid: dict[int, list[tuple[int, int]]] = {}
    for r in spans:
        if r["cat"] in _BUSY_CATS and r["dur_ns"]:
            busy_by_tid.setdefault(r["tid"], []).append(
                (r["t0_ns"], r["t0_ns"] + r["dur_ns"]))
    # children finish before parents, so a single pass over spans sorted
    # by END time ascending propagates coverage bottom-up
    order = sorted(spans, key=lambda r: r["t0_ns"] + r["dur_ns"])
    ns = {s: 0 for s in STAGES}
    rows = tx = rx = overlap = 0
    for r in order:
        counted = r["cat"] in STAGES
        cov = covered.get(r["id"], 0)
        if counted:
            self_ns = max(0, r["dur_ns"] - cov)
            if r["cat"] == "transfer" and r["dur_ns"]:
                ivals = [iv for tid, lst in busy_by_tid.items()
                         if tid != r["tid"] for iv in lst]
                ov = min(self_ns, _union_overlap_ns(
                    r["t0_ns"], r["t0_ns"] + r["dur_ns"], ivals))
                overlap += ov
                self_ns -= ov
            ns[r["cat"]] += self_ns
            n = r["attrs"].get("sig_rows", 0)
            rows += n if isinstance(n, int) else 0
            tx += r.get("bytes_tx", 0)
            rx += r.get("bytes_rx", 0)
        pid = r.get("parent_id")
        if pid is not None and pid in by_id:
            covered[pid] = covered.get(pid, 0) + (
                r["dur_ns"] if counted else cov)
    return _attribution_dict(ns, rows, tx, rx, overlap)


# ----------------------------------------------------------- exporters


def chrome_trace(spans: list[dict] | None = None) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable): complete ("X") events
    in microseconds with span/trace ids and wire bytes in args, plus
    thread-name metadata. json.dump the return value (or the
    `trace-dump` CLI does it for you) and load it at ui.perfetto.dev."""
    if spans is None:
        spans = snapshot()
    tids: dict[int, int] = {}
    events: list[dict] = []
    for r in spans:
        tid = tids.setdefault(r["tid"], len(tids) + 1)
        args = dict(r["attrs"])
        args["span_id"] = r["id"]
        args["trace_id"] = r["trace_id"]
        if r.get("parent_id") is not None:
            args["parent_id"] = r["parent_id"]
        if r.get("bytes_tx"):
            args["bytes_tx"] = r["bytes_tx"]
        if r.get("bytes_rx"):
            args["bytes_rx"] = r["bytes_rx"]
        ph = "i" if args.pop("instant", False) else "X"
        ev = {
            "name": r["name"],
            "cat": r["cat"] or "span",
            "ph": ph,
            "ts": r["t0_ns"] / 1e3,
            "pid": 1,
            "tid": tid,
            "args": args,
        }
        if ph == "X":
            ev["dur"] = r["dur_ns"] / 1e3
        else:
            ev["s"] = "t"  # instant scope: thread
        events.append(ev)
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": idx,
         "args": {"name": f"thread-{idx}"}}
        for idx in sorted(tids.values())
    ]
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: list[dict] | None = None) -> int:
    """Write a Perfetto-loadable trace file; returns the event count."""
    doc = chrome_trace(spans)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])
