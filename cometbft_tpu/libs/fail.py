"""Crash-site injection for WAL/handshake recovery testing.

Reference: libs/fail/fail.go:28-38 — `fail.Fail()` call sites indexed in
program order by FAIL_TEST_INDEX; when the running counter hits the
configured index the process dies immediately (os._exit, no cleanup —
simulating kill -9 at a precise point in the commit path).

Grown into a NAMED registry: every persistence boundary in the commit
path is a crash site, the legacy 5 indices are aliases into it, and the
crash-matrix harness (tests/test_storage_crash_matrix.py) arms sites
in-process with a hook instead of killing the OS process.

Sites (program order through one committed height; legacy index in
brackets — FAIL_TEST_INDEX still honors them):

  blockstore.save [0]  before the block is saved to the block store
  wal.endheight   [1]  after block save, before the WAL EndHeight fsync
  abci.apply      [2]  after the EndHeight fsync, before ApplyBlock
                       <- the committed-but-unapplied crash window
  state.finalize  [3]  after the FinalizeBlock response is persisted,
                       before the state save
  state.save      [4]  after the state save, before the app Commit
  app.commit           after the app Commit response, before the mempool
                       update (app and state agree; mempool rebuild)
  wal.write            before a WAL record is appended (any message)
  privval.save         after signing, before the sign-state file is
                       persisted (the signature must NOT have left yet —
                       crashing here must never enable a double-sign)

Arming: `CBFT_CRASH_SITE=site[:n]` dies on the site's n-th hit (default
1); `FAIL_TEST_INDEX=<0..4>` keeps the original semantics byte-for-byte
(same stderr marker, same exit code 99). In-proc: `arm(site, count,
hook)` — the hook replaces os._exit (the crash-matrix harness raises
libs.diskchaos.SimulatedCrash).
"""

from __future__ import annotations

import os
import sys
import threading

# legacy FAIL_TEST_INDEX -> named site (program order is load-bearing:
# the index IS the program-order position, fail.go:28)
LEGACY_SITES = (
    "blockstore.save",   # 0
    "wal.endheight",     # 1
    "abci.apply",        # 2
    "state.finalize",    # 3
    "state.save",        # 4
)

SITES = LEGACY_SITES + ("app.commit", "wal.write", "privval.save")

_ENV_INDEX = "FAIL_TEST_INDEX"
_ENV_SITE = "CBFT_CRASH_SITE"

_lock = threading.Lock()
_legacy_index: int | None = None
_armed: dict[str, dict] = {}  # site -> {"remaining": int, "hook": callable|None}
_hits: dict[str, int] = {}
_env_loaded = False


def _load_env_locked() -> None:
    global _env_loaded, _legacy_index
    if _env_loaded:
        return
    _env_loaded = True
    try:
        _legacy_index = int(os.environ.get(_ENV_INDEX, "-1"))
    except ValueError:
        _legacy_index = -1
    spec = os.environ.get(_ENV_SITE, "")
    if spec:
        site, _, count = spec.partition(":")
        site = site.strip()
        if site in SITES:
            try:
                n = int(count) if count else 1
            except ValueError:
                n = 1
            _armed[site] = {"remaining": max(1, n), "hook": None}


def arm(site: str, count: int = 1, hook=None) -> None:
    """Arm `site` to crash on its `count`-th hit. `hook` replaces the
    default os._exit(99) (in-proc harnesses raise SimulatedCrash)."""
    if site not in SITES:
        raise ValueError(f"unknown crash site {site!r} (sites: {SITES})")
    if count < 1:
        raise ValueError("crash count must be >= 1")
    with _lock:
        _load_env_locked()
        _armed[site] = {"remaining": count, "hook": hook}


def disarm(site: str) -> None:
    with _lock:
        _armed.pop(site, None)


def reset() -> None:
    """Disarm everything and forget the env (tests re-arm per case)."""
    global _env_loaded, _legacy_index
    with _lock:
        _armed.clear()
        _hits.clear()
        _env_loaded = True  # a reset() overrides the process env schedule
        _legacy_index = -1


def hits(site: str) -> int:
    """How many times the site has been passed (armed or not)."""
    with _lock:
        return _hits.get(site, 0)


def _die(site: str, legacy_index: int | None) -> None:
    if legacy_index is not None:
        sys.stderr.write(f"*** fail-point {legacy_index} triggered ***\n")
    else:
        sys.stderr.write(f"*** crash-site {site} triggered ***\n")
    sys.stderr.flush()
    os._exit(99)


def fail_point(site: str) -> None:
    """Call at a persistence boundary: dies (or fires the armed hook) iff
    this site is armed via env or arm(). Disarmed cost: one uncontended
    lock + two dict ops. The commit-path sites pay it a handful of times
    per height; wal.write pays it per WAL record, where it is noise next
    to the JSON encode + write the record itself costs (the hit counter
    is the crash-matrix's observability and is kept exact on purpose)."""
    hook = None
    trigger = False
    legacy = None
    with _lock:
        _load_env_locked()
        _hits[site] = _hits.get(site, 0) + 1
        try:
            idx = SITES.index(site)
        except ValueError:
            idx = -1
        if (_legacy_index is not None and _legacy_index >= 0
                and idx < len(LEGACY_SITES) and idx == _legacy_index):
            trigger, legacy = True, idx
        else:
            st = _armed.get(site)
            if st is not None:
                st["remaining"] -= 1
                if st["remaining"] <= 0:
                    _armed.pop(site, None)
                    trigger, hook = True, st["hook"]
    if not trigger:
        return
    if hook is not None:
        hook(site)
        return
    _die(site, legacy)


def fail(call_index: int) -> None:
    """Legacy indexed entry point (fail.go Fail): kept so old call sites
    and FAIL_TEST_INDEX fixtures keep working unchanged."""
    if 0 <= call_index < len(LEGACY_SITES):
        fail_point(LEGACY_SITES[call_index])
