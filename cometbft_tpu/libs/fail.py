"""Crash-point injection for WAL/handshake recovery testing.

Reference: libs/fail/fail.go:28-38 — `fail.Fail()` call sites are indexed
in program order by the FAIL_TEST_INDEX env var; when the running counter
hits the configured index the process dies immediately (os._exit, no
cleanup — simulating kill -9 at a precise point in the commit path).

Call sites (mirroring consensus/state.go:1777,1794,1817 and
state/execution.go:251,258):
  0  before the block is saved to the block store
  1  after block save, before the WAL EndHeight fsync
  2  after the EndHeight fsync, before ApplyBlock   <- the crash window
  3  after the FinalizeBlock response is persisted, before the state save
  4  after the state save, before the app Commit
"""

from __future__ import annotations

import os
import sys

_ENV = "FAIL_TEST_INDEX"
_index: int | None = None


def _target() -> int:
    global _index
    if _index is None:
        try:
            _index = int(os.environ.get(_ENV, "-1"))
        except ValueError:
            _index = -1
    return _index


def fail(call_index: int) -> None:
    """Die iff this call site's index matches FAIL_TEST_INDEX."""
    if call_index == _target():
        sys.stderr.write(f"*** fail-point {call_index} triggered ***\n")
        sys.stderr.flush()
        os._exit(99)
