"""Prometheus-style metrics, dependency-free.

Reference: each subsystem's metrics.go (consensus/metrics.go:20-133,
mempool/metrics.go, p2p/metrics.go, state/metrics.go) built on go-kit +
prometheus. Same shape here: typed per-subsystem structs over Counter /
Gauge / Histogram primitives, one process-wide Registry rendering the
Prometheus text exposition format, served by the RPC server's /metrics
route (config.instrumentation.prometheus).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence


def escape_label_value(v: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash, double
    quote, and newline must be escaped or a scraper misparses the series
    (a chaos spec or error string in a label value can contain all
    three)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(s: str) -> str:
    """# HELP line escaping: backslash and newline only (quotes are legal
    there)."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(pairs) -> str:
    return ",".join(f'{n}="{escape_label_value(v)}"' for n, v in pairs)


class _Metric:
    def __init__(self, name: str, help_: str, labels: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def labels(self, *label_values: str) -> "_Bound":
        if len(label_values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: want {len(self.label_names)} labels, got {len(label_values)}")
        return _Bound(self, tuple(str(v) for v in label_values))

    def value(self, *label_values: str) -> float:
        """Current value for the label combination (0.0 if never set) —
        the assertion-friendly read side for tests and health snapshots."""
        key = tuple(str(v) for v in label_values)
        with self._lock:
            return self._values.get(key, 0.0)

    def _set(self, key: tuple, v: float) -> None:
        with self._lock:
            self._values[key] = v

    def _add(self, key: tuple, v: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def _fmt_key(self, key: tuple) -> str:
        if not key:
            return self.name
        return f"{self.name}{{{_fmt_labels(zip(self.label_names, key))}}}"

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {escape_help(self.help)}",
               f"# TYPE {self.name} {self.TYPE}"]
        with self._lock:
            vals = dict(self._values) or ({(): 0.0} if not self.label_names else {})
        for key, v in sorted(vals.items()):
            out.append(f"{self._fmt_key(key)} {v:g}")
        return out


class _Bound:
    def __init__(self, metric: "_Metric", key: tuple):
        self._m = metric
        self._key = key

    def set(self, v: float) -> None:
        self._m._set(self._key, v)

    def inc(self, v: float = 1.0) -> None:
        self._m._add(self._key, v)

    def observe(self, v: float) -> None:
        self._m.observe_key(self._key, v)  # type: ignore[attr-defined]


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, v: float = 1.0) -> None:
        self._add((), v)


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, v: float) -> None:
        self._set((), v)

    def inc(self, v: float = 1.0) -> None:
        self._add((), v)

    def dec(self, v: float = 1.0) -> None:
        self._add((), -v)


class Histogram(_Metric):
    """Prometheus histogram with fixed buckets."""

    TYPE = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, name: str, help_: str, labels: Sequence[str] = (),
                 buckets: Sequence[float] | None = None):
        super().__init__(name, help_, labels)
        self.buckets = tuple(buckets if buckets is not None else self.DEFAULT_BUCKETS)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, v: float) -> None:
        self.observe_key((), v)

    def observe_key(self, key: tuple, v: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            self._totals[key] = self._totals.get(key, 0) + 1

    def sum_value(self, *label_values: str) -> float:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            return self._sums.get(key, 0.0)

    def count_value(self, *label_values: str) -> int:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            return self._totals.get(key, 0)

    def render(self) -> list[str]:
        """Exposition-format series per label set, in the order scrapers
        require: cumulative _bucket lines ascending by `le`, then the
        mandatory `le="+Inf"` bucket, then _sum, then _count — with label
        values escaped. `le` renders LAST within the braces (the
        convention promtool canonicalizes to)."""
        out = [f"# HELP {self.name} {escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = list(self._totals) or ([()] if not self.label_names else [])
            for key in sorted(keys):
                counts = self._counts.get(key, [0] * len(self.buckets))
                base_pairs = list(zip(self.label_names, key))
                # per-bucket counts are recorded cumulatively by
                # observe_key; render them as-is, ascending
                for b, cum in zip(self.buckets, counts):
                    inner = _fmt_labels(base_pairs + [("le", f"{b:g}")])
                    out.append(f"{self.name}_bucket{{{inner}}} {cum}")
                inner = _fmt_labels(base_pairs + [("le", "+Inf")])
                out.append(
                    f"{self.name}_bucket{{{inner}}} {self._totals.get(key, 0)}")
                # the suffix goes on the metric NAME, before the braces
                # (the seed rendered `name{labels}_sum`, which scrapers
                # reject for any labeled histogram)
                braces = f"{{{_fmt_labels(base_pairs)}}}" if key else ""
                out.append(
                    f"{self.name}_sum{braces} {self._sums.get(key, 0.0):g}")
                out.append(
                    f"{self.name}_count{braces} {self._totals.get(key, 0)}")
        return out


class Registry:
    def __init__(self, namespace: str = "cometbft"):
        self.namespace = namespace
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(self._nm(subsystem, name), help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(self._nm(subsystem, name), help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str,
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] | None = None) -> Histogram:
        return self._register(
            Histogram(self._nm(subsystem, name), help_, labels, buckets))

    def _nm(self, subsystem: str, name: str) -> str:
        return f"{self.namespace}_{subsystem}_{name}"

    def _register(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# ------------------------------------------------- per-subsystem structs


class ConsensusMetrics:
    """consensus/metrics.go:20-133."""

    def __init__(self, reg: Registry):
        self.height = reg.gauge("consensus", "height", "Height of the chain")
        self.rounds = reg.gauge("consensus", "rounds", "Round of the current height")
        self.round_duration = reg.histogram(
            "consensus", "round_duration_seconds", "Time per consensus round")
        self.validators = reg.gauge("consensus", "validators", "Number of validators")
        self.validators_power = reg.gauge(
            "consensus", "validators_power", "Total voting power")
        self.missing_validators = reg.gauge(
            "consensus", "missing_validators", "Validators missing from the last commit")
        self.byzantine_validators = reg.gauge(
            "consensus", "byzantine_validators", "Validators with evidence against them")
        self.block_interval = reg.histogram(
            "consensus", "block_interval_seconds", "Time between blocks",
            buckets=(0.1, 0.25, 0.5, 1, 2, 5, 10, 30))
        self.num_txs = reg.gauge("consensus", "num_txs", "Txs in the latest block")
        self.block_size = reg.gauge("consensus", "block_size_bytes", "Latest block size")
        self.total_txs = reg.counter("consensus", "total_txs", "Total committed txs")
        self.vote_extension_received = reg.counter(
            "consensus", "vote_extensions_received", "Peer vote extensions seen",
            labels=("status",))
        self.batch_flushes = reg.counter(
            "consensus", "vote_batch_flushes", "Device vote-batch flushes")
        self.batch_lanes = reg.counter(
            "consensus", "vote_batch_lanes", "Signatures through batched flushes")
        # gossip accounting (fleet dimension): votes sent vs. votes the
        # peer actually needed — vote amplification as a measured number.
        # Receiver-side classification: needed = the vote advanced our
        # view; already_had = our vote set already held it (a wasted
        # send by the peer); stale = for a height we have committed past.
        # Cardinality is bounded by construction (3 statuses, no peer
        # labels — the per-peer split lives in net_telemetry's gossip
        # rollup, bounded by live peers).
        self.gossip_votes_sent = reg.counter(
            "consensus", "gossip_votes_sent",
            "Votes this node's gossip routines sent to peers")
        self.gossip_votes_received = reg.counter(
            "consensus", "gossip_votes_received",
            "Votes received from peers, by whether this node needed them",
            labels=("status",))
        self.gossip_summaries = reg.counter(
            "consensus", "gossip_vote_summaries",
            "Compact vote-summary reconciliation events (sent / applied / "
            "degraded_* = summary ignored, full gossip continues / "
            "peer_unsupported = peer never negotiated the channel)",
            labels=("event",))


class MempoolMetrics:
    """mempool/metrics.go."""

    def __init__(self, reg: Registry):
        self.size = reg.gauge("mempool", "size", "Number of uncommitted txs")
        self.size_bytes = reg.gauge("mempool", "size_bytes", "Mempool byte size")
        self.failed_txs = reg.counter("mempool", "failed_txs", "CheckTx rejections")
        self.recheck_times = reg.counter("mempool", "recheck_times", "Recheck passes")


class P2PMetrics:
    """p2p/metrics.go + the wire-plane accounting dimension.

    Cardinality policy: per-channel series label by `chID` (a handful of
    values, fixed by the reactor set). Per-peer series label by a CAPPED
    peer set — the first `peer_cap` distinct peers get their own label
    (short node id); every later peer folds into an `other` bucket, so a
    10k-peer fleet cannot explode the exposition. The cap is first-come
    (stable across a scrape's lifetime); `peer_label()` is the one
    chokepoint enforcing it."""

    def __init__(self, reg: Registry, peer_cap: int = 32):
        self.peers = reg.gauge("p2p", "peers", "Connected peers")
        self.message_send_bytes = reg.counter(
            "p2p", "message_send_bytes_total", "Bytes sent", labels=("chID",))
        self.message_receive_bytes = reg.counter(
            "p2p", "message_receive_bytes_total", "Bytes received", labels=("chID",))
        # wire-plane accounting (MConnection per-peer/per-channel counters;
        # peer labels capped — see class docstring)
        self.peer_send_bytes = reg.counter(
            "p2p", "peer_send_bytes_total",
            "Wire bytes sent per peer per channel (peer labels capped; "
            "overflow peers fold into peer=\"other\")",
            labels=("peer", "chID"))
        self.peer_receive_bytes = reg.counter(
            "p2p", "peer_receive_bytes_total",
            "Wire bytes received per peer per channel (capped peer set)",
            labels=("peer", "chID"))
        self.peer_send_msgs = reg.counter(
            "p2p", "peer_send_messages_total",
            "Messages sent per peer per channel (capped peer set)",
            labels=("peer", "chID"))
        self.peer_receive_msgs = reg.counter(
            "p2p", "peer_receive_messages_total",
            "Messages received per peer per channel (capped peer set)",
            labels=("peer", "chID"))
        self.peer_ping_rtt = reg.gauge(
            "p2p", "peer_ping_rtt_seconds",
            "Last ping->pong round trip per peer (capped peer set)",
            labels=("peer",))
        # misbehavior-scoring plane (p2p/switch.py PeerScorer): byzantine
        # peers must lose their connection slot, not just their messages
        self.peer_misbehavior = reg.counter(
            "p2p", "peer_misbehavior",
            "Misbehavior reports scored against peers", labels=("reason",))
        self.peer_bans = reg.counter(
            "p2p", "peer_bans",
            "Peers banned after repeated misbehavior")
        # discovery plane (p2p/pex/addrbook.py hashed-bucket book)
        self.addrbook_size = reg.gauge(
            "p2p", "addrbook_size",
            "Address-book entries by set (hashed-bucket geometry)",
            labels=("set",))
        self.addrbook_overwrite_rejected = reg.counter(
            "p2p", "addrbook_overwrite_rejected_total",
            "Gossip records rejected because they would overwrite the "
            "host:port of a successfully-tried (OLD) address")
        self.addrbook_quarantined = reg.counter(
            "p2p", "addrbook_quarantined_total",
            "Corrupt address-book files quarantined to .corrupt at load")
        self.peer_cap = peer_cap
        # label-slot ledger (bounded under churn storms — ISSUE 12):
        #   _peer_labels  ids currently OWNING a label (<= peer_cap live
        #                 owners; a returning released peer may briefly
        #                 push past while its old label is re-armed)
        #   _released     past owners, newest last (<= peer_cap): a peer
        #                 whose ban expired re-claims its OWN label
        #                 instead of minting a new exposition series
        #   _minted       distinct labels ever created — the HARD
        #                 exposition bound (2x peer_cap): counter series
        #                 persist after release, so reclaimed slots must
        #                 not mint fresh label values forever
        # Overflow ids are NOT cached (a churn storm past the cap must
        # not grow this map without bound).
        self._peer_labels: dict[str, str] = {}
        self._released: dict[str, str] = {}
        self._minted = 0
        self._peer_lock = threading.Lock()

    OTHER_PEER_LABEL = "other"

    @property
    def mint_cap(self) -> int:
        """Distinct per-peer label values ever allowed on the exposition
        (live + released-but-persisting series)."""
        return 2 * self.peer_cap

    def peer_label(self, node_id: str) -> str:
        """Bounded-cardinality peer label: up to peer_cap LIVE peers own
        their short-id label; a released peer (disconnect, ban) frees its
        slot and — returning later — gets its old label back; past the
        mint cap, new peers fold into "other" even when slots are free
        (the exposition is already at its bound)."""
        if not node_id:
            return self.OTHER_PEER_LABEL
        with self._peer_lock:
            label = self._peer_labels.get(node_id)
            if label is not None:
                return label
            label = self._released.pop(node_id, None)
            if label is not None:  # ban expired / redial: same series
                self._peer_labels[node_id] = label
                return label
            if (len(self._peer_labels) < self.peer_cap
                    and self._minted < self.mint_cap):
                label = node_id[:10]
                self._peer_labels[node_id] = label
                self._minted += 1
                return label
            return self.OTHER_PEER_LABEL

    def release_peer(self, node_id: str) -> None:
        """Free a disconnected/banned peer's label slot. Its label is
        remembered (bounded FIFO) so the SAME peer returning re-claims
        it; the oldest released memory is dropped past peer_cap — such a
        peer returning after a long churn storm reads as new."""
        with self._peer_lock:
            label = self._peer_labels.pop(node_id, None)
            if label is None:
                return
            self._released.pop(node_id, None)
            self._released[node_id] = label
            while len(self._released) > self.peer_cap:
                del self._released[next(iter(self._released))]

    def peer_label_stats(self) -> dict:
        """Ledger introspection for tests/health: all bounded."""
        with self._peer_lock:
            return {"owners": len(self._peer_labels),
                    "released": len(self._released),
                    "minted": self._minted,
                    "mint_cap": self.mint_cap}

    def record_conn_traffic(self, peer_label: str, per_chan: dict,
                            send: bool) -> None:
        """Apply a batch of per-channel (bytes, msgs) deltas from one
        MConnection flush. `peer_label` must already be capped (the
        Switch hands each Peer its label at construction)."""
        peer = peer_label or self.OTHER_PEER_LABEL
        byte_m = self.peer_send_bytes if send else self.peer_receive_bytes
        msg_m = self.peer_send_msgs if send else self.peer_receive_msgs
        chan_m = self.message_send_bytes if send else self.message_receive_bytes
        for cid, (nbytes, nmsgs) in per_chan.items():
            ch = f"{cid:#x}" if isinstance(cid, int) else str(cid)
            if nbytes:
                byte_m.labels(peer, ch).inc(nbytes)
                chan_m.labels(ch).inc(nbytes)
            if nmsgs:
                msg_m.labels(peer, ch).inc(nmsgs)


class EvidenceMetrics:
    """Evidence-pool observability (no dedicated reference struct; the
    reference folds this into consensus metrics — split out here so the
    byzantine-resilience tests can assert detection end-to-end)."""

    def __init__(self, reg: Registry):
        self.evidence_committed = reg.counter(
            "evidence", "committed",
            "Byzantine-behavior proofs committed into blocks")
        self.evidence_pending = reg.gauge(
            "evidence", "pending", "Verified evidence awaiting commitment")


class StateMetrics:
    """state/metrics.go."""

    def __init__(self, reg: Registry):
        self.block_processing_time = reg.histogram(
            "state", "block_processing_time", "ApplyBlock seconds",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5))


class CryptoMetrics:
    """TPU dimension (no reference analog): device batch activity."""

    def __init__(self, reg: Registry):
        self.device_batches = reg.counter(
            "crypto", "device_batches", "Kernel dispatches", labels=("kind",))
        self.device_lanes = reg.counter(
            "crypto", "device_lanes", "Signature lanes dispatched", labels=("kind",))
        self.device_seconds = reg.counter(
            "crypto", "device_seconds", "Estimated device-busy seconds")
        # transfer-integrity plane: a tunnel-attached device must EARN the
        # in-process-memory trust the reference assumes (validation.go:235)
        self.transfer_checksum_mismatch = reg.counter(
            "crypto", "transfer_checksum_mismatch",
            "Host->device staging checksum failures detected on device")
        self.mask_echo_mismatch = reg.counter(
            "crypto", "mask_echo_mismatch",
            "Device->host mask fetches whose redundant echo disagreed")
        self.mask_oracle_disagreement = reg.counter(
            "crypto", "mask_oracle_disagreement",
            "Device-rejected lanes the host oracle re-accepted")
        # backend-health plane (device-fault resilience layer,
        # ops/dispatch.py): which rung of the TPU->XLA->CPU ladder is
        # serving verifies, and how the supervisors are doing
        self.backend_active = reg.gauge(
            "crypto", "backend_active",
            "1 for the backend currently serving verify batches",
            labels=("backend",))
        self.breaker_state = reg.gauge(
            "crypto", "breaker_state",
            "Device circuit breaker: 0 closed, 1 half-open, 2 open",
            labels=("name",))
        self.device_retries = reg.counter(
            "crypto", "device_retries",
            "Transient device-op retries (backoff path)", labels=("name",))
        self.device_failures = reg.counter(
            "crypto", "device_failures",
            "Supervised device operations that failed after retries",
            labels=("name", "class"))
        self.breaker_transitions = reg.counter(
            "crypto", "breaker_transitions",
            "Circuit breaker state transitions", labels=("name", "to"))
        self.fallback_verifies = reg.counter(
            "crypto", "fallback_verifies",
            "Signature lanes verified on the CPU ladder after a device "
            "failure", labels=("scheme",))
        # staging plane (ops/hashvec + reduced-fetch protocol): how often
        # the happy path keeps the mask off the tunnel, and how the
        # decompressed-pubkey cache is doing
        self.verify_fetches = reg.counter(
            "crypto", "verify_fetches",
            "Device->host verify result fetches by path (happy = 8-byte "
            "header only; full = header + per-lane payload)",
            labels=("path",))
        self.verify_fetch_bytes = reg.counter(
            "crypto", "verify_fetch_bytes",
            "Bytes transferred by verify result fetches, by path",
            labels=("path",))
        self.pubkey_cache_events = reg.counter(
            "crypto", "pubkey_cache_events",
            "Decompressed-pubkey cache hits/misses/evictions per level "
            "(host bytes->coords FIFO; device-resident digest slots)",
            labels=("level", "event"))
        # send-side wire accounting (reduced-send protocol,
        # ops/residency.py), the twin of verify_fetch_bytes{path}:
        # indexed = 2-byte validator indices + staged r/s/k words
        # (steady state); delta = validator-set churn row uploads;
        # full = full-key fallback (coordinate tables + 4-byte indices)
        self.verify_sends = reg.counter(
            "crypto", "verify_sends",
            "Host->device verify staging transfers by send path",
            labels=("path",))
        self.verify_send_bytes = reg.counter(
            "crypto", "verify_send_bytes",
            "Bytes transferred by host->device verify staging, by send "
            "path", labels=("path",))


class MeshMetrics:
    """Multi-chip verify-mesh observability (parallel/mesh.py — no
    reference analog): live mesh size, per-chip breaker state, shard
    redispatch/eviction/readmission churn, and the all-chips-dead
    fallback count. Process-global like CryptoMetrics — the device mesh
    is one per process."""

    def __init__(self, reg: Registry):
        self.verify_mesh_size = reg.gauge(
            "crypto", "verify_mesh_size",
            "Live verify-mesh size: chips whose breaker currently admits "
            "shards (0 = all fault domains dead, ladder fallback engaged)")
        self.mesh_devices = reg.gauge(
            "crypto", "mesh_devices",
            "Total chips the verify mesh was built over")
        self.mesh_breaker_state = reg.gauge(
            "crypto", "mesh_breaker_state",
            "Per-chip fault-domain breaker: 0 closed, 1 half-open, 2 open",
            labels=("device",))
        self.mesh_redispatch_total = reg.counter(
            "crypto", "mesh_redispatch_total",
            "In-flight shards re-dispatched onto surviving chips after "
            "their fault domain failed, by failure class",
            labels=("reason",))
        self.mesh_evictions_total = reg.counter(
            "crypto", "mesh_evictions_total",
            "Chips evicted from the live mesh (breaker opened)")
        self.mesh_readmissions_total = reg.counter(
            "crypto", "mesh_readmissions_total",
            "Chips readmitted to the live mesh (half-open probe healed)")
        self.mesh_fallback_total = reg.counter(
            "crypto", "mesh_fallback_total",
            "Batches that fell off an all-chips-dead mesh onto the "
            "single-chip XLA->CPU ladder")
        self.mesh_shard_lanes = reg.counter(
            "crypto", "mesh_shard_lanes",
            "Padded verify lanes dispatched per chip (the scheduler's "
            "per-chip lane-fill evidence)", labels=("device",))


class SchedMetrics:
    """Verify-scheduler observability (sched/scheduler.py — no reference
    analog): how full the continuously-batched device batches run, how
    deep each priority class queues, and whether deadline flushing keeps
    up. Process-global like CryptoMetrics — one scheduler per process."""

    def __init__(self, reg: Registry):
        self.batch_lanes = reg.histogram(
            "verify_sched", "batch_lanes",
            "Padded lane count of each dispatched verify batch",
            buckets=(8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
                     8192, 16384))
        self.fill_ratio = reg.histogram(
            "verify_sched", "fill_ratio",
            "Rows / padded lanes per dispatched verify batch",
            buckets=(0.1, 0.25, 0.5, 0.625, 0.75, 0.875, 0.95, 1.0))
        self.queue_depth = reg.gauge(
            "verify_sched", "queue_depth",
            "Signature rows queued per priority class", labels=("class",))
        self.flush_deadline_misses = reg.counter(
            "verify_sched", "flush_deadline_misses",
            "Groups flushed past their deadline (plus slack)")
        self.flush_latency = reg.histogram(
            "verify_sched", "flush_latency_seconds",
            "Submit-to-dispatch latency per priority class",
            labels=("class",),
            buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 1.0))


class LightFleetMetrics:
    """Light-client serving-plane observability (light/fleet.py — no
    reference analog): how requests resolve (cache hit / coalesced onto
    an in-flight verification / freshly verified / shed / error), the
    checkpoint-cache churn, and the streaming-subscriber lifecycle.
    Process-global like SchedMetrics — the fleet rides the process's
    verify plane."""

    def __init__(self, reg: Registry):
        self.requests = reg.counter(
            "light_fleet", "requests_total",
            "Fleet verification requests by result (hit = checkpoint "
            "cache; coalesced = shared an in-flight bisection; verified "
            "= ran a fresh bisection; saturated = shed at admission)",
            labels=("result",))
        self.cache_events = reg.counter(
            "light_fleet", "cache_events",
            "Checkpoint skip-list cache events (hit/miss/evict/prune; "
            "prune = trusting-period expiry)", labels=("event",))
        self.request_seconds = reg.histogram(
            "light_fleet", "request_seconds",
            "Wall seconds per UNIQUE fleet verification (cache hits and "
            "coalesced waits excluded)",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 10.0))
        self.inflight = reg.gauge(
            "light_fleet", "inflight",
            "Unique verifications currently in flight")
        self.subscribers = reg.gauge(
            "light_fleet", "subscribers", "Live streaming subscribers")
        self.streamed = reg.counter(
            "light_fleet", "streamed_headers_total",
            "Verified headers streamed to subscribers")
        self.subscriber_drops = reg.counter(
            "light_fleet", "subscriber_drops_total",
            "Subscriptions the fleet closed, by reason (backpressure = "
            "queue high water; budget = per-client send budget spent)",
            labels=("reason",))


class CertMetrics:
    """Commit-certificate plane observability (cert/plane.py — no
    reference analog): the produce/serve/verify/fallback lifecycle of
    succinct finality certificates. Per-node (the plane rides each
    node's stores), registered on the node's registry so the e2e runner
    reads backfill progress off /metrics."""

    def __init__(self, reg: Registry):
        self.cert_produced = reg.counter(
            "cert", "produced_total",
            "Commit certificates produced (event-driven at finalize plus "
            "backfill)")
        self.cert_backfilled = reg.counter(
            "cert", "backfilled_total",
            "Certificates produced by the historical backfill worker "
            "(subset of produced_total)")
        self.cert_served = reg.counter(
            "cert", "served_total",
            "Certificates served to consumers (RPC + blocksync)")
        self.cert_verified = reg.counter(
            "cert", "verified_total",
            "Certificates that decided a commit via the one-pairing "
            "check (light + blocksync consumers)")
        self.cert_fallbacks = reg.counter(
            "cert", "fallbacks_total",
            "Held-certificate verifications that degraded to the classic "
            "per-vote path (invalid/mismatched/corrupt certificate — "
            "counted, never a wrong verdict)")


class OverloadMetrics:
    """Overload resilience plane observability (libs/overload.py — no
    reference analog): per-plane watermark levels and shed accounting.
    Process-global like SchedMetrics — the registry instances are
    per-node but the series are shared, labeled by plane (in-proc test
    nets aggregate, exactly like the scheduler's queue-depth series)."""

    def __init__(self, reg: Registry):
        self.level = reg.gauge(
            "overload", "level",
            "Watermark level per plane (0=normal 1=elevated 2=saturated)",
            labels=("plane",))
        self.sheds = reg.counter(
            "overload", "sheds_total",
            "Requests/txs shed by the coordinated overload policy, per "
            "plane (rpc = in-flight budget, mempool = admission gate, "
            "sched = verify-queue backpressure, events = subscriber "
            "lag)", labels=("plane",))
        self.transitions = reg.counter(
            "overload", "level_transitions_total",
            "Watermark level transitions per plane (a flapping signal "
            "here means the hysteresis band is too narrow)",
            labels=("plane",))


_global: Optional[Registry] = None


def global_registry() -> Registry:
    global _global
    if _global is None:
        _global = Registry()
    return _global


class NetChaosMetrics:
    """Injected network-fault observability (p2p/netchaos.py). Process-
    global like CryptoMetrics: the netchaos registry is one per process."""

    def __init__(self, reg: Registry):
        self.partition_heal_seconds = reg.gauge(
            "p2p", "partition_heal_seconds",
            "Seconds from partition heal to first traffic across a "
            "formerly-cut link")
        self.net_faults = reg.counter(
            "p2p", "net_chaos_faults",
            "Injected network faults by kind", labels=("kind",))


class StorageMetrics:
    """Storage-plane observability (libs/diskchaos, consensus/wal,
    store/db — no reference analog): WAL fsync latency, torn-tail
    truncations and wal-repair runs, db write latency, CRC-guard
    corruption detections, and a per-(site,kind) counter for every
    injected disk fault. Process-global like CryptoMetrics — the disk
    chaos registry and the latency rollups are one per process. The
    `storage_health` RPC section is rendered from health()."""

    # rolling percentile windows: Prometheus histograms lose p50/p99
    # resolution to bucket edges; operators reading storage_health get
    # exact percentiles over the recent window instead
    WINDOW = 4096

    def __init__(self, reg: Registry):
        self.wal_fsync_seconds = reg.histogram(
            "storage", "wal_fsync_seconds", "Consensus WAL fsync latency",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 1.0))
        self.wal_truncations = reg.counter(
            "storage", "wal_truncations",
            "Torn WAL tails repaired by truncation during replay")
        self.wal_repairs = reg.counter(
            "storage", "wal_repairs",
            "wal-repair runs that quarantined a mid-group corrupt chunk")
        self.db_write_seconds = reg.histogram(
            "storage", "db_write_seconds",
            "SQLite write-transaction latency (set/delete/batch)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 1.0))
        self.disk_faults = reg.counter(
            "storage", "disk_faults",
            "Injected disk faults by seam and kind (libs/diskchaos)",
            labels=("site", "kind"))
        self.corruption_detected = reg.counter(
            "storage", "corruption_detected",
            "CRC-guarded records that failed their checksum on read")
        self._lock = threading.Lock()
        self._wal_lat: deque[float] = deque(maxlen=self.WINDOW)
        self._db_lat: deque[float] = deque(maxlen=self.WINDOW)

    def observe_wal_fsync(self, seconds: float) -> None:
        self.wal_fsync_seconds.observe(seconds)
        with self._lock:
            self._wal_lat.append(seconds)

    def observe_db_write(self, seconds: float) -> None:
        self.db_write_seconds.observe(seconds)
        with self._lock:
            self._db_lat.append(seconds)

    @staticmethod
    def _pct(sorted_vals: list[float], q: float) -> float | None:
        if not sorted_vals:
            return None
        return sorted_vals[min(len(sorted_vals) - 1,
                               int(len(sorted_vals) * q))]

    def health(self) -> dict:
        """The storage_health RPC's metric section: exact p50/p99 over
        the recent latency windows plus the counter rollups."""
        with self._lock:
            wal = sorted(self._wal_lat)
            db = sorted(self._db_lat)
        # snapshot under the counter's own lock: a fault firing on
        # another thread may be inserting a new (site,kind) series
        with self.disk_faults._lock:
            fault_items = sorted(self.disk_faults._values.items())
        ms = 1000.0
        return {
            "wal": {
                "fsyncs": self.wal_fsync_seconds.count_value(),
                "fsync_p50_ms": (self._pct(wal, 0.50) or 0.0) * ms if wal else None,
                "fsync_p99_ms": (self._pct(wal, 0.99) or 0.0) * ms if wal else None,
                "truncations": self.wal_truncations.value(),
                "repairs": self.wal_repairs.value(),
            },
            "db": {
                "writes": self.db_write_seconds.count_value(),
                "write_p50_ms": (self._pct(db, 0.50) or 0.0) * ms if db else None,
                "write_p99_ms": (self._pct(db, 0.99) or 0.0) * ms if db else None,
            },
            "corruption_detected": self.corruption_detected.value(),
            "disk_faults": {
                "{}:{}".format(*key): v for key, v in fault_items
            },
        }


_crypto: Optional[CryptoMetrics] = None
_crypto_lock = threading.Lock()


def crypto_metrics() -> CryptoMetrics:
    """Process-global CryptoMetrics on the global registry. The device is a
    process-global resource, so its health plane is too (unlike the
    per-node Consensus/Mempool/P2P structs). Double-checked init: racing
    first calls must not register duplicate series."""
    global _crypto
    if _crypto is None:
        with _crypto_lock:
            if _crypto is None:
                _crypto = CryptoMetrics(global_registry())
    return _crypto


_sched: Optional[SchedMetrics] = None


def sched_metrics() -> SchedMetrics:
    """Process-global SchedMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _sched
    if _sched is None:
        with _crypto_lock:
            if _sched is None:
                _sched = SchedMetrics(global_registry())
    return _sched


_mesh: Optional[MeshMetrics] = None


def mesh_metrics() -> MeshMetrics:
    """Process-global MeshMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _mesh
    if _mesh is None:
        with _crypto_lock:
            if _mesh is None:
                _mesh = MeshMetrics(global_registry())
    return _mesh


_light_fleet: Optional[LightFleetMetrics] = None


def light_fleet_metrics() -> LightFleetMetrics:
    """Process-global LightFleetMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _light_fleet
    if _light_fleet is None:
        with _crypto_lock:
            if _light_fleet is None:
                _light_fleet = LightFleetMetrics(global_registry())
    return _light_fleet


_netchaos: Optional[NetChaosMetrics] = None


def netchaos_metrics() -> NetChaosMetrics:
    """Process-global NetChaosMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _netchaos
    if _netchaos is None:
        with _crypto_lock:
            if _netchaos is None:
                _netchaos = NetChaosMetrics(global_registry())
    return _netchaos


_storage: Optional[StorageMetrics] = None


def storage_metrics() -> StorageMetrics:
    """Process-global StorageMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _storage
    if _storage is None:
        with _crypto_lock:
            if _storage is None:
                _storage = StorageMetrics(global_registry())
    return _storage


_overload: Optional[OverloadMetrics] = None


def overload_metrics() -> OverloadMetrics:
    """Process-global OverloadMetrics on the global registry (same
    double-checked init discipline as crypto_metrics)."""
    global _overload
    if _overload is None:
        with _crypto_lock:
            if _overload is None:
                _overload = OverloadMetrics(global_registry())
    return _overload
