"""Query-addressable pubsub server — the EventBus substrate.

Reference: libs/pubsub/pubsub.go:90-342 (server) and libs/pubsub/query/
(peg-generated parser). Subscribers register a client id + a query string
like:

    tm.event = 'Tx' AND tx.height > 5 AND account.name CONTAINS 'fred'

and receive every published message whose event map matches. Events are
composite-keyed: {"tm.event": ["Tx"], "tx.hash": ["AB12.."], ...}.

The reference generates its parser with peg; a hand-rolled tokenizer +
recursive descent covers the same grammar (conditions joined by AND;
operators = != < <= > >= CONTAINS EXISTS; string/number operands).
"""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from typing import Any, Optional

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<op><=|>=|!=|=|<|>)
      | (?P<kw>\bAND\b|\bCONTAINS\b|\bEXISTS\b)
      | (?P<str>'(?:[^'\\]|\\.)*')
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


class QueryError(ValueError):
    pass


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '!=', '<', '<=', '>', '>=', 'CONTAINS', 'EXISTS'
    operand: Any = None  # str | float | None

    def matches(self, values: list[str]) -> bool:
        if self.op == "EXISTS":
            return bool(values)
        for v in values:
            if self._match_one(v):
                return True
        return False

    def _match_one(self, v: str) -> bool:
        op, operand = self.op, self.operand
        if op == "CONTAINS":
            return str(operand) in v
        if isinstance(operand, float):
            try:
                num = float(v)
            except ValueError:
                return False
            return {
                "=": num == operand, "!=": num != operand,
                "<": num < operand, "<=": num <= operand,
                ">": num > operand, ">=": num >= operand,
            }[op]
        if op == "=":
            return v == operand
        if op == "!=":
            return v != operand
        return False  # ordered ops need numeric operands


class Query:
    """libs/pubsub/query/query.go — immutable compiled query."""

    def __init__(self, s: str):
        self.str_ = s.strip()
        self.conditions = _parse(self.str_)

    def matches(self, events: dict[str, list[str]]) -> bool:
        return all(c.matches(events.get(c.key, [])) for c in self.conditions)

    def __str__(self) -> str:
        return self.str_

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self.str_ == other.str_

    def __hash__(self) -> int:
        return hash(self.str_)


def _tokenize(s: str):
    pos = 0
    out = []
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None or m.end() == pos:
            if s[pos:].strip():
                raise QueryError(f"bad token at {s[pos:]!r}")
            break
        pos = m.end()
        kind = m.lastgroup
        out.append((kind, m.group(kind)))
    return out


def _parse(s: str) -> list[Condition]:
    if not s:
        raise QueryError("empty query")
    toks = _tokenize(s)
    conds: list[Condition] = []
    i = 0
    while i < len(toks):
        if toks[i][0] != "key":
            raise QueryError(f"expected key, got {toks[i][1]!r}")
        key = toks[i][1]
        i += 1
        if i >= len(toks):
            raise QueryError(f"dangling key {key!r}")
        kind, tok = toks[i]
        if kind == "kw" and tok == "EXISTS":
            conds.append(Condition(key, "EXISTS"))
            i += 1
        elif kind == "kw" and tok == "CONTAINS":
            i += 1
            if i >= len(toks) or toks[i][0] != "str":
                raise QueryError("CONTAINS requires a string operand")
            conds.append(Condition(key, "CONTAINS", _unquote(toks[i][1])))
            i += 1
        elif kind == "op":
            op = tok
            i += 1
            if i >= len(toks):
                raise QueryError(f"operator {op!r} missing operand")
            vkind, vtok = toks[i]
            if vkind == "str":
                conds.append(Condition(key, op, _unquote(vtok)))
            elif vkind == "num":
                conds.append(Condition(key, op, float(vtok)))
            else:
                raise QueryError(f"bad operand {vtok!r}")
            i += 1
        else:
            raise QueryError(f"expected operator after {key!r}, got {tok!r}")
        if i < len(toks):
            if toks[i] != ("kw", "AND"):
                raise QueryError(f"expected AND, got {toks[i][1]!r}")
            i += 1
            if i >= len(toks):
                raise QueryError("dangling AND")
    return conds


def _unquote(s: str) -> str:
    return s[1:-1].replace("\\'", "'").replace("\\\\", "\\")


# --------------------------------------------------------------- the server


@dataclass
class Message:
    data: Any
    events: dict[str, list[str]]


class Subscription:
    """pubsub.go Subscription: a bounded queue + cancellation signal.
    capacity=0 means unbounded — the SubscribeUnbuffered analog
    (pubsub.go:191) for consumers that must never be dropped (indexer)."""

    def __init__(self, query: Query, capacity: int):
        self.query = query
        self.out: asyncio.Queue = asyncio.Queue(maxsize=capacity)
        self.canceled: Optional[str] = None  # reason when terminated

    def cancel(self, reason: str) -> None:
        self.canceled = reason
        try:
            self.out.put_nowait(None)  # wake the consumer
        except asyncio.QueueFull:
            pass


class ErrAlreadySubscribed(Exception):
    pass


class ErrSubscriptionNotFound(Exception):
    pass


class Server:
    """pubsub.go:90 Server. publish() is synchronous fan-out on the caller's
    task (the reference serializes through a channel; a single asyncio loop
    gives the same ordering for free). A subscriber that falls behind its
    buffer is cancelled rather than back-pressuring consensus
    (out-of-capacity semantics)."""

    def __init__(self, capacity_per_subscription: int = 256):
        self.capacity = capacity_per_subscription
        # client_id -> query_str -> Subscription
        self._subs: dict[str, dict[str, Subscription]] = {}

    def subscribe(self, client_id: str, query: str | Query,
                  capacity: int | None = None) -> Subscription:
        """capacity=None -> server default; 0 -> unbounded (unbuffered-
        subscriber semantics: never cancelled for falling behind)."""
        q = query if isinstance(query, Query) else Query(query)
        by_q = self._subs.setdefault(client_id, {})
        if q.str_ in by_q:
            raise ErrAlreadySubscribed(f"{client_id!r} already subscribed to {q.str_!r}")
        sub = Subscription(q, self.capacity if capacity is None else capacity)
        by_q[q.str_] = sub
        return sub

    def unsubscribe(self, client_id: str, query: str | Query) -> None:
        qs = query.str_ if isinstance(query, Query) else Query(query).str_
        by_q = self._subs.get(client_id, {})
        sub = by_q.pop(qs, None)
        if sub is None:
            raise ErrSubscriptionNotFound(f"{client_id!r} not subscribed to {qs!r}")
        sub.cancel("unsubscribed")
        if not by_q:
            self._subs.pop(client_id, None)

    def unsubscribe_all(self, client_id: str) -> None:
        by_q = self._subs.pop(client_id, None)
        if not by_q:
            raise ErrSubscriptionNotFound(f"{client_id!r} has no subscriptions")
        for sub in by_q.values():
            sub.cancel("unsubscribed")

    def num_clients(self) -> int:
        return len(self._subs)

    def max_lag_fraction(self) -> float:
        """The events plane's overload signal (libs/overload.py): the
        worst subscriber's queue fill fraction. Unbounded subscribers
        (capacity 0, e.g. the indexer) can't lag by this definition —
        they never drop — so they read 0."""
        worst = 0.0
        for by_q in self._subs.values():
            for sub in by_q.values():
                cap = sub.out.maxsize
                if cap > 0:
                    worst = max(worst, sub.out.qsize() / cap)
        return worst

    def num_client_subscriptions(self, client_id: str) -> int:
        return len(self._subs.get(client_id, {}))

    def publish(self, data: Any, events: dict[str, list[str]] | None = None) -> None:
        events = events or {}
        msg = Message(data, events)
        for client_id, by_q in list(self._subs.items()):
            for qs, sub in list(by_q.items()):
                if sub.canceled is not None or not sub.query.matches(events):
                    continue
                try:
                    sub.out.put_nowait(msg)
                except asyncio.QueueFull:
                    sub.cancel("out of capacity")
                    by_q.pop(qs, None)
                    if not by_q:
                        self._subs.pop(client_id, None)
