"""Single-flight dedup for async work: concurrent callers for one key
share the FIRST caller's result instead of repeating the computation.

The pattern first appeared in mempool CheckTx dedup (mempool/mempool.py —
left in place there: its flight result is interwoven with the tx cache
and sender bookkeeping); the light client's per-height bisections and the
fleet service's coalesced verifications reuse THIS helper so the
shield/cancellation edge cases live in one audited place:

  - waiters `asyncio.shield` the first flight's future, so a cancelled
    WAITER never cancels the shared flight;
  - a cancelled FIRST flight leaves its waiters with an unknown result —
    they re-run the thunk themselves rather than propagate a foreign
    cancellation;
  - a failing flight fans its exception to every waiter (consumed on the
    future so no never-retrieved warning), and the key is released in
    all cases.

Event-loop-confined (no locks): callers share one asyncio loop, which is
every current consumer's model.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Hashable


class SingleFlight:
    """A keyed map of in-flight computations. `do(key, thunk)` returns
    (shared, result): shared=True when this call coalesced onto another
    caller's flight — the accounting hook coalescing layers need."""

    def __init__(self):
        self._inflight: dict[Hashable, asyncio.Future] = {}

    def __len__(self) -> int:
        return len(self._inflight)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._inflight

    async def do(self, key: Hashable,
                 thunk: Callable[[], Awaitable]) -> tuple[bool, object]:
        first = self._inflight.get(key)
        if first is not None:
            try:
                return True, await asyncio.shield(first)
            except asyncio.CancelledError:
                if not first.cancelled():
                    raise  # WE were cancelled, not the first caller
                # first flight cancelled mid-run: its result is unknown;
                # run the thunk ourselves (possibly becoming the new
                # first flight for later arrivals)
                return await self.do(key, thunk)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        try:
            res = await thunk()
        except BaseException as e:
            if not fut.done():
                if isinstance(e, Exception):
                    fut.set_exception(e)
                    fut.exception()  # consumed: no never-retrieved warning
                else:  # CancelledError: waiters retry on their own
                    fut.cancel()
            raise
        else:
            fut.set_result(res)
            return False, res
        finally:
            self._inflight.pop(key, None)
