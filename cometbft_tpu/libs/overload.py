"""Node-wide overload resilience plane (no reference analog).

Every fault plane so far injects *failures* (device, network, disk);
this plane handles *saturation* — sustained admission traffic past what
the node can absorb. Before it, each subsystem shed by its own ad-hoc
rule (`ErrMempoolIsFull`, `FleetSaturated`, `SchedulerSaturated`) with
no shared view of pressure: the RPC plane would happily queue work for
a mempool that was already drowning, and a recheck storm after a big
block could starve admission for seconds.

The registry here is that shared view: each plane registers one cheap
utilization signal (a callable returning 0.0..1.0+, fraction of that
plane's capacity) that already exists —

  rpc      in-flight requests vs the per-route-class budgets
  mempool  txs/bytes vs the pool caps
  sched    verify-scheduler queue depth vs its queue limit
  events   event-bus subscriber lag vs queue capacity

— and the registry grades each into one of three watermark levels with
hysteresis, so every plane sheds by the SAME policy:

  normal     admit everything
  elevated   trim optional work (eager mempool expiry, gossip throttle,
             smaller batches) but admit
  saturated  shed MEMPOOL/LIGHT-class work at the door, BEFORE it costs
             an ABCI round-trip or a device batch; broadcast_tx_sync
             downgrades to async

CONSENSUS/SYNC-class work is never shed at any level — under overload
the chain keeps committing (bounded p99 height latency, zero consensus
flush deadline misses) while the planes around it degrade. That
liveness guarantee is graded end-to-end by the saturation soak
(`bench.py --soak`, tests/test_overload_soak.py).

Hysteresis: a level is entered when utilization crosses its watermark
and only left when utilization drops BELOW `watermark - hysteresis` —
a signal oscillating exactly at a boundary holds its level instead of
flapping (and re-flapping the shed policy) every sample.

Every shed is counted per plane both here (the `health()` snapshot
served by the `health` RPC route) and on /metrics
(`cometbft_overload_sheds_total{plane=...}`).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

# watermark levels (ordered: comparisons like `level >= ELEVATED` are
# the intended idiom)
NORMAL = 0
ELEVATED = 1
SATURATED = 2
LEVEL_NAMES = ("normal", "elevated", "saturated")

# the planes the node wires by default (tests may register others; the
# registry accepts any name — this tuple is documentation + the metrics
# pre-touch list so every plane's series exist before its first shed)
PLANES = ("rpc", "mempool", "sched", "events")

# default watermarks as utilization fractions: elevated at 60% of a
# plane's capacity, saturated at 90% (shedding at 90% full is the
# point — at 100% the ad-hoc "is_full" errors fire anyway, AFTER the
# work was paid for)
DEFAULT_ELEVATED = 0.60
DEFAULT_SATURATED = 0.90
DEFAULT_HYSTERESIS = 0.10

# retry-after hints handed to shed clients per level, in ms — rough
# "when might a slot open" guidance, not a promise
RETRY_AFTER_MS = {NORMAL: 0, ELEVATED: 100, SATURATED: 1000}


class OverloadRegistry:
    """Per-node pressure registry: watermark state machine + shed
    accounting. Thread-safe — the verify scheduler's worker thread and
    the asyncio planes sample it concurrently."""

    def __init__(
        self,
        elevated: float = DEFAULT_ELEVATED,
        saturated: float = DEFAULT_SATURATED,
        hysteresis: float = DEFAULT_HYSTERESIS,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 < elevated < saturated:
            raise ValueError("need 0 < elevated < saturated watermarks")
        if hysteresis < 0 or hysteresis >= elevated:
            raise ValueError("hysteresis must be in [0, elevated)")
        self.elevated = elevated
        self.saturated = saturated
        self.hysteresis = hysteresis
        self._clock = clock
        self._lock = threading.Lock()
        self._sources: dict[str, Callable[[], float]] = {}
        self._levels: dict[str, int] = {}
        self._sheds: dict[str, int] = {}
        self._transitions: dict[str, int] = {}
        self._last_util: dict[str, float] = {}
        self._since: dict[str, float] = {}

    # --------------------------------------------------------- wiring

    def register(self, plane: str, source: Callable[[], float]) -> None:
        """Attach a plane's utilization signal (idempotent: re-register
        replaces the source, keeping level/shed history)."""
        with self._lock:
            self._sources[plane] = source
            self._levels.setdefault(plane, NORMAL)
            self._sheds.setdefault(plane, 0)
            self._transitions.setdefault(plane, 0)
            self._since.setdefault(plane, self._clock())

    def unregister(self, plane: str) -> None:
        with self._lock:
            self._sources.pop(plane, None)

    def planes(self) -> list[str]:
        with self._lock:
            return sorted(self._levels)

    # -------------------------------------------------------- reading

    def utilization(self, plane: str) -> float:
        """Sample a plane's signal. A broken signal reads as 0.0 — the
        overload plane must never take a node down on its own."""
        with self._lock:
            src = self._sources.get(plane)
        if src is None:
            return 0.0
        try:
            return max(0.0, float(src()))
        except Exception:  # noqa: BLE001
            return 0.0

    def level(self, plane: str) -> int:
        """Current watermark level for a plane, advancing the hysteresis
        state machine on the fresh sample."""
        util = self.utilization(plane)
        with self._lock:
            cur = self._levels.get(plane, NORMAL)
            new = self._step(cur, util)
            self._last_util[plane] = util
            if new != cur:
                self._levels[plane] = new
                self._transitions[plane] = self._transitions.get(plane, 0) + 1
                self._since[plane] = self._clock()
                self._publish_level(plane, new, transition=True)
            else:
                self._levels.setdefault(plane, cur)
        return self._levels.get(plane, NORMAL)

    def _step(self, cur: int, util: float) -> int:
        """One hysteresis step: rise eagerly at a watermark, fall only
        past `watermark - hysteresis` below it."""
        if util >= self.saturated:
            return SATURATED
        if util >= self.elevated:
            # at/above elevated but below saturated: an already-
            # saturated plane holds until util clears the sat band
            if cur == SATURATED and util >= self.saturated - self.hysteresis:
                return SATURATED
            return ELEVATED
        # below elevated: falling edges need the hysteresis margin
        if cur == SATURATED and util >= self.saturated - self.hysteresis:
            return SATURATED
        if cur >= ELEVATED and util >= self.elevated - self.hysteresis:
            return ELEVATED
        return NORMAL

    def overall(self) -> int:
        """The node-wide level: the worst plane's."""
        return max((self.level(p) for p in self.planes()), default=NORMAL)

    def retry_after_ms(self, plane: str) -> int:
        """The retry hint a shed response should carry for this plane."""
        return RETRY_AFTER_MS[self.level(plane)]

    # ------------------------------------------------------- shedding

    def shed(self, plane: str, n: int = 1) -> None:
        """Count n shed requests/txs on a plane (registry + /metrics)."""
        with self._lock:
            self._sheds[plane] = self._sheds.get(plane, 0) + n
        m = self._metrics()
        if m is not None:
            try:
                m.sheds.labels(plane).inc(n)
            except Exception:  # noqa: BLE001
                pass

    def sheds(self, plane: str) -> int:
        with self._lock:
            return self._sheds.get(plane, 0)

    def total_sheds(self) -> int:
        with self._lock:
            return sum(self._sheds.values())

    # -------------------------------------------------------- metrics

    @staticmethod
    def _metrics():
        try:
            from cometbft_tpu.libs import metrics as m

            return m.overload_metrics()
        except Exception:  # noqa: BLE001 - metrics must never break shedding
            return None

    def _publish_level(self, plane: str, level: int,
                       transition: bool = False) -> None:
        m = self._metrics()
        if m is None:
            return
        try:
            m.level.labels(plane).set(level)
            if transition:
                m.transitions.labels(plane).inc()
        except Exception:  # noqa: BLE001
            pass

    # --------------------------------------------------------- health

    def health(self) -> dict:
        """The `overload` section of the health RPC route and the
        assertion surface for tests/bench."""
        planes = self.planes()
        per_plane = {}
        overall = NORMAL
        now = self._clock()
        for p in planes:
            lvl = self.level(p)  # advances the state machine too
            overall = max(overall, lvl)
            with self._lock:
                per_plane[p] = {
                    "level": LEVEL_NAMES[lvl],
                    "utilization": round(self._last_util.get(p, 0.0), 4),
                    "sheds": self._sheds.get(p, 0),
                    "transitions": self._transitions.get(p, 0),
                    "since_s": round(now - self._since.get(p, now), 3),
                }
        return {
            "level": LEVEL_NAMES[overall],
            "planes": per_plane,
            "watermarks": {
                "elevated": self.elevated,
                "saturated": self.saturated,
                "hysteresis": self.hysteresis,
            },
        }


_default: Optional[OverloadRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> OverloadRegistry:
    """A process-default registry for components created outside a Node
    (tests, benches). Nodes own their own instance — two in-proc nodes
    must not read each other's mempool pressure."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = OverloadRegistry()
    return _default
