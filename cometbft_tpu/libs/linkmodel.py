"""Live link estimation: EWMA bandwidth/RTT models for the wires the node
actually runs on.

Two links dominate this framework's measured ceilings and both were, until
now, hand-measured constants baked into bench notes ("~22 MB/s, ~89 ms
RTT"):

  the device tunnel   every h2d staging transfer and d2h result fetch
                      crosses the host<->accelerator link (a network
                      tunnel on the dev box, PCIe on a co-located host).
                      The kernels report every measured transfer span here
                      (ops/ed25519_kernel.py, ops/sr25519_kernel.py), so
                      `tunnel()` converges on the REAL link within a few
                      windows of traffic — crypto_health exposes it, the
                      scheduler reads it, and the reduced-send work will
                      be graded against it.
  peer links          MConnection ping RTTs and flowrate throughput feed
                      per-peer models (owned by the MConnection) plus the
                      process-wide `p2p()` aggregate that net_telemetry
                      reports.

Estimation model (shared by both): a transfer of n bytes costs
rtt_share + n/bandwidth. Small transfers (below `rtt_bytes`) are
latency-dominated and update the RTT estimate; large ones (above
`bw_bytes`) update bandwidth after subtracting the current RTT estimate
from the measured wall time. Both estimates are exponentially weighted
moving averages, so the model tracks a link whose quality drifts (a
contended tunnel, a healing partition) instead of averaging history
forever. `observe_rtt()` feeds pure round-trip measurements (p2p pings,
header-only fetches) without a byte count.

Everything is thread-safe and allocation-free on the observe path — these
sites sit inside verify batches and send routines.
"""

from __future__ import annotations

import threading


class LinkModel:
    """EWMA bandwidth/RTT estimator for one link."""

    def __init__(self, alpha: float = 0.2, rtt_bytes: int = 4096,
                 bw_bytes: int = 65536):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = alpha
        self.rtt_bytes = rtt_bytes
        self.bw_bytes = bw_bytes
        self._lock = threading.Lock()
        self._bw = 0.0  # bytes/sec EWMA (0 = no estimate yet)
        self._rtt = 0.0  # seconds EWMA (0 = no estimate yet)
        self._bw_samples = 0
        self._rtt_samples = 0
        self._bytes_total = 0
        self._seconds_total = 0.0

    # ---------------------------------------------------------- observing

    def observe_rtt(self, seconds: float) -> None:
        """A pure round-trip measurement (ping/pong, header-only fetch)."""
        if seconds <= 0:
            return
        with self._lock:
            self._rtt_samples += 1
            self._rtt = (seconds if self._rtt == 0.0
                         else self._rtt + self.alpha * (seconds - self._rtt))

    def observe_transfer(self, nbytes: int, seconds: float) -> None:
        """A measured transfer of nbytes taking seconds of wall time.
        Small transfers refine RTT; large ones refine bandwidth (with the
        RTT share subtracted, so a latency-heavy link doesn't read as
        slow bandwidth)."""
        if seconds <= 0 or nbytes < 0:
            return
        with self._lock:
            self._bytes_total += nbytes
            self._seconds_total += seconds
            if nbytes <= self.rtt_bytes:
                self._rtt_samples += 1
                self._rtt = (seconds if self._rtt == 0.0
                             else self._rtt + self.alpha * (seconds - self._rtt))
                return
            if nbytes < self.bw_bytes:
                return  # mid-size: ambiguous between rtt and bandwidth
            wire = seconds - self._rtt
            if wire <= 0:
                # faster than the RTT floor says is possible: the link got
                # quicker — bleed the RTT estimate down and use raw time
                self._rtt *= 1.0 - self.alpha
                wire = seconds
            sample = nbytes / wire
            self._bw_samples += 1
            self._bw = (sample if self._bw == 0.0
                        else self._bw + self.alpha * (sample - self._bw))

    # ------------------------------------------------------------ reading

    def bandwidth_bps(self) -> float:
        """Estimated link bandwidth in bytes/sec (0.0 = no estimate)."""
        with self._lock:
            return self._bw

    def rtt_seconds(self) -> float:
        """Estimated round-trip time in seconds (0.0 = no estimate)."""
        with self._lock:
            return self._rtt

    def transfer_seconds(self, nbytes: int) -> float | None:
        """Predicted wall time for an nbytes transfer (None until both
        estimates exist) — the scheduler/reduced-send planning primitive."""
        with self._lock:
            if self._bw == 0.0:
                return None
            return self._rtt + nbytes / self._bw

    def converged(self, min_samples: int = 3) -> bool:
        with self._lock:
            return self._bw_samples >= min_samples and self._rtt_samples >= 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bandwidth_bytes_per_s": round(self._bw, 1),
                "bandwidth_mb_per_s": round(self._bw / 1e6, 3),
                "rtt_ms": round(self._rtt * 1e3, 3),
                "bandwidth_samples": self._bw_samples,
                "rtt_samples": self._rtt_samples,
                "bytes_observed": self._bytes_total,
                "seconds_observed": round(self._seconds_total, 3),
                "converged": (self._bw_samples >= 3
                              and self._rtt_samples >= 1),
            }

    def reset(self) -> None:
        with self._lock:
            self._bw = self._rtt = 0.0
            self._bw_samples = self._rtt_samples = 0
            self._bytes_total = 0
            self._seconds_total = 0.0


class SkewEstimator:
    """Per-peer wall-clock offset model (peer_clock - local_clock, ms).

    Two sample sources, kept as separate EWMAs so they cross-check each
    other:

      ping      the pong packet carries the responder's wall clock
                (p2p/conn/connection.py); with the send stamped at wall
                t0 and a measured RTT, ``offset = remote_wall -
                (t0 + rtt/2)``. Exact up to path asymmetry, so the
                per-sample error is bounded by rtt/2 plus jitter.
      vote      a received vote's signing timestamp against the local
                arrival clock, credited rtt/2 of flight time. Network
                delay is at least rtt/2, so vote samples are a LOWER
                bound on the true offset — they serve as the
                cross-check, not the estimate.

    ``offset_ms()`` prefers the ping EWMA and falls back to votes.  The
    documented error bound (asserted by tests/test_skew.py) is::

        |estimate - true| <= max(2 ms, rtt/2 * 1e3 + 3 * dev_ms)

    after ~50 samples, where dev_ms is the EWMA of absolute residuals —
    i.e. the estimator converges to within half the round trip plus
    three deviations of the observed jitter.  Thread-safe: samples
    arrive from per-connection recv tasks, reads from the RPC thread.
    """

    def __init__(self, alpha: float = 0.1):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = alpha
        self._lock = threading.Lock()
        self._peers: dict[str, dict] = {}

    def _peer(self, peer: str) -> dict:
        p = self._peers.get(peer)
        if p is None:
            p = {"ping_off": None, "ping_dev": 0.0, "ping_n": 0,
                 "vote_off": None, "vote_n": 0, "rtt_s": 0.0}
            self._peers[peer] = p
        return p

    def observe_ping(self, peer: str, remote_wall_ns: int,
                     midpoint_wall_ns: int, rtt_s: float) -> None:
        """A pong that carried the responder's wall clock; midpoint is
        the sender's wall clock at t0 + rtt/2."""
        sample = (remote_wall_ns - midpoint_wall_ns) / 1e6
        with self._lock:
            p = self._peer(peer)
            p["ping_n"] += 1
            if rtt_s > 0:
                p["rtt_s"] = (rtt_s if p["rtt_s"] == 0.0
                              else p["rtt_s"] + self.alpha * (rtt_s - p["rtt_s"]))
            if p["ping_off"] is None:
                p["ping_off"] = sample
                return
            resid = abs(sample - p["ping_off"])
            p["ping_dev"] += self.alpha * (resid - p["ping_dev"])
            p["ping_off"] += self.alpha * (sample - p["ping_off"])

    def observe_vote(self, peer: str, vote_wall_ns: int,
                     arrival_wall_ns: int, rtt_s: float = 0.0) -> None:
        """Vote-timestamp delta cross-check (lower bound on the offset:
        gossip delay exceeds rtt/2, pulling the sample down)."""
        sample = (vote_wall_ns - arrival_wall_ns) / 1e6 + rtt_s * 500.0
        with self._lock:
            p = self._peer(peer)
            p["vote_n"] += 1
            if p["vote_off"] is None:
                p["vote_off"] = sample
            else:
                p["vote_off"] += self.alpha * (sample - p["vote_off"])

    def offset_ms(self, peer: str) -> float | None:
        """Best offset estimate for peer (peer clock minus local), ms."""
        with self._lock:
            p = self._peers.get(peer)
            if p is None:
                return None
            if p["ping_off"] is not None:
                return p["ping_off"]
            return p["vote_off"]

    def error_bound_ms(self, peer: str) -> float | None:
        with self._lock:
            p = self._peers.get(peer)
            if p is None or p["ping_off"] is None:
                return None
            return max(2.0, p["rtt_s"] * 500.0 + 3.0 * p["ping_dev"])

    def snapshot(self) -> dict:
        """Per-peer skew table for consensus_timeline / net_telemetry."""
        out = {}
        with self._lock:
            for peer, p in self._peers.items():
                off = p["ping_off"] if p["ping_off"] is not None else p["vote_off"]
                ent = {
                    "offset_ms": None if off is None else round(off, 3),
                    "source": ("ping" if p["ping_off"] is not None
                               else "vote" if p["vote_off"] is not None
                               else "none"),
                    "ping_samples": p["ping_n"],
                    "vote_samples": p["vote_n"],
                    "rtt_ms": round(p["rtt_s"] * 1e3, 3),
                }
                if p["ping_off"] is not None:
                    ent["error_bound_ms"] = round(
                        max(2.0, p["rtt_s"] * 500.0 + 3.0 * p["ping_dev"]), 3)
                    ent["dev_ms"] = round(p["ping_dev"], 3)
                if p["ping_off"] is not None and p["vote_off"] is not None:
                    # votes lower-bound the offset; a vote EWMA far ABOVE
                    # the ping estimate means one of the clocks lies
                    ent["cross_check_ms"] = round(
                        p["vote_off"] - p["ping_off"], 3)
                out[peer] = ent
        return out

    def reset(self) -> None:
        with self._lock:
            self._peers.clear()


# ---------------------------------------------------------------------------
# process-global links. The device tunnel is a process-global resource
# (like the device supervisors); the p2p aggregate pools every peer's ping
# RTTs and flow rates into one "how is my network" view for net_telemetry.
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_tunnel: LinkModel | None = None
_p2p: LinkModel | None = None
_skew: SkewEstimator | None = None


def tunnel() -> LinkModel:
    """The host<->device link (fed by the kernels' measured h2d/d2h
    transfers — ops/ed25519_kernel.py, ops/sr25519_kernel.py)."""
    global _tunnel
    if _tunnel is None:
        with _lock:
            if _tunnel is None:
                # thresholds sized to the kernels' real transfer mix: the
                # 4 B/lane index uploads (<=2 KB at small buckets) probe
                # RTT; staged-word uploads start at 24 KB for a 256-lane
                # flush, so 16 KB+ counts toward bandwidth
                _tunnel = LinkModel(alpha=0.2, rtt_bytes=2048,
                                    bw_bytes=16384)
    return _tunnel


def p2p() -> LinkModel:
    """The aggregate peer-link view (fed by MConnection ping RTTs)."""
    global _p2p
    if _p2p is None:
        with _lock:
            if _p2p is None:
                _p2p = LinkModel(alpha=0.1, rtt_bytes=4096, bw_bytes=16384)
    return _p2p


def skew() -> SkewEstimator:
    """The per-peer clock-skew table (fed by MConnection pong wall stamps
    and the consensus reactor's vote-timestamp deltas; read by the
    heightline aggregator to project node clocks onto one fleet axis)."""
    global _skew
    if _skew is None:
        with _lock:
            if _skew is None:
                _skew = SkewEstimator(alpha=0.1)
    return _skew


def reset() -> None:
    """Forget the process links and the skew table (tests)."""
    global _tunnel, _p2p, _skew
    with _lock:
        _tunnel = None
        _p2p = None
        _skew = None
