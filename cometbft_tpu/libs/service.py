"""Service lifecycle (reference: libs/service/service.go:24-239).

Every long-lived object in the framework embeds BaseService: idempotent
start/stop, no restart after stop (reset() to allow), a quit event to wait
on. The reference uses atomics + a quit channel; here starts/stops happen on
the event loop so plain flags suffice, while `stopped_event` lets any task
await termination.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from cometbft_tpu.libs import log as cmtlog


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class BaseService:
    """Subclasses override on_start / on_stop."""

    def __init__(self, name: str, logger: Optional[cmtlog.Logger] = None):
        self.name = name
        self.logger = logger or cmtlog.nop()
        self._started = False
        self._stopped = False
        self._stopped_event: Optional[asyncio.Event] = None

    # -- lifecycle --

    async def start(self) -> None:
        if self._stopped:
            raise AlreadyStoppedError(self.name)
        if self._started:
            raise AlreadyStartedError(self.name)
        self._started = True
        self._stopped_event = asyncio.Event()
        self.logger.info("service start", service=self.name)
        try:
            await self.on_start()
        except BaseException:
            # failed start leaves the service startable again (reference
            # resets started on OnStart error, service.go:171-178)
            self._started = False
            self._stopped_event = None
            raise

    async def stop(self) -> None:
        if self._stopped:
            return
        if not self._started:
            raise ServiceError(f"{self.name}: stop before start")
        self._stopped = True
        self.logger.info("service stop", service=self.name)
        await self.on_stop()
        if self._stopped_event is not None:
            self._stopped_event.set()

    def reset(self) -> None:
        """Allow a stopped service to start again (reference Reset)."""
        self._started = False
        self._stopped = False
        self._stopped_event = None

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopped

    async def wait(self) -> None:
        """Block until the service stops."""
        if self._stopped_event is None:
            if self._stopped:
                return
            raise ServiceError(f"{self.name}: wait before start")
        await self._stopped_event.wait()

    # -- overridables --

    async def on_start(self) -> None:  # pragma: no cover - trivial
        pass

    async def on_stop(self) -> None:  # pragma: no cover - trivial
        pass

    def set_logger(self, logger: cmtlog.Logger) -> None:
        self.logger = logger


class TaskRunner:
    """Helper owning a set of background asyncio tasks tied to a service:
    spawn() tracks them, cancel_all() tears them down on stop. Replaces the
    reference's ad-hoc goroutine-per-routine pattern with structured
    cancellation."""

    def __init__(self, name: str = "tasks"):
        self.name = name
        self._tasks: set[asyncio.Task] = set()

    def spawn(self, coro, name: str | None = None) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro, name=name or self.name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def cancel_all(self) -> None:
        """Cancel and reap every task. Cancels REPEATEDLY: on Python < 3.11
        asyncio.wait_for can swallow a pending cancellation when its inner
        future completes in the same scheduling window (bpo-42130), leaving
        a task alive after one cancel — a single `await t` would then hang
        the whole service stop. Re-cancelling until the task actually dies
        makes teardown immune to that lost-wakeup race; tasks that survive
        every attempt (a tight loop swallowing CancelledError) are abandoned
        with a warning rather than wedging shutdown."""
        current = asyncio.current_task()
        # a service routine may stop its own service (a peer's recv loop
        # tearing the peer down): never cancel-and-await the calling task —
        # it ends naturally after teardown, and cancelling it here would
        # abort the teardown itself mid-flight
        tasks = [t for t in self._tasks if t is not current]
        for t in tasks:
            t.cancel()
        pending = set(tasks)
        for _attempt in range(10):
            if not pending:
                break
            done, pending = await asyncio.wait(pending, timeout=1.0)
            for t in done:
                if not t.cancelled() and t.exception() is not None:
                    pass  # swallowed: stop paths must not re-raise task errors
            for t in pending:
                t.cancel()
        if pending:
            import logging

            logging.getLogger("cometbft").warning(
                "%s.cancel_all: %d task(s) survived repeated cancellation: %s",
                self.name, len(pending),
                [t.get_name() for t in pending])
        self._tasks.clear()

    def __len__(self) -> int:
        return len(self._tasks)
