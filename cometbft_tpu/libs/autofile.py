"""Rotating file groups (reference: libs/autofile/group.go).

A Group is a head file plus numbered chunks (`path`, `path.000`,
`path.001`, ...): writers append to the head; when the head passes
chunk_size (checked at record boundaries so records never split), it
rotates to the next numbered chunk and a fresh head opens. Total size is
bounded by pruning the oldest chunks (group.go:36 headSizeLimit /
totalSizeLimit). Readers see one logical stream across chunks in order.
"""

from __future__ import annotations

import os
import re
from typing import Iterator

DEFAULT_CHUNK_SIZE = 10 * 1024 * 1024   # group.go:41 defaultHeadSizeLimit
DEFAULT_TOTAL_SIZE = 1024 * 1024 * 1024  # group.go:42 defaultTotalSizeLimit


class Group:
    def __init__(self, head_path: str,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 total_size: int = DEFAULT_TOTAL_SIZE):
        self.head_path = head_path
        self.chunk_size = chunk_size
        self.total_size = total_size
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._head = open(head_path, "ab")

    # ------------------------------------------------------------- write

    def write(self, data: bytes) -> None:
        self._head.write(data)

    def flush(self) -> None:
        self._head.flush()

    def fsync(self) -> None:
        self._head.flush()
        os.fsync(self._head.fileno())

    def maybe_rotate(self) -> bool:
        """Call at a record boundary; rotates the head into a numbered
        chunk when it exceeds chunk_size (group.go:190 checkHeadSizeLimit).
        Returns True if a rotation happened."""
        if self._head.tell() < self.chunk_size:
            return False
        self.fsync()
        self._head.close()
        idx = self._chunk_indexes()
        nxt = (idx[-1] + 1) if idx else 0
        os.replace(self.head_path, f"{self.head_path}.{nxt:03d}")
        self._head = open(self.head_path, "ab")
        self._prune()
        return True

    def _prune(self) -> None:
        """Drop oldest chunks while total size exceeds the limit
        (group.go:216 checkTotalSizeLimit)."""
        while True:
            paths = self.chunk_paths()
            total = sum(os.path.getsize(p) for p in paths if os.path.exists(p))
            idx = self._chunk_indexes()
            if total <= self.total_size or not idx:
                return
            os.remove(f"{self.head_path}.{idx[0]:03d}")

    def close(self) -> None:
        try:
            self.fsync()
        except (OSError, ValueError):
            pass
        self._head.close()

    # -------------------------------------------------------------- read

    def _chunk_indexes(self) -> list[int]:
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
        out = []
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def chunk_paths(self) -> list[str]:
        """Oldest chunk first, the head last — the logical stream order."""
        paths = [f"{self.head_path}.{i:03d}" for i in self._chunk_indexes()]
        paths.append(self.head_path)
        return paths

    def iter_bytes(self) -> Iterator[tuple[str, bytes]]:
        for p in self.chunk_paths():
            if os.path.exists(p):
                with open(p, "rb") as f:
                    yield p, f.read()
