"""Rotating file groups (reference: libs/autofile/group.go).

A Group is a head file plus numbered chunks (`path`, `path.000`,
`path.001`, ...): writers append to the head; when the head passes
chunk_size (checked at record boundaries so records never split), it
rotates to the next numbered chunk and a fresh head opens. Total size is
bounded by pruning the oldest chunks (group.go:36 headSizeLimit /
totalSizeLimit). Readers see one logical stream across chunks in order.

Storage-fault plane: every append rides the `wal.write` disk-chaos seam,
every fsync the `wal.fsync` seam, and the rotation rename is a
durable_replace through `wal.rotate` — the head->chunk rename is only
durable after the directory fsync, and a crash between the rename and
the next write must leave a replayable group (tests: autofile
rotation-crash cases in test_storage_crash_matrix.py).
"""

from __future__ import annotations

import os
import re
from typing import Iterator

from cometbft_tpu.libs import diskchaos, diskio

DEFAULT_CHUNK_SIZE = 10 * 1024 * 1024   # group.go:41 defaultHeadSizeLimit
DEFAULT_TOTAL_SIZE = 1024 * 1024 * 1024  # group.go:42 defaultTotalSizeLimit


class Group:
    def __init__(self, head_path: str,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 total_size: int = DEFAULT_TOTAL_SIZE):
        self.head_path = head_path
        self.chunk_size = chunk_size
        self.total_size = total_size
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        # UNBUFFERED on purpose: a user-space Python buffer made every
        # append's durability a lie — a kill -9 dropped records that
        # write() had "accepted" but never handed to the OS. Unbuffered,
        # a process kill loses nothing (the page cache survives); only
        # power loss can, and that is exactly what the fsync discipline
        # (and the fsync_lie chaos model) governs.
        self._head = open(head_path, "ab", buffering=0)
        # fsync-lie rewind anchor: bytes on disk at open are durable
        diskchaos.track_open(head_path)

    # ------------------------------------------------------------- write

    def write(self, data: bytes) -> None:
        diskchaos.fault_write("wal.write", self._head, data)

    def flush(self) -> None:
        self._head.flush()

    def fsync(self) -> None:
        self._head.flush()
        diskchaos.fault_fsync("wal.fsync", self._head.fileno(), self.head_path)

    def maybe_rotate(self) -> bool:
        """Call at a record boundary; rotates the head into a numbered
        chunk when it exceeds chunk_size (group.go:190 checkHeadSizeLimit).
        Returns True if a rotation happened. The rename is durable (dir
        fsync) before the fresh head opens — a crash anywhere in between
        leaves either the old head or the completed chunk, never a
        half-renamed group."""
        if self._head.tell() < self.chunk_size:
            return False
        self.fsync()
        self._head.close()
        idx = self._chunk_indexes()
        nxt = (idx[-1] + 1) if idx else 0
        diskio.durable_replace(
            self.head_path, f"{self.head_path}.{nxt:03d}", site="wal.rotate")
        self._head = open(self.head_path, "ab", buffering=0)
        # fresh=True: the head path is a NEW empty file now — the renamed
        # chunk's durable anchor must not ride along
        diskchaos.track_open(self.head_path, fresh=True)
        self._prune()
        return True

    def _prune(self) -> None:
        """Drop oldest chunks while total size exceeds the limit
        (group.go:216 checkTotalSizeLimit)."""
        while True:
            paths = self.chunk_paths()
            total = sum(os.path.getsize(p) for p in paths if os.path.exists(p))
            idx = self._chunk_indexes()
            if total <= self.total_size or not idx:
                return
            os.remove(f"{self.head_path}.{idx[0]:03d}")

    def close(self) -> None:
        try:
            self.fsync()
        except (OSError, ValueError):
            pass
        self._head.close()

    def abandon(self) -> None:
        """Crash-simulation teardown: close the raw handle WITHOUT the
        close() fsync — the disk keeps exactly what the process had
        handed the OS at 'death', so the crash-matrix harness examines
        the same bytes a kill -9 would leave behind."""
        try:
            self._head.close()  # raw unbuffered: close never fsyncs
        except (OSError, ValueError):
            pass

    # -------------------------------------------------------------- read

    def _chunk_indexes(self) -> list[int]:
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
        out = []
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def chunk_paths(self) -> list[str]:
        """Oldest chunk first, the head last — the logical stream order."""
        paths = [f"{self.head_path}.{i:03d}" for i in self._chunk_indexes()]
        paths.append(self.head_path)
        return paths

    def iter_bytes(self) -> Iterator[tuple[str, bytes]]:
        for p in self.chunk_paths():
            if os.path.exists(p):
                with open(p, "rb") as f:
                    yield p, f.read()
