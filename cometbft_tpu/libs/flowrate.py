"""Flow-rate measurement and limiting.

Reference: libs/flowrate (token-bucket rate monitor used by MConnection to
throttle per-peer send/recv to config.SendRate/RecvRate,
p2p/conn/connection.go:44-45). asyncio-native: `limit()` returns the delay
to sleep before transferring n more bytes.
"""

from __future__ import annotations

import time


class Monitor:
    """Sliding-average rate monitor with an optional hard limit."""

    def __init__(self, rate_limit: int = 0, window: float = 1.0):
        self.rate_limit = rate_limit  # bytes/sec; 0 = unlimited
        self.window = window
        self.bytes_total = 0
        self.updates_total = 0
        self.peak_rate = 0.0  # highest completed-window average seen
        self._t0 = time.monotonic()
        self._window_start = self._t0
        self._window_bytes = 0
        self._avg_rate = 0.0

    def update(self, n: int) -> float:
        """Record n transferred bytes; return seconds the caller should
        sleep to stay under rate_limit (0.0 when unlimited/under budget).
        Accounting (bytes_total / rate / peak_rate) is recorded whether or
        not a limit is set — rate_limit=0 means non-throttling, never
        non-measuring."""
        now = time.monotonic()
        self.bytes_total += n
        self.updates_total += 1
        self._window_bytes += n
        elapsed = now - self._window_start
        if elapsed >= self.window:
            self._avg_rate = self._window_bytes / elapsed
            if self._avg_rate > self.peak_rate:
                self.peak_rate = self._avg_rate
            self._window_start = now
            self._window_bytes = 0
        if self.rate_limit <= 0:
            return 0.0
        # delay so that window_bytes/elapsed <= rate_limit
        min_elapsed = self._window_bytes / self.rate_limit
        return max(0.0, min_elapsed - elapsed)

    def rate(self) -> float:
        """Most recent windowed average rate (bytes/sec)."""
        elapsed = time.monotonic() - self._window_start
        if elapsed > 0.1:
            return self._window_bytes / elapsed
        return self._avg_rate

    def lifetime_rate(self) -> float:
        """bytes_total over the monitor's whole lifetime (bytes/sec)."""
        elapsed = time.monotonic() - self._t0
        return self.bytes_total / elapsed if elapsed > 0 else 0.0

    def stats(self) -> dict:
        """Snapshot for status()/telemetry consumers."""
        return {
            "bytes_total": self.bytes_total,
            "updates_total": self.updates_total,
            "rate_bytes_per_s": round(self.rate(), 1),
            "lifetime_rate_bytes_per_s": round(self.lifetime_rate(), 1),
            "peak_rate_bytes_per_s": round(self.peak_rate, 1),
            "rate_limit": self.rate_limit,
        }
