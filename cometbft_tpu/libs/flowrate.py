"""Flow-rate measurement and limiting.

Reference: libs/flowrate (token-bucket rate monitor used by MConnection to
throttle per-peer send/recv to config.SendRate/RecvRate,
p2p/conn/connection.go:44-45). asyncio-native: `limit()` returns the delay
to sleep before transferring n more bytes.
"""

from __future__ import annotations

import time


class Monitor:
    """Sliding-average rate monitor with an optional hard limit."""

    def __init__(self, rate_limit: int = 0, window: float = 1.0):
        self.rate_limit = rate_limit  # bytes/sec; 0 = unlimited
        self.window = window
        self.bytes_total = 0
        self._window_start = time.monotonic()
        self._window_bytes = 0
        self._avg_rate = 0.0

    def update(self, n: int) -> float:
        """Record n transferred bytes; return seconds the caller should
        sleep to stay under rate_limit (0.0 when unlimited/under budget)."""
        now = time.monotonic()
        self.bytes_total += n
        self._window_bytes += n
        elapsed = now - self._window_start
        if elapsed >= self.window:
            self._avg_rate = self._window_bytes / elapsed
            self._window_start = now
            self._window_bytes = 0
        if self.rate_limit <= 0:
            return 0.0
        # delay so that window_bytes/elapsed <= rate_limit
        min_elapsed = self._window_bytes / self.rate_limit
        return max(0.0, min_elapsed - elapsed)

    def rate(self) -> float:
        """Most recent windowed average rate (bytes/sec)."""
        elapsed = time.monotonic() - self._window_start
        if elapsed > 0.1:
            return self._window_bytes / elapsed
        return self._avg_rate
