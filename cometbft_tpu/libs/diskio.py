"""Durable file primitives shared by every on-disk seam.

The one lesson of the storage-fault plane: `os.replace` alone is NOT
durable. POSIX only promises the rename is on disk after the containing
DIRECTORY is fsynced — until then a power cut can resurrect the old file
(or leave neither). Every rename that guards consensus safety (privval
sign-state, WAL chunk rotation, config writes) must go through
`durable_replace`, which is also the `privval.save`/`wal.rotate`
disk-chaos seam.
"""

from __future__ import annotations

import os
import tempfile


def fsync_dir(path: str) -> None:
    """fsync the directory containing `path` (or `path` itself when it is
    a directory) so a rename inside it survives power loss."""
    d = path if os.path.isdir(path) else os.path.dirname(os.path.abspath(path))
    fd = os.open(d or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def durable_replace(src: str, dst: str, site: str | None = None) -> None:
    """os.replace(src, dst) followed by an fsync of dst's directory. With
    `site` set, the whole operation runs through the disk-chaos seam
    (libs/diskchaos.fault_replace) so fault schedules can tear, lie
    about, or fail the rename deterministically."""
    if site is not None:
        from cometbft_tpu.libs import diskchaos

        diskchaos.fault_replace(site, src, dst)
        return
    os.replace(src, dst)
    fsync_dir(dst)


def atomic_write_durable(path: str, data: bytes, site: str | None = None) -> None:
    """Write `data` to a same-directory temp file, fsync it, and
    durable_replace it over `path`: after this returns, either the old
    or the complete new content survives any crash — never a torn mix,
    and (unlike a bare os.replace) never neither."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        durable_replace(tmp, path, site=site)
    except Exception:
        # error paths clean the temp up; a SimulatedCrash (BaseException)
        # leaves it behind on purpose — a real power cut would too, and
        # loaders never read temp names
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise
