"""Device-fault injection registry (the accelerator analog of libs/fail.py).

fail.py kills the PROCESS at indexed call sites to test WAL recovery;
chaos.py breaks the DEVICE at named call sites to test the verify ladder's
degradation paths (ops/dispatch.py supervisor: retry -> breaker -> CPU
fallback -> re-probe). Sites live on the device-dispatch seams:

  ed25519.dispatch   the ed25519 transfer+kernel dispatch worker
  ed25519.fetch      the ed25519 device->host payload fetch
  ed25519.challenge  the on-device challenge derivation (ops/challenge.py
                     derive program): a fault degrades the batch to
                     host-computed k, `corrupt` perturbs one device-derived
                     k word so the recheck plane must flip the lane back —
                     counted, never a verdict change
  dispatch.doublebuf the two-slot in-flight gate (ops/dispatch.DoubleBuffer)
                     acquired before each batch's h2d: a fault degrades the
                     fault domain to serialized single-buffer dispatch until
                     its breaker re-closes — overlap lost, verdicts untouched
  sr25519.dispatch   the sr25519 transfer+kernel dispatch worker
  sr25519.fetch      the sr25519 device->host payload fetch
  pallas.trace       inside the Pallas gate, before the fused-kernel call
  mixed.resolve      the coalesced multi-batch fetch (resolve_batches)
  sched.flush        the verify scheduler's batch-formation seam
                     (sched/scheduler.py _dispatch): an injected fault
                     degrades to per-group fragmented dispatch, never
                     failed verification

plus the per-chip mesh shard sites (parallel/mesh.py — one fault domain
per device, indices 0..MESH_CHAOS_DEVICES-1):

  ed25519.dispatch.devN / sr25519.dispatch.devN
                     one chip's shard dispatch inside the multi-chip
                     verify mesh; killing dev3 evicts exactly that fault
                     domain while the mesh re-shards over the survivors

plus the transport seams (the network plane's deterministic faults; the
probabilistic link faults — latency/drop/dup/reorder/partitions — live in
p2p/netchaos.py):

  net.dial           p2p outbound TCP dial (transport.dial)
  net.accept         p2p inbound connection intake (before upgrade)
  net.handshake      the secret-connection + node-info upgrade

plus the light-client provider seam (light/rpc_provider.py):

  light.fetch        one light_block RPC attempt against a provider; a
                     transient/timeout fault here exercises the capped
                     backoff+jitter retry instead of failing the whole
                     bisection on one flaky witness hop

Arming, via env (`CBFT_CHAOS`) or `arm()`/`arm_spec()`:

  CBFT_CHAOS="ed25519.dispatch=transient:3,pallas.trace=permanent"

`kind[:count]` per site — `count` firings (default: unlimited), then the
site heals. Kinds:

  timeout     raise ChaosTimeout (a hung fetch; the watchdog's TimeoutError)
  transient   raise ChaosTransientError (XlaRuntimeError-style, retryable)
  permanent   raise ChaosPermanentError (Mosaic compile death, not retryable)
  corrupt     leave the call alive but flip lane 0 of the fetched mask
              (exercises the transfer-integrity echo plane)

Every fault is deterministic: no randomness, a plain per-site counter, so a
chaos schedule is a reproducible test fixture. Thread-safe: sites fire from
the kernel transfer pool as well as the event loop.
"""

from __future__ import annotations

import os
import threading

# per-device mesh shard sites ("ed25519.dispatch.dev3"): the multi-chip
# verify mesh (parallel/mesh.py) fires BOTH the plain scheme site and the
# chip-indexed site inside every shard dispatch, so a schedule can kill or
# flap exactly one mesh fault domain while the other chips keep serving —
# the deterministic fixture behind the shrink/grow test matrix
MESH_CHAOS_DEVICES = 8

_MESH_SITES = tuple(
    f"{scheme}.dispatch.dev{i}"
    for scheme in ("ed25519", "sr25519")
    for i in range(MESH_CHAOS_DEVICES)
)

SITES = (
    "ed25519.dispatch",
    "ed25519.fetch",
    "ed25519.challenge",
    "dispatch.doublebuf",
    "sr25519.dispatch",
    "sr25519.fetch",
    "pallas.trace",
    "mixed.resolve",
    "sched.flush",
    "net.dial",
    "net.accept",
    "net.handshake",
    "light.fetch",
) + _MESH_SITES

KINDS = ("timeout", "transient", "permanent", "corrupt")

_ENV = "CBFT_CHAOS"


class ChaosTimeout(Exception):
    """Injected hung-device timeout."""


class ChaosTransientError(Exception):
    """Injected retryable device failure (XlaRuntimeError-style)."""


class ChaosPermanentError(Exception):
    """Injected permanent device failure (Mosaic compile death)."""


class _Site:
    __slots__ = ("kind", "remaining", "fired")

    def __init__(self, kind: str, remaining: int | None):
        self.kind = kind
        self.remaining = remaining  # None = unlimited
        self.fired = 0


_lock = threading.Lock()
_sites: dict[str, _Site] = {}
_env_loaded = False


def parse_spec(spec: str) -> list[tuple[str, str, int | None]]:
    """Parse a schedule string into (site, kind, count) triples, raising
    ValueError on any malformed part — config validation uses this so a
    typo'd schedule fails at boot, not inside a device dispatch."""
    out: list[tuple[str, str, int | None]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, fault = part.partition("=")
        kind, _, count = fault.partition(":")
        site, kind = site.strip(), kind.strip()
        if site not in SITES:
            raise ValueError(f"unknown chaos site {site!r} (sites: {SITES})")
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r} (kinds: {KINDS})")
        if count:
            try:
                n = int(count)
            except ValueError:
                raise ValueError(
                    f"bad chaos count {count!r} in {part!r}") from None
            if n < 0:
                raise ValueError(f"negative chaos count in {part!r}")
        else:
            n = None
        out.append((site, kind, n))
    return out


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(_ENV, "")
    if not spec:
        return
    try:
        _arm_spec_locked(spec)
    except ValueError as e:
        # a malformed env schedule must fail LOUDLY, not surface later as
        # a phantom "device failure" inside a dispatch worker — but this
        # loads lazily at the first fire(), where raising would be
        # classified as a device fault; log-and-ignore is the safe floor
        from cometbft_tpu.libs import log as _log

        _log.default().error(
            "ignoring malformed CBFT_CHAOS schedule", spec=spec, err=str(e))


def _arm_spec_locked(spec: str) -> None:
    for site, kind, count in parse_spec(spec):
        _arm_locked(site, kind, count)


def _arm_locked(site: str, kind: str, count: int | None) -> None:
    if site not in SITES:
        raise ValueError(f"unknown chaos site {site!r} (sites: {SITES})")
    if kind not in KINDS:
        raise ValueError(f"unknown chaos kind {kind!r} (kinds: {KINDS})")
    _sites[site] = _Site(kind, count)


def arm(site: str, kind: str, count: int | None = None) -> None:
    """Arm `site` to fail `count` times (None = until disarmed)."""
    with _lock:
        _load_env_locked()
        _arm_locked(site, kind, count)


def arm_spec(spec: str) -> None:
    """Arm from a CBFT_CHAOS-syntax schedule string."""
    with _lock:
        _load_env_locked()
        _arm_spec_locked(spec)


def disarm(site: str) -> None:
    with _lock:
        _sites.pop(site, None)


def reset() -> None:
    """Disarm everything and forget the env (tests re-arm per case)."""
    global _env_loaded
    with _lock:
        _sites.clear()
        _env_loaded = True  # a reset() overrides the process env schedule


def armed(site: str) -> str | None:
    """The site's live fault kind, or None."""
    with _lock:
        _load_env_locked()
        s = _sites.get(site)
        return s.kind if s is not None and s.remaining != 0 else None


def fired(site: str) -> int:
    """How many times the site has fired (armed or not: 0)."""
    with _lock:
        s = _sites.get(site)
        return s.fired if s is not None else 0


def _take(site: str, want_corrupt: bool) -> str | None:
    """Consume one firing if armed; returns the kind or None."""
    with _lock:
        _load_env_locked()
        s = _sites.get(site)
        if s is None or s.remaining == 0:
            return None
        if (s.kind == "corrupt") != want_corrupt:
            return None
        if s.remaining is not None:
            s.remaining -= 1
        s.fired += 1
        return s.kind


def fire(site: str) -> None:
    """Call at a dispatch/fetch site: raises the armed fault, if any.
    `corrupt` never raises here — it applies at corrupt_mask()."""
    kind = _take(site, want_corrupt=False)
    if kind is None:
        return
    if kind == "timeout":
        raise ChaosTimeout(f"chaos: injected device hang at {site}")
    if kind == "transient":
        raise ChaosTransientError(
            f"chaos: injected transient device failure at {site} "
            "(RESOURCE_EXHAUSTED)")
    raise ChaosPermanentError(
        f"chaos: injected permanent Mosaic failure at {site}")


def should_corrupt(site: str) -> bool:
    """Consume one `corrupt` firing at a value-perturbation site (e.g. the
    device-derived challenge words at ed25519.challenge, where there is no
    fetched mask to flip — the caller perturbs its own payload). True when
    the site was armed with `corrupt` and a firing was consumed."""
    return _take(site, want_corrupt=True) is not None


def corrupt_mask(site: str, payload):
    """Flip lane 0 of a fetched integrity payload when the site is armed
    with `corrupt` — simulates single-lane tunnel corruption, which the
    mask-echo check must detect (the echo half is left intact)."""
    if _take(site, want_corrupt=True) is None:
        return payload
    out = payload.copy()
    out[0] = ~out[0] if out.dtype != bool else not out[0]
    return out


def snapshot() -> dict:
    """Armed sites + fire counts (surfaced in the crypto-health RPC)."""
    with _lock:
        _load_env_locked()
        return {
            site: {"kind": s.kind, "remaining": s.remaining, "fired": s.fired}
            for site, s in _sites.items()
        }
