"""BitArray (reference: libs/bits/bit_array.go).

Vote-presence maps and block-part masks. The reference guards with a mutex;
here all mutation happens on the event loop, so no lock — but the API mirrors
the reference (Sub, Or, Not, PickRandom, GetTrueIndices) including its
proto form (Bits size + little-endian uint64 elems → we use bytes).
"""

from __future__ import annotations

import random
from typing import Iterator, Optional


class BitArray:
    __slots__ = ("bits", "_elems")

    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bit count")
        self.bits = bits
        self._elems = bytearray((bits + 7) // 8)

    @classmethod
    def from_bools(cls, bools: list[bool]) -> "BitArray":
        ba = cls(len(bools))
        for i, b in enumerate(bools):
            if b:
                ba.set_index(i, True)
        return ba

    @classmethod
    def from_bytes(cls, bits: int, data: bytes) -> "BitArray":
        ba = cls(bits)
        n = len(ba._elems)
        ba._elems[: min(n, len(data))] = data[:n]
        ba._mask_tail()
        return ba

    def _mask_tail(self) -> None:
        if self.bits % 8 and self._elems:
            self._elems[-1] &= (1 << (self.bits % 8)) - 1

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        return bool(self._elems[i // 8] >> (i % 8) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        if v:
            self._elems[i // 8] |= 1 << (i % 8)
        else:
            self._elems[i // 8] &= ~(1 << (i % 8)) & 0xFF
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = bytearray(self._elems)
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        """Union; result size = max (reference bit_array.go Or)."""
        big, small = (self, other) if self.bits >= other.bits else (other, self)
        out = big.copy()
        for i, b in enumerate(small._elems):
            out._elems[i] |= b
        out._mask_tail()
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        bits = min(self.bits, other.bits)
        out = BitArray(bits)
        for i in range(len(out._elems)):
            out._elems[i] = self._elems[i] & other._elems[i]
        out._mask_tail()
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.bits)
        for i, b in enumerate(self._elems):
            out._elems[i] = ~b & 0xFF
        out._mask_tail()
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference Sub semantics)."""
        out = self.copy()
        n = min(len(self._elems), len(other._elems))
        for i in range(n):
            out._elems[i] &= ~other._elems[i] & 0xFF
        out._mask_tail()
        return out

    def is_empty(self) -> bool:
        return not any(self._elems)

    def is_full(self) -> bool:
        if self.bits == 0:
            return True
        full, rem = divmod(self.bits, 8)
        if any(b != 0xFF for b in self._elems[:full]):
            return False
        if rem:
            return self._elems[full] == (1 << rem) - 1
        return True

    def pick_random(self, rng: Optional[random.Random] = None) -> tuple[int, bool]:
        """Random true index (reference PickRandom)."""
        trues = self.get_true_indices()
        if not trues:
            return 0, False
        return (rng or random).choice(trues), True

    def get_true_indices(self) -> list[int]:
        return [i for i in range(self.bits) if self.get_index(i)]

    def num_true(self) -> int:
        return sum(bin(b).count("1") for b in self._elems)

    def to_bytes(self) -> bytes:
        return bytes(self._elems)

    def or_update(self, other: "BitArray") -> None:
        """In-place union restricted to self's size. Used by vote-summary
        reconciliation: has-vote knowledge is monotonic, and mutating in
        place keeps any aliases (catchup_commit may BE precommits) in
        agreement where a rebinding union would silently fork them."""
        n = min(len(self._elems), len(other._elems))
        for i in range(n):
            self._elems[i] |= other._elems[i]
        self._mask_tail()

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (sizes should match)."""
        n = min(len(self._elems), len(other._elems))
        self._elems[:n] = other._elems[:n]
        self._mask_tail()

    def __iter__(self) -> Iterator[bool]:
        for i in range(self.bits):
            yield self.get_index(i)

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, BitArray) and self.bits == other.bits
                and self._elems == other._elems)

    def __str__(self) -> str:
        return "".join("x" if b else "_" for b in self)

    def __repr__(self) -> str:
        return f"BitArray{{{self}}}"
