"""Shared-prefix byte rows — the host half of the reduced-send protocol.

The canonical sign-bytes of the votes in one commit differ only in the
timestamp field (and the NIL votes' block_id omission): ~105 of ~122
bytes per row are one shared per-(height, round, chain) prefix. The old
row builder materialized every row in full, so a 10k-validator commit
copied ~1.2 MB of identical prefix bytes per verification — and the
staging fast path then joined them AGAIN into the hash-input matrix.

These types carry the factored form end to end:

  SharedPrefixRows   the commit-level row container (built by
                     types/commit.vote_sign_bytes_all): one prefix,
                     per-row suffixes, and a small exceptions map for
                     rows that cannot share (NIL heads, an off-length
                     timestamp encoding). Indexing materializes real
                     bytes, so every legacy consumer sees the exact
                     rows it always did.
  PrefixedMsg        one row in factored form. Flows through the verify
                     plane (scheduler groups, kernel staging) without
                     materializing; ops/hashvec.assemble_prefixed_rows
                     reassembles whole runs on the batch axis with ONE
                     broadcast of the shared prefix. bytes(m) gives the
                     exact row for host oracles.

Layering: libs so both types/ (row construction) and ops/ (staging
reassembly) can import it.
"""

from __future__ import annotations

from collections.abc import Sequence


class PrefixedMsg:
    """One message in (shared prefix, per-row suffix) factored form.
    len() is O(1); bytes() materializes the exact row. Staging groups
    consecutive rows whose `prefix` is the SAME OBJECT into one
    batch-axis broadcast, so builders must reuse one prefix object per
    run (SharedPrefixRows does)."""

    __slots__ = ("prefix", "suffix")

    def __init__(self, prefix: bytes, suffix: bytes):
        self.prefix = prefix
        self.suffix = suffix

    def __len__(self) -> int:
        return len(self.prefix) + len(self.suffix)

    def __bytes__(self) -> bytes:
        return self.prefix + self.suffix

    def tobytes(self) -> bytes:
        return self.prefix + self.suffix

    def __eq__(self, other) -> bool:
        if isinstance(other, PrefixedMsg):
            return (self.prefix == other.prefix
                    and self.suffix == other.suffix) or \
                bytes(self) == bytes(other)
        if isinstance(other, (bytes, bytearray)):
            return bytes(self) == bytes(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(bytes(self))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PrefixedMsg({len(self.prefix)}B prefix + "
                f"{len(self.suffix)}B suffix)")


def as_bytes(msg) -> bytes:
    """Materialize a message that may be a PrefixedMsg (host-oracle and
    serial-verifier boundaries)."""
    return bytes(msg) if isinstance(msg, PrefixedMsg) else msg


class SharedPrefixRows(Sequence):
    """An immutable sequence of byte rows where row[i] is either
    `prefix + suffixes[i]` or an explicit exception row. Indexing and
    iteration yield real bytes (drop-in for the old list); rows_for()
    yields the factored PrefixedMsg form for the staging pipeline."""

    __slots__ = ("prefix", "suffixes", "exceptions")

    def __init__(self, prefix: bytes, suffixes: list,
                 exceptions: dict[int, bytes] | None = None):
        self.prefix = prefix
        self.suffixes = suffixes
        self.exceptions = exceptions or {}

    def __len__(self) -> int:
        return len(self.suffixes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        exc = self.exceptions.get(i)
        if exc is not None:
            return exc
        return self.prefix + self.suffixes[i]

    def rows_for(self, idxs) -> list:
        """The factored rows for the selected indices: PrefixedMsg for
        shared rows (all referencing THE one prefix object, so staging
        batches them as a single run), exact bytes for exceptions."""
        out = []
        for i in idxs:
            exc = self.exceptions.get(i)
            out.append(exc if exc is not None
                       else PrefixedMsg(self.prefix, self.suffixes[i]))
        return out

    def shared_fraction(self) -> float:
        """How much of the container actually shares the prefix (tests,
        telemetry)."""
        n = len(self.suffixes)
        return (n - len(self.exceptions)) / n if n else 0.0
