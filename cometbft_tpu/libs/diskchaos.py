"""Disk-fault injection registry (the storage analog of libs/chaos.py
and p2p/netchaos.py).

chaos.py breaks the DEVICE, netchaos.py breaks the WIRE; diskchaos.py
breaks the DISK at the real file seams every committed height ultimately
rests on. Sites:

  wal.write          one consensus-WAL record append (libs/autofile
                     Group.write via consensus/wal.py)
  wal.fsync          the WAL group fsync (write_sync / EndHeight / flush)
  wal.rotate         the head->chunk rename inside Group.maybe_rotate
  wal.read           one WAL record read during replay (iter_records)
  db.write           one SQLiteDB set/delete/batch transaction
  db.read            one SQLiteDB get (value returned to the caller)
  privval.save       the sign-state durable_replace (privval/file_pv.py)
  blockstore.save    the block-store save batch (store/blockstore.py)
  addrbook.save      the PEX address-book durable write
                     (p2p/pex/addrbook.py AddrBook.save)

Kinds (not every kind applies at every seam; an armed kind waits,
un-consumed, at seams it does not apply to):

  torn_write   write a PREFIX of the bytes, then die (the power-loss torn
               write; at non-byte seams: die before the operation lands).
               Death = the crash hook — os._exit(99) by default, exactly
               like libs/fail.py; in-proc harnesses install a hook that
               raises SimulatedCrash instead and then apply the
               crash-file model (crash_truncate) before "rebooting".
  fsync_error  the fsync raises EIO
  fsync_lie    the fsync returns success but NOTHING was made durable: on
               a real disk this is an ack-then-drop firmware lie only a
               power cut exposes — the in-proc model records the last
               genuinely-durable size per file and crash_truncate()
               rewinds lied files to it at simulated-crash time
  enospc       the write raises ENOSPC
  eio          the write/read raises EIO
  bitrot       a read returns the stored bytes with one bit flipped
  slow         the operation sleeps SLOW_SECONDS first, then proceeds

Arming mirrors the other planes: `CBFT_DISK_CHAOS` env, the
`storage.chaos` config knob (node boot), or the `unsafe_disk_chaos` RPC
route, all using the `site=kind[:count]` schedule syntax. Faults are
deterministic (plain per-site counters, no randomness) and every firing
is counted into the storage metrics plane (libs/metrics.storage_metrics)
so `storage_health` can account for every injected fault.
"""

from __future__ import annotations

import errno
import os
import threading
import time

SITES = (
    "wal.write",
    "wal.fsync",
    "wal.rotate",
    "wal.read",
    "db.write",
    "db.read",
    "privval.save",
    "blockstore.save",
    "addrbook.save",
)

KINDS = ("torn_write", "fsync_error", "fsync_lie", "enospc", "eio",
         "bitrot", "slow")

# seconds an injected `slow` fault stalls the seam (a degraded disk, not
# a dead one — long enough to surface in the fsync latency plane, short
# enough that liveness budgets absorb it)
SLOW_SECONDS = 0.05

_ENV = "CBFT_DISK_CHAOS"


class DiskChaosError(OSError):
    """An injected disk fault (errno carries ENOSPC/EIO like the real
    thing; `isinstance(e, DiskChaosError)` tells tests it was injected)."""


class SimulatedCrash(BaseException):
    """Raised by an in-proc crash hook instead of os._exit: the harness
    catches it, abandons the node's open handles, applies
    crash_truncate(), and reboots the node from disk. BaseException so
    no library except-Exception handler can swallow a 'power cut'."""

    def __init__(self, site: str):
        super().__init__(f"simulated power loss at {site}")
        self.site = site


class _Site:
    __slots__ = ("kind", "remaining", "fired")

    def __init__(self, kind: str, remaining: int | None):
        self.kind = kind
        self.remaining = remaining  # None = unlimited
        self.fired = 0


_lock = threading.Lock()
_sites: dict[str, _Site] = {}
_env_loaded = False
_crash_hook = None  # None -> os._exit(99)
# unlocked fast-path gate: the seams sit on per-record hot paths (every
# WAL append/fsync, every db op) — an unarmed process must not pay a
# lock per operation. Maintained under _lock wherever _sites mutates;
# the benign race (a stale False for one op right at arm time) cannot
# matter to the deterministic schedules, which arm before traffic.
_active = False

# the fsync-lie power-loss model: last genuinely durable size per path
# (updated by every real fsync through a seam) and the rewind list
# recorded when a lie fires — (path, durable_size, None) for append
# seams, (dst, old_content|None, src) for rename seams. crash_truncate()
# applies the rewinds.
_durable_sizes: dict[str, int] = {}
_lies: list[tuple[str, object, str | None]] = []


def parse_spec(spec: str) -> list[tuple[str, str, int | None]]:
    """Parse `site=kind[:count],...` into (site, kind, count) triples,
    raising ValueError on any malformed part — config validation uses
    this so a typo'd schedule fails at boot, not inside a WAL fsync."""
    out: list[tuple[str, str, int | None]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, fault = part.partition("=")
        kind, _, count = fault.partition(":")
        site, kind = site.strip(), kind.strip()
        if site not in SITES:
            raise ValueError(f"unknown disk-chaos site {site!r} (sites: {SITES})")
        if kind not in KINDS:
            raise ValueError(f"unknown disk-chaos kind {kind!r} (kinds: {KINDS})")
        if count:
            try:
                n = int(count)
            except ValueError:
                raise ValueError(f"bad disk-chaos count {count!r} in {part!r}") from None
            if n < 0:
                raise ValueError(f"negative disk-chaos count in {part!r}")
        else:
            n = None
        out.append((site, kind, n))
    return out


def _load_env_locked() -> None:
    global _env_loaded, _active
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get(_ENV, "")
    if not spec:
        return
    try:
        for site, kind, count in parse_spec(spec):
            _sites[site] = _Site(kind, count)
        _active = bool(_sites)
    except ValueError as e:
        # same floor as libs/chaos.py: the env loads lazily at the first
        # seam, where raising would be misread as a real disk failure
        from cometbft_tpu.libs import log as _log

        _log.default().error(
            "ignoring malformed CBFT_DISK_CHAOS schedule", spec=spec, err=str(e))


def arm(site: str, kind: str, count: int | None = None) -> None:
    if site not in SITES:
        raise ValueError(f"unknown disk-chaos site {site!r} (sites: {SITES})")
    if kind not in KINDS:
        raise ValueError(f"unknown disk-chaos kind {kind!r} (kinds: {KINDS})")
    global _active
    with _lock:
        _load_env_locked()
        _sites[site] = _Site(kind, count)
        _active = True


def arm_spec(spec: str) -> None:
    triples = parse_spec(spec)  # validate the WHOLE spec before arming any
    global _active
    with _lock:
        _load_env_locked()
        for site, kind, count in triples:
            _sites[site] = _Site(kind, count)
        _active = bool(_sites)


def disarm(site: str) -> None:
    global _active
    with _lock:
        _sites.pop(site, None)
        _active = bool(_sites)


def reset() -> None:
    """Disarm everything, forget the env, clear the crash-file model and
    the crash hook (tests re-arm per case)."""
    global _env_loaded, _crash_hook, _active
    with _lock:
        _sites.clear()
        _active = False
        _env_loaded = True  # a reset() overrides the process env schedule
        _durable_sizes.clear()
        _lies.clear()
        _crash_hook = None


def armed(site: str) -> str | None:
    with _lock:
        _load_env_locked()
        s = _sites.get(site)
        return s.kind if s is not None and s.remaining != 0 else None


def fired(site: str) -> int:
    with _lock:
        s = _sites.get(site)
        return s.fired if s is not None else 0


def snapshot() -> dict:
    """Armed sites + fire counts (the storage_health RPC section)."""
    with _lock:
        _load_env_locked()
        return {
            site: {"kind": s.kind, "remaining": s.remaining, "fired": s.fired}
            for site, s in _sites.items()
        }


def set_crash_hook(hook) -> None:
    """Install the death behavior for torn_write crashes. None restores
    the default os._exit(99). In-proc harnesses pass a callable raising
    SimulatedCrash(site)."""
    global _crash_hook
    with _lock:
        _crash_hook = hook


def _take(site: str, applicable: tuple) -> str | None:
    """Consume one firing iff the armed kind applies at this seam type —
    an inapplicable kind stays armed, waiting for its seam. Unarmed
    processes exit on the lock-free gate above the lock."""
    if _env_loaded and not _active:
        return None
    with _lock:
        _load_env_locked()
        s = _sites.get(site)
        if s is None or s.remaining == 0 or s.kind not in applicable:
            return None
        if s.remaining is not None:
            s.remaining -= 1
        s.fired += 1
        kind = s.kind
    _count_fault(site, kind)
    return kind


def _count_fault(site: str, kind: str) -> None:
    from cometbft_tpu.libs import metrics as cmtmetrics

    cmtmetrics.storage_metrics().disk_faults.labels(site, kind).inc()


def _crash(site: str) -> None:
    hook = _crash_hook
    if hook is not None:
        hook(site)
        return  # a hook that returns leaves the process running
    import sys

    sys.stderr.write(f"*** disk-chaos crash at {site} ***\n")
    sys.stderr.flush()
    os._exit(99)


# ------------------------------------------------------------------ seams


def fault_write(site: str, fh, data: bytes) -> None:
    """The byte-append seam: write `data` to file object `fh`, honoring
    any armed fault. torn_write flushes a strict prefix to the OS, then
    dies — the half-record a power cut leaves behind."""
    kind = _take(site, ("torn_write", "enospc", "eio", "slow"))
    if kind is None:
        fh.write(data)
        return
    if kind == "enospc":
        raise DiskChaosError(errno.ENOSPC,
                             f"disk-chaos: injected ENOSPC at {site}")
    if kind == "eio":
        raise DiskChaosError(errno.EIO, f"disk-chaos: injected EIO at {site}")
    if kind == "slow":
        time.sleep(SLOW_SECONDS)
        fh.write(data)
        return
    # torn_write: a strict non-empty prefix (never the whole record)
    fh.write(data[:max(1, len(data) // 2)])
    fh.flush()
    _crash(site)


def fault_fsync(site: str, fd: int, path: str | None = None) -> None:
    """The fsync seam: os.fsync(fd) unless a fault is armed. A real fsync
    updates the path's durable size (the fsync-lie rewind anchor) AND
    cancels the path's pending append lies — an honest fsync flushes all
    dirty pages, including the ones an earlier lie dropped on the floor.
    A lie records the stale durable size for crash_truncate(); only the
    FIRST pending lie per path is kept (no real fsync ran in between, so
    later lies carry the identical anchor)."""
    kind = _take(site, ("fsync_error", "fsync_lie", "slow"))
    if kind == "fsync_error":
        raise DiskChaosError(errno.EIO,
                             f"disk-chaos: injected fsync failure at {site}")
    if kind == "fsync_lie":
        if path is not None:
            with _lock:
                if not any(p == path and src is None for p, _, src in _lies):
                    _lies.append((path, _durable_sizes.get(path, 0), None))
        return
    if kind == "slow":
        time.sleep(SLOW_SECONDS)
    os.fsync(fd)
    if path is not None:
        with _lock:
            _durable_sizes[path] = os.fstat(fd).st_size
            _lies[:] = [e for e in _lies
                        if not (e[0] == path and e[2] is None)]


def fault_replace(site: str, src: str, dst: str) -> None:
    """The durable-rename seam (libs/diskio.durable_replace): os.replace
    + containing-directory fsync, honoring armed faults. fsync_lie skips
    the directory fsync and records the OLD dst content — at simulated
    crash time the rename is rolled back (the power cut dropped the
    un-fsynced directory entry)."""
    kind = _take(site, ("torn_write", "enospc", "eio", "slow",
                        "fsync_error", "fsync_lie"))
    if kind == "enospc":
        raise DiskChaosError(errno.ENOSPC,
                             f"disk-chaos: injected ENOSPC at {site}")
    if kind == "eio":
        raise DiskChaosError(errno.EIO, f"disk-chaos: injected EIO at {site}")
    if kind == "slow":
        time.sleep(SLOW_SECONDS)
    if kind == "torn_write":
        # power dies mid-rename: the new name never lands
        _crash(site)
    old: bytes | None = None
    if kind == "fsync_lie":
        try:
            with open(dst, "rb") as f:
                old = f.read()
        except FileNotFoundError:
            old = None
    os.replace(src, dst)
    if kind == "fsync_lie":
        # the rename's directory entry never reached disk: at crash time
        # the OLD directory wins — src reappears with the new content and
        # dst reverts to its old content (or absence). Recording src is
        # load-bearing for WAL rotation, where "dst reverts" alone would
        # destroy a whole chunk of records no power cut could take.
        with _lock:
            _lies.append((dst, old, src))
        return
    d = os.path.dirname(os.path.abspath(dst))
    dfd = os.open(d, os.O_RDONLY)
    try:
        if kind == "fsync_error":
            raise DiskChaosError(
                errno.EIO, f"disk-chaos: injected directory-fsync failure at {site}")
        os.fsync(dfd)
    finally:
        os.close(dfd)
    with _lock:
        # an honest directory fsync persists EVERY pending rename in this
        # directory — cancel their recorded lies
        _lies[:] = [e for e in _lies
                    if not (e[2] is not None
                            and os.path.dirname(os.path.abspath(e[0])) == d)]
        try:
            _durable_sizes[dst] = os.path.getsize(dst)
        except OSError:
            pass


def fault_read(site: str, data: bytes) -> bytes:
    """The read seam: return `data` as stored, or with one bit flipped
    (bitrot), or raise EIO. The CRC planes above this seam must turn a
    flipped bit into a typed error, never a corrupt record."""
    kind = _take(site, ("bitrot", "eio", "slow"))
    if kind is None:
        return data
    if kind == "eio":
        raise DiskChaosError(errno.EIO, f"disk-chaos: injected EIO at {site}")
    if kind == "slow":
        time.sleep(SLOW_SECONDS)
        return data
    if not data:
        return data
    out = bytearray(data)
    out[0] ^= 0x01
    return bytes(out)


def fault_op(site: str) -> None:
    """The opaque-operation seam (SQLite transactions, block-store save):
    enospc/eio raise before anything lands; torn_write dies mid-operation
    (the caller placed this call where a power cut would tear — e.g.
    between the statements of a batch, where only a real transaction
    saves you); slow stalls."""
    kind = _take(site, ("torn_write", "enospc", "eio", "slow"))
    if kind is None:
        return
    if kind == "enospc":
        raise DiskChaosError(errno.ENOSPC,
                             f"disk-chaos: injected ENOSPC at {site}")
    if kind == "eio":
        raise DiskChaosError(errno.EIO, f"disk-chaos: injected EIO at {site}")
    if kind == "slow":
        time.sleep(SLOW_SECONDS)
        return
    _crash(site)


def track_open(path: str, fresh: bool = False) -> None:
    """Record a file's size at open as its durable baseline (everything
    already on disk at open is assumed durable). Called by the append
    seams (autofile Group) so a later fsync_lie knows where to rewind.
    `fresh=True` re-anchors unconditionally — rotation reopens the head
    path as a NEW empty file, and keeping the renamed-away chunk's
    anchor would rewind (and zero-extend!) the wrong file."""
    with _lock:
        if fresh or path not in _durable_sizes:
            try:
                _durable_sizes[path] = os.path.getsize(path)
            except OSError:
                _durable_sizes[path] = 0


def crash_truncate() -> list[str]:
    """Apply the power-loss model for every recorded fsync lie: append
    seams are truncated back to the last genuinely durable size, rename
    seams are rolled back to the old content (or unlinked when the file
    did not exist). Returns the repaired paths. The in-proc crash
    harness calls this between 'power cut' and 'reboot'; the OS-process
    path never needs it (a real kill leaves the kernel page cache
    intact — only real power loss exposes a lying fsync)."""
    with _lock:
        lies, _lies[:] = list(_lies), ()
    touched = []
    for path, state, src in lies:
        try:
            if src is not None:
                # rename rollback: the new content returns to the src
                # name, dst reverts to its old content or to absence
                if os.path.exists(path):
                    os.replace(path, src)
                if isinstance(state, bytes):
                    with open(path, "wb") as f:
                        f.write(state)
            elif isinstance(state, int):
                # clamp: power loss can only SHRINK a file — truncating
                # past the current size would zero-extend, and a zeroed
                # region is not something a dropped write leaves behind
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                with open(path, "r+b") as f:
                    f.truncate(min(state, size))
            else:
                with open(path, "wb") as f:
                    f.write(state)
        except OSError:
            continue
        touched.append(path)
    return touched
