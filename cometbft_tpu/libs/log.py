"""Structured logfmt/JSON logger (reference: libs/log).

Module-scoped child loggers via with_fields(); lazy value rendering so hot
paths (vote ingestion) pay nothing when the level is filtered — the analog of
the reference's log.NewLazySprintf (consensus/state.go:1654).

Trace correlation: when the flight recorder (libs/trace.py) is armed and a
span is active on the emitting thread/task, every record is stamped with
`trace_id`/`span_id` — a slow-batch capture and its log lines correlate by
id. Consensus-path records additionally carry `height`/`round` from the
contextvar set by ConsensusState._new_step (set_height_round), so
grep-by-height works across the whole node log. JSON output is opt-in
process-wide via set_default_format("json") (node boot wires
base.log_format through it) or CBFT_LOG_FORMAT=json, so library code
calling default() follows the node's choice.
"""

from __future__ import annotations

import contextvars
import io
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Optional, TextIO

from cometbft_tpu.libs import trace as _trace

# (height, round) of the consensus step the emitting task is in, or None
# outside the consensus path. A contextvar, so the stamp follows the
# consensus receive task without leaking into reactor/RPC tasks.
_height_ctx: contextvars.ContextVar[Optional[tuple]] = contextvars.ContextVar(
    "cbft_log_height", default=None)


def set_height_round(height: int, round_: int) -> None:
    """Stamp subsequent log records from this task with height/round."""
    _height_ctx.set((height, round_))


def clear_height_round() -> None:
    _height_ctx.set(None)


def current_height_round() -> Optional[tuple]:
    return _height_ctx.get()

DEBUG, INFO, WARN, ERROR, NONE = 0, 1, 2, 3, 4
_LEVEL_NAMES = {DEBUG: "debug", INFO: "info", WARN: "warn", ERROR: "error"}
_NAME_LEVELS = {v: k for k, v in _LEVEL_NAMES.items()} | {"none": NONE}


def parse_level(name: str) -> int:
    try:
        return _NAME_LEVELS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r}") from None


class Lazy:
    """Defers fn() until the record is actually emitted."""

    __slots__ = ("fn",)

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def __str__(self) -> str:
        return str(self.fn())


def lazy_hex(b: bytes) -> Lazy:
    return Lazy(lambda: b.hex().upper())


def _fmt_value(v: Any) -> str:
    if isinstance(v, Lazy):
        v = str(v)
    if isinstance(v, bytes):
        v = v.hex().upper()
    s = str(v)
    if any(c in ' ="' or ord(c) < 0x20 or c == "\x7f" for c in s):
        return json.dumps(s)
    return s


class Logger:
    """logfmt (default) or JSON lines to a stream."""

    def __init__(self, stream: Optional[TextIO] = None, level: int = INFO,
                 fields: tuple = (), fmt: str = "logfmt"):
        self._stream = stream if stream is not None else sys.stderr
        self.level = level
        self._fields = fields
        self._fmt = fmt
        self._lock = threading.Lock()

    def with_fields(self, **kv: Any) -> "Logger":
        child = Logger(self._stream, self.level, self._fields + tuple(kv.items()), self._fmt)
        child._lock = self._lock
        return child

    # alias matching the reference's logger.With(...)
    with_ = with_fields

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        if level < self.level:
            return
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
        ids = _trace.current_ids()  # None in two reads when tracing is off
        items = self._fields + tuple(kv.items())
        hr = _height_ctx.get()  # None outside the consensus path
        if hr is not None:
            items += (("height", hr[0]), ("round", hr[1]))
        if ids is not None:
            items += (("trace_id", ids[0]), ("span_id", ids[1]))
        if self._fmt == "json":
            rec = {"level": _LEVEL_NAMES[level], "ts": ts, "msg": msg}
            for k, v in items:
                if isinstance(v, bytes):
                    v = v.hex().upper()
                elif isinstance(v, Lazy):
                    v = str(v)
                rec[k] = v
            line = json.dumps(rec, default=str)
        else:
            buf = io.StringIO()
            buf.write(f"{_LEVEL_NAMES[level][0].upper()}[{ts}] {msg}")
            for k, v in items:
                buf.write(f" {k}={_fmt_value(v)}")
            line = buf.getvalue()
        with self._lock:
            self._stream.write(line + "\n")

    def debug(self, msg: str, **kv: Any) -> None:
        self._emit(DEBUG, msg, kv)

    def info(self, msg: str, **kv: Any) -> None:
        self._emit(INFO, msg, kv)

    def warn(self, msg: str, **kv: Any) -> None:
        self._emit(WARN, msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        self._emit(ERROR, msg, kv)


class _NopLogger(Logger):
    def __init__(self) -> None:
        super().__init__(stream=io.StringIO(), level=NONE)

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        pass


_NOP = _NopLogger()


def nop() -> Logger:
    return _NOP


_default_fmt: str | None = None


def set_default_format(fmt: str) -> None:
    """Process-wide default output format for default()-constructed
    loggers ("logfmt" | "json"). Node boot routes base.log_format here so
    deep library log sites (kernels, scheduler, supervisors) emit in the
    node's configured format instead of hardcoded logfmt."""
    if fmt not in ("logfmt", "json"):
        raise ValueError(f"unknown log format {fmt!r}")
    global _default_fmt
    _default_fmt = fmt


def default(level: int = INFO, fmt: str | None = None) -> Logger:
    if fmt is None:
        # the env var is the operator overlay and wins over the config-
        # routed process default (the CBFT_TRACE-over-config pattern)
        fmt = os.environ.get("CBFT_LOG_FORMAT") or _default_fmt or "logfmt"
    return Logger(sys.stderr, level, (), fmt)


def test_logger() -> Logger:
    return Logger(sys.stdout, DEBUG)
