"""CertPlane: the service that keeps the certificate store full.

Production is event-driven: the plane subscribes to NewBlock on the
node's EventBus (the same bridge the light fleet's head watcher rides)
and certifies each height the moment its commit lands — no polling
while the bus is live, which a regression test asserts via the
`poll_ticks` counter. Nodes without a bus (inspect shims, tests) fall
back to a store poll. A bounded backfill worker walks [base, head] in
batches so a node that enables the plane late — or restarts with an
empty cert db — converges on full coverage of the retained range while
the chain keeps advancing.

Commit-source discipline: the store's serving convention is
`load_block_commit(h) or load_seen_commit(h)` (canonical first). The
plane certifies with the same preference, and when the CANONICAL commit
for h-1 appears (written when block h saves) it re-checks the stored
certificate against it — a seen-commit cert whose round or signer set
differs from canonical is rebuilt, so a served certificate always
attests the commit the node actually serves next to it.

Uncertifiable (sets, commits) — mixed/ed25519 validator sets, empty or
sub-threshold commits — are counted and skipped; every consumer keeps
the classic per-vote path. A BLS set with the backend disabled raises
inside build_certificate; the plane counts it as a production failure
and logs loudly rather than dying (the verify paths enforce the same
misconfiguration with a raise, so it cannot go unnoticed).
"""

from __future__ import annotations

import asyncio

from cometbft_tpu.cert.certificate import build_certificate, matches_commit
from cometbft_tpu.cert.store import CertStore
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService

DEFAULT_POLL_INTERVAL = 1.0
DEFAULT_BACKFILL_BATCH = 32


class CertPlane(BaseService):
    def __init__(
        self,
        store: CertStore,
        block_store,
        state_store,
        chain_id: str,
        event_bus=None,
        backfill: bool = True,
        backfill_batch: int = DEFAULT_BACKFILL_BATCH,
        poll_interval: float = DEFAULT_POLL_INTERVAL,
        metrics=None,
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("CertPlane", logger)
        self.store = store
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.event_bus = event_bus
        self.backfill_enabled = backfill
        self.backfill_batch = max(1, int(backfill_batch))
        self.poll_interval = poll_interval
        self.metrics = metrics
        self._tasks: list[asyncio.Task] = []
        self._sub = None
        # counters (health() surfaces all of them; consumers bump the
        # serve/verify/fallback side through the count_* helpers)
        self.produced = 0
        self.rebuilt = 0  # seen-commit certs realigned to canonical
        self.uncertifiable = 0
        self.produce_failures = 0
        self.backfilled = 0
        self.served = 0
        self.verified = 0
        self.verify_failures = 0
        self.fallbacks = 0
        self.bus_events = 0
        self.poll_ticks = 0  # MUST stay 0 while the bus is live

    # ------------------------------------------------------------ produce

    def _load_commit(self, height: int):
        return (self.block_store.load_block_commit(height)
                or self.block_store.load_seen_commit(height))

    def certify_height(self, height: int, *, backfill: bool = False) -> bool:
        """Certify one height from the stored commit + validator set.
        True when a certificate exists afterwards (fresh or prior);
        False when the height is uncertifiable or material is missing.
        Synchronous and idempotent — exposed for tests and backfill."""
        if height <= 0:
            return False
        if self.store.has(height):
            return True
        commit = self._load_commit(height)
        if commit is None:
            return False
        vals = self.state_store.load_validators(height)
        if vals is None:
            return False
        try:
            cert = build_certificate(self.chain_id, vals, commit)
        except Exception as e:  # noqa: BLE001 - keep the plane alive
            self.produce_failures += 1
            self.logger.error("certificate production failed",
                              height=height, err=str(e))
            return False
        if cert is None:
            self.uncertifiable += 1
            return False
        self.store.put(cert)
        self.produced += 1
        if backfill:
            self.backfilled += 1
        if self.metrics is not None:
            self.metrics.cert_produced.inc()
            if backfill:
                self.metrics.cert_backfilled.inc()
        return True

    def _realign_canonical(self, height: int) -> None:
        """Once the canonical commit for `height` exists, make the
        stored certificate attest IT (the commit every serving path
        returns), rebuilding a seen-commit cert that differs."""
        if height <= 0:
            return
        canon = self.block_store.load_block_commit(height)
        if canon is None:
            return
        cert = self.store.get(height)
        if cert is not None and matches_commit(cert, canon):
            return
        vals = self.state_store.load_validators(height)
        if vals is None:
            return
        try:
            fresh = build_certificate(self.chain_id, vals, canon)
        except Exception as e:  # noqa: BLE001
            self.produce_failures += 1
            self.logger.error("certificate realign failed",
                              height=height, err=str(e))
            return
        if fresh is None:
            if cert is None:
                self.uncertifiable += 1
            return
        self.store.put(fresh)
        if cert is None:
            self.produced += 1
            if self.metrics is not None:
                self.metrics.cert_produced.inc()
        else:
            self.rebuilt += 1

    def _on_new_height(self, height: int) -> None:
        self.certify_height(height)
        self._realign_canonical(height - 1)

    # ------------------------------------------------------------ consume

    def serve(self, height: int) -> bytes | None:
        """Encoded certificate bytes for a consumer (RPC, blocksync),
        counting the serve. None when absent/quarantined."""
        raw = self.store.get_raw(height)
        if raw is not None:
            self.served += 1
            if self.metrics is not None:
                self.metrics.cert_served.inc()
        return raw

    def count_verified(self) -> None:
        self.verified += 1
        if self.metrics is not None:
            self.metrics.cert_verified.inc()

    def count_fallback(self) -> None:
        """A consumer held a certificate but ran the classic per-vote
        path anyway (invalid/mismatched/corrupt cert). The fallback
        invariant makes this a counted degradation, never a verdict."""
        self.fallbacks += 1
        if self.metrics is not None:
            self.metrics.cert_fallbacks.inc()

    def count_verify_failure(self) -> None:
        self.verify_failures += 1

    # ------------------------------------------------------------ service

    async def on_start(self) -> None:
        loop = asyncio.get_running_loop()
        if self.event_bus is not None:
            from cometbft_tpu.types import event_bus as eb

            try:
                self._sub = self.event_bus.subscribe(
                    "cert-plane", eb.QUERY_NEW_BLOCK)
            except Exception:  # noqa: BLE001 - no server/already subscribed
                self._sub = None
        if self._sub is not None:
            self._tasks.append(loop.create_task(
                self._event_loop(), name="cert-plane-events"))
        else:
            self._tasks.append(loop.create_task(
                self._poll_loop(), name="cert-plane-poll"))
        if self.backfill_enabled:
            self._tasks.append(loop.create_task(
                self._backfill_loop(), name="cert-plane-backfill"))

    async def on_stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks.clear()
        if self._sub is not None and self.event_bus is not None:
            try:
                self.event_bus.unsubscribe_all("cert-plane")
            except Exception:  # noqa: BLE001
                pass
            self._sub = None

    async def _event_loop(self) -> None:
        sub = self._sub
        while True:
            msg = await sub.out.get()
            if msg is None:  # cancellation wake-up
                if sub.canceled is not None:
                    return
                continue
            block = getattr(msg.data, "block", None)
            header = getattr(block, "header", None)
            height = getattr(header, "height", None)
            if not height:
                continue
            self.bus_events += 1
            try:
                self._on_new_height(int(height))
            except Exception as e:  # noqa: BLE001 - keep the pump alive
                self.logger.error("cert event handling failed",
                                  height=height, err=str(e))

    async def _poll_loop(self) -> None:
        """Store-poll fallback for nodes without an event bus. Never
        runs alongside the event loop — poll_ticks counts its
        iterations, and the bus-liveness regression test pins it at 0."""
        last = 0
        while True:
            self.poll_ticks += 1
            try:
                head = self.block_store.height()
                while last < head:
                    last += 1
                    self._on_new_height(last)
            except Exception as e:  # noqa: BLE001
                self.logger.error("cert poll failed", err=str(e))
            await asyncio.sleep(self.poll_interval)

    async def _backfill_loop(self) -> None:
        """Bounded historical certification: walk [base, head] in
        batches, yielding between heights so production stays ahead of
        backfill and the loop never starves the node."""
        while True:
            try:
                base = max(1, self.block_store.base())
                head = self.block_store.height()
                missing = self.store.missing_in(base, head,
                                                self.backfill_batch)
            except Exception as e:  # noqa: BLE001
                self.logger.error("cert backfill scan failed", err=str(e))
                missing = []
            progressed = 0
            for h in missing:
                try:
                    if self.certify_height(h, backfill=True):
                        progressed += 1
                except Exception as e:  # noqa: BLE001
                    self.logger.error("cert backfill failed",
                                      height=h, err=str(e))
                await asyncio.sleep(0)
            # an uncertifiable range (ed25519 history) yields no
            # progress; sleep the full interval instead of spinning
            await asyncio.sleep(
                0.05 if progressed and len(missing) >= self.backfill_batch
                else self.poll_interval)

    # ------------------------------------------------------ observability

    def health(self) -> dict:
        return {
            "certified_heights": self.store.count(),
            "produced": self.produced,
            "rebuilt": self.rebuilt,
            "backfilled": self.backfilled,
            "uncertifiable": self.uncertifiable,
            "produce_failures": self.produce_failures,
            "served": self.served,
            "verified": self.verified,
            "verify_failures": self.verify_failures,
            "fallbacks": self.fallbacks,
            "quarantined": self.store.quarantined,
            "bus_events": self.bus_events,
            "poll_ticks": self.poll_ticks,
        }
