"""CommitCertificate: a succinct, durable finality artifact.

For an all-BLS validator set a committed height is fully decided by
~200 bytes: one aggregated G2 signature over the signers' canonical
precommit sign-bytes, a bitmap naming WHICH validators signed, and the
(chain_id, height, round, block_id, valset_hash) tuple pinning what
they signed about. Verification is a >2/3-voting-power tally over the
bitmap plus ONE pairing-product check — the same check
`_bls_aggregate_ok` runs per commit, minus the per-vote signature sum
(the certificate carries the sum pre-computed).

Sign-bytes subtlety: CometBFT precommits embed each validator's own
timestamp, so the messages under the aggregate differ per signer. The
certificate therefore carries a base timestamp plus one uvarint
nanosecond delta per set bit (index order); reconstruction reuses
Commit.vote_sign_bytes_all so the rows are byte-identical to what the
per-vote path verifies.

The fallback invariant every consumer relies on: a certificate can
only ever ACCEPT. Absent, mismatched, corrupt, or failing certificates
all fall through to the unchanged per-vote path, so verdicts (and
raised errors) are bit-identical with or without the plane — a forged
certificate can never cause acceptance, only a counted fallback.
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import BlockID, BlockIDFlag
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb

# compressed G2 point
AGG_SIG_SIZE = 96

# decode() guards: a certificate names one committee, not a DoS vector
MAX_CHAIN_ID_LEN = 64
MAX_VALIDATORS = 1 << 20


class ErrCertInvalid(Exception):
    """The certificate failed verification against a validator set.

    Consumers treat this exactly like a missing certificate: count it
    and run the classic per-vote path. Never a ban, never a verdict."""


@dataclass
class CommitCertificate:
    chain_id: str
    height: int
    round_: int
    block_id: BlockID
    valset_hash: bytes
    n_vals: int
    signers: BitArray
    ts_base: cmttime.Timestamp
    ts_deltas: list[int]  # ns offsets from ts_base, one per set bit, index order
    agg_sig: bytes

    def signer_indices(self) -> list[int]:
        return self.signers.get_true_indices()

    def signer_timestamps(self) -> list[cmttime.Timestamp]:
        """Per-signer timestamps (same order as signer_indices)."""
        base_ns = self.ts_base.unix_ns()
        out = []
        for d in self.ts_deltas:
            ns = base_ns + d
            out.append(cmttime.Timestamp(ns // 1_000_000_000, ns % 1_000_000_000))
        return out

    def to_commit(self) -> Commit:
        """A synthetic Commit carrying exactly the certified votes:
        COMMIT rows (with reconstructed timestamps) for set bits, ABSENT
        elsewhere. Canonical vote sign-bytes do not include the
        validator address, so none is needed — vote_sign_bytes_all on
        this commit yields rows byte-identical to the original."""
        sigs = [CommitSig.absent() for _ in range(self.n_vals)]
        for i, ts in zip(self.signer_indices(), self.signer_timestamps()):
            sigs[i] = CommitSig(block_id_flag=BlockIDFlag.COMMIT, timestamp=ts)
        return Commit(height=self.height, round_=self.round_,
                      block_id=self.block_id, signatures=sigs)

    def encode(self) -> bytes:
        w = pb.Writer()
        w.string(1, self.chain_id)
        w.varint_i64(2, self.height)
        w.varint_i64(3, self.round_)
        w.message(4, self.block_id.to_proto(), always=True)
        w.bytes(5, self.valset_hash)
        w.uvarint(6, self.n_vals)
        w.bytes(7, self.signers.to_bytes())
        w.message(8, pb.timestamp_bytes(self.ts_base.seconds, self.ts_base.nanos),
                  always=True)
        w.bytes(9, b"".join(pb.encode_uvarint(d) for d in self.ts_deltas))
        w.bytes(10, self.agg_sig)
        return w.output()

    @classmethod
    def decode(cls, data: bytes) -> "CommitCertificate":
        """Raises ValueError on any malformed input (store quarantine
        and wire handlers catch it)."""
        r = pb.Reader(data)
        chain_id = ""
        height = 0
        round_ = 0
        block_id = BlockID()
        valset_hash = b""
        n_vals = 0
        bitmap_raw = b""
        ts_base = cmttime.Timestamp.zero()
        deltas_raw = b""
        agg_sig = b""
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                chain_id = r.read_bytes().decode("utf-8")
            elif f == 2:
                height = r.read_varint_i64()
            elif f == 3:
                round_ = r.read_varint_i64()
            elif f == 4:
                block_id = BlockID.from_proto(r.read_bytes())
            elif f == 5:
                valset_hash = r.read_bytes()
            elif f == 6:
                n_vals = r.read_uvarint()
            elif f == 7:
                bitmap_raw = r.read_bytes()
            elif f == 8:
                secs, nanos = r.read_timestamp()
                ts_base = cmttime.Timestamp(secs, nanos)
            elif f == 9:
                deltas_raw = r.read_bytes()
            elif f == 10:
                agg_sig = r.read_bytes()
            else:
                r.skip(w)
        if len(chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError("certificate chain_id too long")
        if not (0 < n_vals <= MAX_VALIDATORS):
            raise ValueError(f"certificate n_vals out of range: {n_vals}")
        if len(bitmap_raw) != (n_vals + 7) // 8:
            raise ValueError("certificate bitmap length mismatch")
        if height <= 0:
            raise ValueError(f"certificate height out of range: {height}")
        if round_ < 0:
            raise ValueError(f"negative certificate round: {round_}")
        if len(agg_sig) != AGG_SIG_SIZE:
            raise ValueError("certificate aggregate signature must be "
                             f"{AGG_SIG_SIZE} bytes, got {len(agg_sig)}")
        signers = BitArray.from_bytes(n_vals, bitmap_raw)
        deltas: list[int] = []
        pos = 0
        while pos < len(deltas_raw):
            d, pos = pb.decode_uvarint(deltas_raw, pos)
            deltas.append(d)
        if len(deltas) != signers.num_true():
            raise ValueError("certificate timestamp deltas do not match "
                             "signer count")
        return cls(chain_id=chain_id, height=height, round_=round_,
                   block_id=block_id, valset_hash=valset_hash, n_vals=n_vals,
                   signers=signers, ts_base=ts_base, ts_deltas=deltas,
                   agg_sig=agg_sig)

    def summary(self) -> dict:
        """JSON-safe view for RPC / debugging (no signature material)."""
        return {
            "chain_id": self.chain_id,
            "height": self.height,
            "round": self.round_,
            "block_hash": self.block_id.hash.hex(),
            "valset_hash": self.valset_hash.hex(),
            "n_vals": self.n_vals,
            "n_signers": self.signers.num_true(),
            "size_bytes": len(self.encode()),
        }


def build_certificate(chain_id: str, vals, commit: Commit):
    """Condense a verified commit into a certificate, or return None
    when this (set, commit) pair is not certifiable: mixed/ed25519
    validator sets, empty or sub-threshold commits, or undecodable
    signature points. None is the ONLY negative outcome — production is
    best-effort and consumers always have the per-vote path.

    Raises ErrInvalidKey when the set is all-BLS but the BLS backend is
    disabled: that is a misconfiguration, and the loud-failure rule from
    the verify path (`_bls_aggregate_ok`) applies to production too.
    """
    if commit is None or vals is None:
        return None
    n = len(vals.validators)
    if n == 0 or len(commit.signatures) != n:
        return None
    if any(v.pub_key.type_() != "bls12381" for v in vals.validators):
        return None
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import bls12381
    if not bls12381.enabled():
        raise crypto_batch.crypto.ErrInvalidKey(
            "bls12381 validator set but crypto.bls_enabled is off")
    idxs = [i for i, cs in enumerate(commit.signatures)
            if cs.block_id_flag == BlockIDFlag.COMMIT]
    if not idxs:
        return None
    tallied = sum(vals.validators[i].voting_power for i in idxs)
    if tallied <= vals.total_voting_power() * 2 // 3:
        return None
    from cometbft_tpu.ops import bls_kernel
    try:
        agg = bls_kernel.aggregate_signatures(
            [bytes(commit.signatures[i].signature) for i in idxs])
    except ValueError:
        return None
    ts_ns = [commit.signatures[i].timestamp.unix_ns() for i in idxs]
    base_ns = min(ts_ns)
    signers = BitArray(n)
    for i in idxs:
        signers.set_index(i, True)
    return CommitCertificate(
        chain_id=chain_id,
        height=commit.height,
        round_=commit.round_,
        block_id=commit.block_id,
        valset_hash=vals.hash(),
        n_vals=n,
        signers=signers,
        ts_base=cmttime.Timestamp(base_ns // 1_000_000_000,
                                  base_ns % 1_000_000_000),
        ts_deltas=[t - base_ns for t in ts_ns],
        agg_sig=agg,
    )


def verify_certificate(cert: CommitCertificate, chain_id: str, vals) -> None:
    """Full certificate verification against a validator set: structural
    checks, >2/3 voting-power tally over the bitmap, and ONE
    pairing-product check through the scheduler/mesh path. Raises
    ErrCertInvalid on any failure; returns None on success.

    Raises ErrInvalidKey (not ErrCertInvalid) when the set is all-BLS
    but the backend is disabled — misconfiguration must stay loud, the
    same rule the per-vote aggregate path enforces.
    """
    if cert.chain_id != chain_id:
        raise ErrCertInvalid(
            f"certificate chain {cert.chain_id!r} != {chain_id!r}")
    if vals is None or not vals.validators:
        raise ErrCertInvalid("empty validator set")
    if cert.n_vals != len(vals.validators):
        raise ErrCertInvalid(
            f"certificate covers {cert.n_vals} validators, set has "
            f"{len(vals.validators)}")
    if cert.valset_hash != vals.hash():
        raise ErrCertInvalid("certificate valset_hash does not match set")
    if cert.block_id.is_nil():
        raise ErrCertInvalid("certificate for nil block")
    if len(cert.agg_sig) != AGG_SIG_SIZE:
        raise ErrCertInvalid("bad aggregate signature size")
    idxs = cert.signer_indices()
    if not idxs or len(cert.ts_deltas) != len(idxs):
        raise ErrCertInvalid("certificate signer bitmap/timestamps malformed")
    tallied = sum(vals.validators[i].voting_power for i in idxs)
    needed = vals.total_voting_power() * 2 // 3
    if tallied <= needed:
        raise ErrCertInvalid(
            f"insufficient certified voting power: {tallied} <= needed "
            f"{needed}")
    pubs = [vals.validators[i].pub_key for i in idxs]
    rows = cert.to_commit().vote_sign_bytes_all(chain_id)
    msgs = rows.rows_for(idxs)
    from cometbft_tpu.types import validation
    ok = validation._bls_aggregate_agg_ok(pubs, msgs, cert.agg_sig)
    if ok is None:
        # mixed/non-BLS sets never get a certificate; one claiming to
        # cover such a set is forged or misdirected
        raise ErrCertInvalid("validator set is not all-BLS")
    if not ok:
        raise ErrCertInvalid("aggregate pairing check failed")


def matches_commit(cert: CommitCertificate, commit: Commit) -> bool:
    """Does this certificate attest EXACTLY the given commit? Same
    height/round/block_id, bitmap == the commit's COMMIT-flag signer
    set, and identical per-signer timestamps. Consumers that hold both
    artifacts (light clients verifying a header whose hash covers the
    commit) require a match before letting the certificate stand in for
    per-vote verification — that is what keeps verdicts bit-identical."""
    if commit is None:
        return False
    if (cert.height != commit.height or cert.round_ != commit.round_
            or cert.block_id != commit.block_id
            or cert.n_vals != len(commit.signatures)):
        return False
    commit_idxs = [i for i, cs in enumerate(commit.signatures)
                   if cs.block_id_flag == BlockIDFlag.COMMIT]
    if commit_idxs != cert.signer_indices():
        return False
    cert_ts = cert.signer_timestamps()
    for i, ts in zip(commit_idxs, cert_ts):
        if commit.signatures[i].timestamp.unix_ns() != ts.unix_ns():
            return False
    return True


def attests_commit(cert: CommitCertificate, commit: Commit) -> bool:
    """matches_commit PLUS signature-sum equality: the commit's own
    signature bytes must aggregate to cert.agg_sig. A consumer holding
    BOTH artifacts needs this before the certificate may stand in for
    per-vote verification — without it, a commit carrying a mauled
    signature next to an honestly-aggregated certificate would verify
    via the certificate while the per-vote path rejects it. With the
    sum pinned, cert-accept is equivalent to today's aggregate-first
    BLS path (`_bls_aggregate_ok`) on this exact commit: same sum, same
    messages, same one-pairing verdict. Point adds only — the pairing
    stays in verify_certificate."""
    if not matches_commit(cert, commit):
        return False
    from cometbft_tpu.ops import bls_kernel
    try:
        agg = bls_kernel.aggregate_signatures(
            [bytes(commit.signatures[i].signature)
             for i in cert.signer_indices()])
    except ValueError:
        return False
    return agg == cert.agg_sig
