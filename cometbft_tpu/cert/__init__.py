"""Commit-certificate plane: succinct finality certificates.

A CommitCertificate condenses an all-BLS commit into one aggregated G2
signature plus a signer bitmap — produced once at commit finalize,
verified anywhere with ONE pairing-product check, and served to every
consumer (RPC, light fleet, blocksync) so commit transport and
re-verification cost stays ~independent of committee size.
"""

from cometbft_tpu.cert.certificate import (
    CommitCertificate,
    ErrCertInvalid,
    attests_commit,
    build_certificate,
    matches_commit,
    verify_certificate,
)
from cometbft_tpu.cert.plane import CertPlane
from cometbft_tpu.cert.store import CertStore

__all__ = [
    "CommitCertificate",
    "ErrCertInvalid",
    "CertPlane",
    "CertStore",
    "attests_commit",
    "build_certificate",
    "matches_commit",
    "verify_certificate",
]
