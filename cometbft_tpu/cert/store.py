"""CertStore: durable commit certificates on the storage plane.

One KVStore (normally an `open_db(..., checksum=True)` CRC-guarded
SQLite db, so every read/write rides the diskchaos `db.read`/`db.write`
seams and every value carries a crc32 envelope) holding one certificate
per height under a fixed-width big-endian key — range iteration walks
heights in order, which is what pruning and backfill gap-scans need.

Corruption policy mirrors the block store's quarantine rule: a value
that fails the CRC envelope or the certificate codec is DELETED and
counted, and the reader sees "no certificate" — consumers then run the
classic per-vote path. A bad byte on disk can cost a fallback, never a
wrong verdict and never a crash loop.
"""

from __future__ import annotations

import struct
import threading

from cometbft_tpu.cert.certificate import CommitCertificate
from cometbft_tpu.store.db import ErrCorruptValue, KVStore

_PREFIX = b"cert:"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">Q", height)


def _height_of(key: bytes) -> int:
    return struct.unpack(">Q", key[len(_PREFIX):])[0]


class CertStore:
    def __init__(self, db: KVStore):
        self.db = db
        self._lock = threading.RLock()
        self.quarantined = 0  # corrupt values deleted on read

    def put(self, cert: CommitCertificate) -> None:
        with self._lock:
            self.db.set(_key(cert.height), cert.encode())

    def has(self, height: int) -> bool:
        with self._lock:
            return self.db.has(_key(height))

    def get(self, height: int) -> CommitCertificate | None:
        """The decoded certificate, or None (absent OR quarantined)."""
        raw = self.get_raw(height)
        if raw is None:
            return None
        try:
            return CommitCertificate.decode(raw)
        except ValueError:
            self._quarantine(height)
            return None

    def get_raw(self, height: int) -> bytes | None:
        """The encoded certificate bytes (serving paths ship these
        verbatim), or None."""
        with self._lock:
            try:
                return self.db.get(_key(height))
            except ErrCorruptValue:
                self._quarantine(height)
                return None

    def _quarantine(self, height: int) -> None:
        with self._lock:
            self.quarantined += 1
            try:
                self.db.delete(_key(height))
            except Exception:  # noqa: BLE001 - best-effort removal
                pass

    def _scan_keys(self, start: bytes, end: bytes) -> list[bytes]:
        """Key-only range scan tolerant of corrupt VALUES: a CRC-guarded
        iterator raises mid-scan on a rotted record, which would let one
        bad byte veto pruning and backfill planning for every other
        height. Quarantine the offender and resume past it instead."""
        keys: list[bytes] = []
        while True:
            try:
                for k, _ in self.db.iterate(start, end):
                    keys.append(k)
                return keys
            except ErrCorruptValue as e:
                self._quarantine(_height_of(e.key))
                start = e.key + b"\x00"

    def heights(self) -> list[int]:
        """All certified heights, ascending."""
        with self._lock:
            return [_height_of(k)
                    for k in self._scan_keys(_PREFIX, _PREFIX + b"\xff")]

    def missing_in(self, base: int, head: int, limit: int) -> list[int]:
        """Up to `limit` uncertified heights in [base, head], ascending —
        the backfill worker's batch planner."""
        if head < base or limit <= 0:
            return []
        with self._lock:
            have = {_height_of(k)
                    for k in self._scan_keys(_key(base), _key(head + 1))}
        out = []
        for h in range(base, head + 1):
            if h not in have:
                out.append(h)
                if len(out) >= limit:
                    break
        return out

    def count(self) -> int:
        with self._lock:
            return len(self._scan_keys(_PREFIX, _PREFIX + b"\xff"))

    def prune(self, retain_height: int) -> int:
        """Delete certificates for heights < retain_height (the block
        pruner's discipline: strictly below retain is gone, at/above is
        kept). Returns the number pruned."""
        with self._lock:
            doomed = self._scan_keys(_PREFIX, _key(retain_height))
            if doomed:
                self.db.batch_set([(k, None) for k in doomed])
            return len(doomed)

    def close(self) -> None:
        self.db.close()
