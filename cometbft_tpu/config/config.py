"""Unified node configuration tree.

Reference: config/config.go:76-1445 — one Config struct with 12 sections,
per-section ValidateBasic, serialized to config.toml (config/toml.go) and
loaded with flag/env layering. Here: dataclass sections, tomllib loading,
a hand-rolled TOML writer (stdlib has no writer), and `crypto.backend`
as the TPU framework's addition (SURVEY §5.6).

Layout under the node home (config.go:208-236):
  config/config.toml            this file
  config/genesis.json           genesis doc
  config/node_key.json          p2p identity
  config/priv_validator_key.json
  data/priv_validator_state.json
  data/blockstore.db, data/state.db, data/evidence.db
  data/cs.wal/                  consensus WAL
"""

from __future__ import annotations

import os

try:
    import tomllib  # 3.11+
except ImportError:  # 3.10: the API-identical backport
    import tomli as tomllib
from dataclasses import dataclass, field, fields

from cometbft_tpu.consensus.config import ConsensusConfig
from cometbft_tpu.mempool.mempool import MempoolConfig


@dataclass
class BaseConfig:
    """config.go:76-206."""

    moniker: str = "anonymous"
    proxy_app: str = "kvstore"  # "kvstore", "noop", or "tcp://host:port"
    abci: str = "local"  # "local" | "socket"
    db_backend: str = "sqlite"  # "sqlite" | "memdb"
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "logfmt"  # "logfmt" | "json"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""  # remote signer listen addr
    node_key_file: str = "config/node_key.json"
    filter_peers: bool = False

    def validate_basic(self) -> None:
        if self.abci not in ("local", "socket"):
            raise ValueError(f"unknown abci transport {self.abci!r}")
        if self.db_backend not in ("sqlite", "memdb"):
            raise ValueError(f"unknown db_backend {self.db_backend!r}")
        if self.log_format not in ("logfmt", "json"):
            raise ValueError(f"unknown log_format {self.log_format!r} "
                             "(expected \"logfmt\" or \"json\")")


@dataclass
class CryptoConfig:
    """The TPU framework's addition (SURVEY §5.6, BASELINE.json): which
    backend verifies signature batches, and how the node survives the
    backend failing.

    Degradation semantics (`backend = "auto"` — see ops/dispatch.py): every
    batch rides the highest healthy rung of the TPU (Pallas) -> XLA -> CPU
    (exact host oracle) ladder. Transient device failures retry with capped
    exponential backoff + jitter; `breaker_failure_threshold` consecutive
    failed operations (or one permanent Mosaic failure) open a circuit
    breaker that routes ALL new batches to the CPU rung; every
    `breaker_cooldown` seconds the breaker half-opens and one probe batch
    re-tries the device — success closes the breaker and reclaims it.
    `backend = "cpu"` pins the CPU rung; `backend = "tpu"` still degrades
    to CPU on device failure (liveness beats placement) but never stops
    re-probing the device."""

    backend: str = "auto"  # "cpu" | "tpu" | "auto"
    # coalesce at most this many signatures into one device batch
    max_batch_size: int = 16384
    # --- global verify scheduler (sched/scheduler.py) ---
    # route ALL batch verification through the node-wide scheduler
    # (continuous batching: consensus flushes drain immediately and
    # coalesce queued sync/mempool work as filler). Off = the pre-
    # scheduler fragmented dispatch (each producer its own batch).
    scheduler: bool = True
    # cap on rows coalesced into one scheduler batch (groups never split)
    sched_max_lanes: int = 16384
    # flush deadlines per class: consensus is always 0 (inline drain);
    # sync/light/mempool work waits at most this long for a ride before
    # the deadline worker flushes it (light = the serving plane's fleet
    # bisections, sched/scheduler.py LIGHT)
    sched_sync_deadline: float = 0.002
    sched_light_deadline: float = 0.004
    sched_mempool_deadline: float = 0.010
    # mempool-class admission rejected past this many queued rows (also
    # rejected while consensus/sync backlog alone exceeds it)
    sched_queue_limit: int = 16384
    # any queued group older than this rides the next batch regardless
    # of class priority (starvation guard)
    sched_starvation_limit: float = 0.25
    # pre-trace the device bucket ladder at node boot (TPU backend only;
    # a cold Mosaic compile must not land mid-consensus-round). Rungs are
    # traced up to sched_warmup_max_lanes — each rung pays one compile
    # (tens of seconds cold on Mosaic), so the cap bounds boot time;
    # raise it toward sched_max_lanes on nodes serving huge valsets
    sched_warmup: bool = False
    sched_warmup_max_lanes: int = 2048
    # --- multi-chip verify mesh (parallel/mesh.py) ---
    # shard scheduler batches across all visible devices, each chip its
    # own fault domain (dedicated supervisor/breaker): a dead chip
    # shrinks the mesh instead of tripping the whole node onto the CPU
    # ladder; a healed chip is readmitted by the half-open re-probe
    mesh_enabled: bool = True
    # below this many devices the mesh stays inactive and the classic
    # single-chip dispatch path serves (2 = mesh only when there is a
    # second fault domain to shrink onto)
    mesh_min_devices: int = 2
    # placement policy: "class_aware" pins consensus batches to the
    # least-loaded chip (latency) and spreads sync/mempool (throughput);
    # "spread"/"pinned" force one behavior for every class
    mesh_placement: str = "class_aware"
    # --- reduced-send wire protocol (ops/residency.py) ---
    # keep the active validator set's decompressed coordinates resident
    # on device keyed by set hash: steady-state flushes send 2-byte
    # validator indices instead of key/coordinate material, and set
    # churn ships only the evict/insert delta. Off = every batch rides
    # the full-key digest-cache path (the pre-reduced-send protocol)
    wire_indexed_sends: bool = True
    # per-scheme device validator-table capacity in rows (320 B/row of
    # device memory; one row is reserved for the padding identity).
    # Must fit a uint16 index: [64, 65536]
    wire_table_rows: int = 16384
    # derive the ed25519 challenge k = SHA-512(R||A||M) mod L ON DEVICE
    # (ops/challenge.py): the wire carries only R/s plus per-lane
    # (prefix-id, suffix) descriptors against a resident prefix table
    # (~66-82 B/sig vs 98), with per-lane and whole-batch host-k
    # fallbacks that never change a verdict. Off = every batch ships
    # host-computed k words (the pre-device-challenge protocol)
    wire_device_challenge: bool = True
    # --- BLS12-381 aggregate-signature scheme (crypto/bls12381.py) ---
    # the third verify-plane scheme: 48 B G1 pubkeys, 96 B G2 sigs,
    # aggregate commit verify (one pairing-product check per commit) and
    # batched single-verify through the scheduler. Off = a BLS key
    # reaching the batch seam raises a LOUD ErrInvalidKey naming this
    # knob (never a silent CPU fallback — the light-proxy https rule)
    bls_enabled: bool = True
    # --- device-fault supervision (ops/dispatch.py DeviceSupervisor) ---
    # transient failures: retries per dispatch, with backoff doubling from
    # retry_backoff_base up to retry_backoff_cap (plus jitter)
    retry_max_attempts: int = 2
    retry_backoff_base: float = 0.05
    retry_backoff_cap: float = 1.0
    # consecutive failed operations before the breaker opens (a permanent
    # Mosaic failure opens it immediately)
    breaker_failure_threshold: int = 3
    # seconds the breaker stays open before a half-open re-probe
    breaker_cooldown: float = 30.0
    # wall-clock cap on any single device dispatch wait or device->host
    # fetch; a hung device fails the batch onto the CPU ladder instead of
    # stalling a consensus round. Generous by default: it must cover a
    # cold first-dispatch kernel compile, not just steady-state batches
    watchdog_timeout: float = 120.0
    # deterministic device-fault injection schedule (libs/chaos.py syntax,
    # e.g. "ed25519.dispatch=transient:3,pallas.trace=permanent");
    # test/e2e only — the CBFT_CHAOS env var overlays this
    chaos: str = ""

    def validate_basic(self) -> None:
        if self.backend not in ("cpu", "tpu", "auto"):
            raise ValueError(f"unknown crypto backend {self.backend!r}")
        if self.retry_max_attempts < 0:
            raise ValueError("retry_max_attempts cannot be negative")
        if self.retry_backoff_base < 0 or self.retry_backoff_cap < 0:
            raise ValueError("retry backoff values cannot be negative")
        if self.breaker_failure_threshold < 1:
            raise ValueError("breaker_failure_threshold must be >= 1")
        if self.breaker_cooldown < 0:
            raise ValueError("breaker_cooldown cannot be negative")
        if self.watchdog_timeout <= 0:
            raise ValueError("watchdog_timeout must be positive")
        if self.sched_max_lanes < 8:
            raise ValueError("sched_max_lanes must be >= 8")
        if (self.sched_sync_deadline < 0 or self.sched_light_deadline < 0
                or self.sched_mempool_deadline < 0):
            raise ValueError("scheduler deadlines cannot be negative")
        if self.sched_queue_limit < 1:
            raise ValueError("sched_queue_limit must be >= 1")
        if self.sched_starvation_limit < 0:
            raise ValueError("sched_starvation_limit cannot be negative")
        if self.sched_warmup_max_lanes < 8:
            raise ValueError("sched_warmup_max_lanes must be >= 8")
        if self.mesh_min_devices < 1:
            raise ValueError("mesh_min_devices must be >= 1")
        if self.mesh_placement not in ("class_aware", "spread", "pinned"):
            raise ValueError(
                f"unknown mesh_placement {self.mesh_placement!r} "
                "(expected \"class_aware\", \"spread\", or \"pinned\")")
        if not 64 <= self.wire_table_rows <= 65536:
            raise ValueError(
                "wire_table_rows must be in [64, 65536] (uint16 indices; "
                "one row reserved for the padding identity)")
        if self.chaos:
            from cometbft_tpu.libs import chaos as _chaos

            _chaos.parse_spec(self.chaos)  # raises ValueError on any part


@dataclass
class LightConfig:
    """The light-client serving plane (light/fleet.py — no reference
    analog): a witness-side verification service that coalesces many
    concurrent skipping-verification requests into shared verification
    futures, caches verified headers in a trust-period-bounded skip list,
    and streams verified headers to subscribed clients over the
    `light_subscribe` WS route. All knobs are fleet_* because the plain
    single-flight light client (light/client.py) needs none of them."""

    # serve the light_verify / light_subscribe routes (opt-in: the fleet
    # holds a verified-header cache and a head watcher task)
    fleet_enabled: bool = False
    # checkpoint skip-list cache capacity in headers (~2-5 KB/header for
    # small valsets; eviction drops the lowest non-anchor heights first)
    fleet_cache_capacity: int = 4096
    # skip-list fanout: heights divisible by fleet_skip_base^k live on
    # lane k, so nearest-checkpoint lookups walk O(log_base height) lanes
    fleet_skip_base: int = 16
    # seconds a cached checkpoint is served before it must be re-verified
    # (the light-client trusting period applied to the CACHE: an expired
    # entry is a miss, never a stale answer)
    fleet_trust_period: float = 168 * 3600.0
    # comma-separated witness RPC endpoints for divergence cross-checks;
    # empty = the fleet's own primary doubles as witness (a node serving
    # its own chain)
    fleet_witnesses: str = ""
    # concurrent UNIQUE verification requests before new ones are shed
    # with FleetSaturated (coalesced duplicates never count)
    fleet_max_inflight: int = 1024
    # streaming-subscriber bounds: per-client queued-header high water
    # (a subscriber this far behind is dropped — backpressure), total
    # headers a client may be sent before its subscription closes
    # (0 = unlimited), and the subscriber cap
    fleet_subscriber_queue: int = 64
    fleet_send_budget: int = 0
    fleet_max_subscribers: int = 10000
    # head-watcher poll cadence when no event bus feeds the fleet
    fleet_poll_interval: float = 0.25

    def validate_basic(self) -> None:
        if self.fleet_cache_capacity < 2:
            raise ValueError("fleet_cache_capacity must be >= 2 "
                             "(trust root + at least one checkpoint)")
        if self.fleet_skip_base < 2:
            raise ValueError("fleet_skip_base must be >= 2")
        if self.fleet_trust_period <= 0:
            raise ValueError("fleet_trust_period must be positive")
        if self.fleet_max_inflight < 1:
            raise ValueError("fleet_max_inflight must be >= 1")
        if self.fleet_subscriber_queue < 1:
            raise ValueError("fleet_subscriber_queue must be >= 1")
        if self.fleet_send_budget < 0:
            raise ValueError("fleet_send_budget cannot be negative")
        if self.fleet_max_subscribers < 1:
            raise ValueError("fleet_max_subscribers must be >= 1")
        if self.fleet_poll_interval <= 0:
            raise ValueError("fleet_poll_interval must be positive")


@dataclass
class RPCConfig:
    """config.go:392-576."""

    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: list[str] = field(default_factory=list)
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit: float = 10.0
    max_body_bytes: int = 1_000_000
    max_header_bytes: int = 1 << 20
    pprof_laddr: str = ""
    # expose the operator control routes (dial_seeds/dial_peers/
    # unsafe_flush_mempool/unsafe_disconnect_peers; config.go Unsafe)
    unsafe: bool = False
    # overload guard (libs/overload.py, no reference analog): bounded
    # per-route-class in-flight budgets — excess requests wait out the
    # queue deadline then shed with -32005 + a retry-after hint. 0
    # disables a class's budget. Control routes are always exempt.
    overload_read_inflight: int = 256
    overload_write_inflight: int = 64
    overload_queue_timeout: float = 0.05
    # a client that stops draining its socket gets this long before the
    # server abandons the response and closes the connection
    slow_client_timeout: float = 10.0

    def validate_basic(self) -> None:
        if self.max_open_connections < 0:
            raise ValueError("max_open_connections cannot be negative")
        if self.timeout_broadcast_tx_commit <= 0:
            raise ValueError("timeout_broadcast_tx_commit must be positive")
        if self.overload_read_inflight < 0 or self.overload_write_inflight < 0:
            raise ValueError("overload in-flight budgets cannot be negative")
        if self.overload_queue_timeout < 0:
            raise ValueError("overload_queue_timeout cannot be negative")
        if self.slow_client_timeout <= 0:
            raise ValueError("slow_client_timeout must be positive")


@dataclass
class P2PConfig:
    """config.go:592-810."""

    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""  # comma-separated id@host:port
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_timeout: float = 0.1
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    seed_mode: bool = False
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0
    # fault injection for soak testing (config.go:739-740 TestFuzz +
    # FuzzConnConfig; knobs flattened instead of a subtable). Mode "drop"
    # mirrors the reference FuzzModeDrop (drops + conn kills + delays);
    # "delay" is latency-only (FuzzModeDelay)
    test_fuzz: bool = False
    test_fuzz_mode: str = "drop"  # "drop" | "delay"
    test_fuzz_prob_drop_rw: float = 0.01
    test_fuzz_prob_drop_conn: float = 0.003
    test_fuzz_prob_sleep: float = 0.01
    test_fuzz_max_delay: float = 0.05
    # deterministic-ish network-fault schedule armed at boot
    # (p2p/netchaos.py syntax: latency/jitter/drop/dup/reorder/bandwidth/
    # partition); test/e2e only — CBFT_NET_CHAOS overlays this
    chaos: str = ""
    # wire-plane metrics cardinality cap (libs/metrics.P2PMetrics): how
    # many distinct peers get their own label on the per-peer Prometheus
    # series before later peers fold into peer="other" — bounds the
    # exposition on a large-fleet node
    metrics_peer_cap: int = 32
    # misbehavior scoring / ban ledger (p2p/switch.py PeerScorer):
    # misbehavior score that triggers a ban, the first-offense ban window,
    # its cap as repeat offenses double it, and the score decay half-life
    ban_score_threshold: float = 3.0
    ban_duration: float = 60.0
    ban_max_duration: float = 3600.0
    ban_score_half_life: float = 120.0
    # discovery-plane diversity (p2p/pex/reactor.py): outbound slots one
    # /16 netblock may hold (0 = auto: half the outbound budget, min 2)
    # and how often ensure-peers wakes to fill the outbound set
    max_outbound_per_group: int = 0
    pex_ensure_interval: float = 30.0

    def validate_basic(self) -> None:
        if self.max_num_inbound_peers < 0 or self.max_num_outbound_peers < 0:
            raise ValueError("peer limits cannot be negative")
        if self.send_rate < 0 or self.recv_rate < 0:
            raise ValueError("rates cannot be negative")
        if self.test_fuzz_mode not in ("drop", "delay"):
            raise ValueError(f"unknown test_fuzz_mode {self.test_fuzz_mode!r}")
        for name in ("test_fuzz_prob_drop_rw", "test_fuzz_prob_drop_conn",
                     "test_fuzz_prob_sleep"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be a probability, got {v}")
        if self.test_fuzz_max_delay < 0:
            raise ValueError("test_fuzz_max_delay cannot be negative")
        if self.metrics_peer_cap < 0:
            raise ValueError("metrics_peer_cap cannot be negative")
        if self.ban_score_threshold <= 0:
            raise ValueError("ban_score_threshold must be positive")
        if self.ban_duration < 0 or self.ban_max_duration < 0:
            raise ValueError("ban durations cannot be negative")
        if self.ban_score_half_life <= 0:
            raise ValueError("ban_score_half_life must be positive")
        if self.max_outbound_per_group < 0:
            raise ValueError("max_outbound_per_group cannot be negative")
        if self.pex_ensure_interval <= 0:
            raise ValueError("pex_ensure_interval must be positive")
        if self.chaos:
            from cometbft_tpu.p2p import netchaos as _netchaos

            _netchaos.parse_spec(self.chaos)  # raises ValueError on any part

    def persistent_peer_list(self) -> list[str]:
        return [p.strip() for p in self.persistent_peers.split(",") if p.strip()]

    def seed_list(self) -> list[str]:
        return [p.strip() for p in self.seeds.split(",") if p.strip()]


@dataclass
class BlockSyncConfig:
    """config.go:1064-1086."""

    enable: bool = True
    version: str = "v0"

    def validate_basic(self) -> None:
        if self.version != "v0":
            raise ValueError(f"unknown blocksync version {self.version!r}")


@dataclass
class StateSyncConfig:
    """config.go:966-1062."""

    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: float = 168 * 3600.0  # 1 week
    discovery_time: float = 15.0
    chunk_request_timeout: float = 10.0

    def validate_basic(self) -> None:
        if not self.enable:
            return
        if len(self.rpc_servers) < 2:
            raise ValueError("statesync requires >=2 rpc_servers")
        if self.trust_height <= 0:
            raise ValueError("statesync requires trust_height > 0")
        if not self.trust_hash:
            raise ValueError("statesync requires trust_hash")


@dataclass
class StorageConfig:
    """config.go:1240-1265, plus the storage-fault resilience plane
    (libs/diskchaos, store/db hardening).

    Durability semantics: `synchronous` is the sqlite pragma applied to
    EVERY connection of the block/state/evidence/index DBs — NORMAL
    (default) fsyncs the sqlite WAL at checkpoints (power loss can drop
    the tail of recently-committed transactions, never corrupt; the
    consensus WAL EndHeight fsync is what guards committed heights),
    FULL fsyncs every commit. The privval sign-state is ALWAYS
    FULL-grade (fsynced temp file + durable rename) regardless of this
    knob — it is the one write whose loss enables a double-sign."""

    discard_abci_responses: bool = False
    # sqlite synchronous pragma for the node's kv stores: NORMAL | FULL
    synchronous: str = "NORMAL"
    # CRC32-guard every block-store and state-store record value: a
    # rotted bit surfaces as a typed ErrCorruptValue naming the repair
    # path instead of a mis-parsed block. The guard changes the on-disk
    # value format — a store written WITHOUT it must be read with
    # checksum=false (or re-synced onto a fresh home); there is no
    # mixed-format mode, by design: "maybe legacy" reads would give a
    # rotted tag byte a way to smuggle a raw mis-parse past the guard
    checksum: bool = True
    # deterministic disk-fault schedule (libs/diskchaos.py syntax, e.g.
    # "wal.fsync=fsync_lie:1,db.read=bitrot"); test/e2e only — the
    # CBFT_DISK_CHAOS env var overlays this
    chaos: str = ""

    def validate_basic(self) -> None:
        if self.synchronous not in ("NORMAL", "FULL"):
            raise ValueError(
                f"unknown storage.synchronous {self.synchronous!r} "
                "(expected \"NORMAL\" or \"FULL\")")
        if self.chaos:
            from cometbft_tpu.libs import diskchaos as _diskchaos

            _diskchaos.parse_spec(self.chaos)  # raises ValueError on any part


@dataclass
class CertConfig:
    """Commit-certificate plane (cert/ — no reference analog): succinct
    finality certificates produced once at commit finalize, verified
    with ONE pairing-product check, served over RPC and a negotiated
    blocksync channel. Only all-BLS validator sets certify; on any
    other set the plane stays idle and every consumer keeps the classic
    per-vote path."""

    enabled: bool = True
    # certify historical heights [store base, head] in the background
    backfill: bool = True
    # heights per backfill planning batch (bounds the per-pass work)
    backfill_batch: int = 32
    # store-poll cadence (seconds) for nodes WITHOUT an event bus, and
    # the backfill worker's idle sleep
    poll_interval: float = 1.0
    # serve certificates to peers on the negotiated 0x25 channel
    serve: bool = True

    def validate_basic(self) -> None:
        if self.backfill_batch < 1:
            raise ValueError("cert.backfill_batch must be >= 1")
        if self.poll_interval <= 0:
            raise ValueError("cert.poll_interval must be positive")


@dataclass
class GRPCConfig:
    """config.go:520-543 GRPCConfig: the gRPC service surface. Empty
    addresses disable the listeners. The pruning (data-companion) service
    is only ever served on the privileged listener."""

    laddr: str = ""
    privileged_laddr: str = ""


@dataclass
class TxIndexConfig:
    """config.go:1279-1302."""

    # "kv": query-language search via RPC; "sql": write-only relational
    # sink (the psql-sink analog — SQL consumers query the DB directly,
    # tx_search/block_search disabled, as with the reference's psql sink);
    # "null": no indexing
    indexer: str = "kv"

    def validate_basic(self) -> None:
        if self.indexer not in ("kv", "null", "sql"):
            raise ValueError(f"unknown indexer {self.indexer!r}")


@dataclass
class InstrumentationConfig:
    """config.go:1333-1378, plus the verify-plane flight recorder
    (libs/trace.py): span tracing with per-batch wall-time attribution,
    Chrome-trace export over the `trace_dump` RPC route, and a slow-batch
    capture ring. Near-zero cost when `tracing` is off (tier-1 asserts
    <3% on a 1k-row verify). The CBFT_TRACE env var ("1"/"0") overlays
    `tracing` at node boot, the same pattern as CBFT_CHAOS."""

    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "cometbft"
    # --- flight recorder (libs/trace.py) ---
    tracing: bool = False
    # bounded span ring: oldest finished spans overwritten past this
    trace_buffer_spans: int = 65536
    # a root span (sched.verify drain, sync.window, consensus.height,
    # mempool.admit) slower than this keeps its FULL span tree in the
    # slow capture ring for post-mortem; < 0 disables capture
    trace_slow_ms: float = 250.0
    # how many slow captures are retained (FIFO)
    trace_slow_captures: int = 32
    # --- consensus heightline (consensus/timeline.py) ---
    # per-height critical-path event ring + clock-skew model; the
    # CBFT_TIMELINE env var overlays `timeline` at node boot
    timeline: bool = False
    # bounded ring: how many recent heights keep their event records
    timeline_heights: int = 64
    # a height whose wall time exceeds this auto-captures a postmortem
    # bundle (timeline + span captures + gossip/wire/scheduler context),
    # served by the `postmortems` RPC route; <= 0 disables capture
    height_slow_ms: float = 0.0
    # how many postmortem bundles are retained (FIFO)
    postmortem_captures: int = 8

    def validate_basic(self) -> None:
        if self.trace_buffer_spans < 1:
            raise ValueError("trace_buffer_spans must be >= 1")
        if self.trace_slow_captures < 1:
            raise ValueError("trace_slow_captures must be >= 1")
        if self.timeline_heights < 1:
            raise ValueError("timeline_heights must be >= 1")
        if self.postmortem_captures < 1:
            raise ValueError("postmortem_captures must be >= 1")


@dataclass
class WALConfig:
    """Consensus WAL file knobs (reference: part of ConsensusConfig,
    config.go:1096 WalPath + libs/autofile group limits)."""

    wal_dir: str = "data/cs.wal"
    segment_size_bytes: int = 8 << 20  # rotate segments at 8 MB
    max_segments: int = 32


@dataclass
class Config:
    """The root tree (config.go:76)."""

    base: BaseConfig = field(default_factory=BaseConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)
    light: LightConfig = field(default_factory=LightConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    grpc: GRPCConfig = field(default_factory=GRPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    wal: WALConfig = field(default_factory=WALConfig)
    block_sync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    state_sync: StateSyncConfig = field(default_factory=StateSyncConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    cert: CertConfig = field(default_factory=CertConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)
    home: str = "."  # set at load time, not serialized

    def validate_basic(self) -> None:
        """config.go:318 ValidateBasic: every section that defines one."""
        for section in (self.base, self.crypto, self.light, self.rpc,
                        self.p2p, self.mempool, self.block_sync,
                        self.state_sync, self.storage, self.tx_index,
                        self.cert, self.instrumentation):
            section.validate_basic()

    # ------------------------------------------------------------ paths

    def _abs(self, rel: str) -> str:
        return rel if os.path.isabs(rel) else os.path.join(self.home, rel)

    def genesis_path(self) -> str:
        return self._abs(self.base.genesis_file)

    def node_key_path(self) -> str:
        return self._abs(self.base.node_key_file)

    def priv_validator_key_path(self) -> str:
        return self._abs(self.base.priv_validator_key_file)

    def priv_validator_state_path(self) -> str:
        return self._abs(self.base.priv_validator_state_file)

    def db_path(self, name: str) -> str:
        return self._abs(os.path.join(self.base.db_dir, f"{name}.db"))

    def wal_path(self) -> str:
        return self._abs(self.wal.wal_dir)

    # ------------------------------------------------------------- TOML

    _SECTIONS = (
        ("base", ""),  # base fields live at top level, like the reference
        ("crypto", "crypto"),
        ("light", "light"),
        ("rpc", "rpc"),
        ("grpc", "grpc"),
        ("p2p", "p2p"),
        ("mempool", "mempool"),
        ("consensus", "consensus"),
        ("wal", "wal"),
        ("block_sync", "blocksync"),
        ("state_sync", "statesync"),
        ("storage", "storage"),
        ("tx_index", "tx_index"),
        ("cert", "cert"),
        ("instrumentation", "instrumentation"),
    )

    def to_toml(self) -> str:
        out = ["# cometbft_tpu node configuration\n"]
        for attr, section in self._SECTIONS:
            obj = getattr(self, attr)
            if section:
                out.append(f"\n[{section}]\n")
            for f in fields(obj):
                out.append(f"{f.name} = {_toml_value(getattr(obj, f.name))}\n")
        return "".join(out)

    def save(self, path: str | None = None) -> str:
        path = path or os.path.join(self.home, "config", "config.toml")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_toml())
        # durable rename (libs/diskio): the e2e runner rewrites configs
        # between respawns — a half-landed config after a crash-storm
        # kill would boot the node with default knobs
        from cometbft_tpu.libs import diskio

        diskio.durable_replace(tmp, path)
        return path

    @classmethod
    def load(cls, home: str) -> "Config":
        """Load config/config.toml under home; missing keys keep defaults
        (the reference's viper layering, minus env/flags which the CLI
        applies on top)."""
        cfg = cls(home=home)
        path = os.path.join(home, "config", "config.toml")
        if not os.path.exists(path):
            return cfg
        with open(path, "rb") as f:
            doc = tomllib.load(f)
        for attr, section in cls._SECTIONS:
            obj = getattr(cfg, attr)
            src = doc if not section else doc.get(section, {})
            for fld in fields(obj):
                if fld.name in src:
                    setattr(obj, fld.name, src[fld.name])
        return cfg


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise TypeError(f"cannot TOML-encode {type(v)}")


def default_config(home: str = ".") -> Config:
    return Config(home=home)


def test_config(home: str = ".") -> Config:
    """Millisecond-scale timeouts (reference config.TestConfig)."""
    from cometbft_tpu.consensus.config import test_consensus_config

    cfg = Config(home=home, consensus=test_consensus_config())
    cfg.base.db_backend = "memdb"
    cfg.crypto.backend = "cpu"
    cfg.p2p.send_rate = 50_000_000
    cfg.p2p.recv_rate = 50_000_000
    return cfg
