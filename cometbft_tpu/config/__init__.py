from cometbft_tpu.config.config import (
    BaseConfig,
    BlockSyncConfig,
    Config,
    InstrumentationConfig,
    LightConfig,
    P2PConfig,
    RPCConfig,
    StateSyncConfig,
    StorageConfig,
    TxIndexConfig,
    default_config,
    test_config,
)

__all__ = [
    "BaseConfig",
    "BlockSyncConfig",
    "Config",
    "InstrumentationConfig",
    "LightConfig",
    "P2PConfig",
    "RPCConfig",
    "StateSyncConfig",
    "StorageConfig",
    "TxIndexConfig",
    "default_config",
    "test_config",
]
