"""Small shared codecs and helpers (no domain logic)."""
