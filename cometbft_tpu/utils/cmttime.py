"""Canonical time handling.

The reference canonicalizes all signed timestamps to UTC with monotonic clock
reading stripped (reference: types/canonical.go:84-90, libs/time). We carry
timestamps as (seconds, nanos) pairs — protobuf Timestamp semantics — because
Python datetimes cannot represent nanoseconds.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from datetime import datetime, timezone


@dataclass(frozen=True, order=True)
class Timestamp:
    """Nanosecond-precision UTC instant. nanos in [0, 1e9)."""

    seconds: int = 0
    nanos: int = 0

    def __post_init__(self) -> None:
        if not 0 <= self.nanos < 1_000_000_000:
            # normalize (frozen dataclass: use object.__setattr__)
            total = self.seconds * 1_000_000_000 + self.nanos
            object.__setattr__(self, "seconds", total // 1_000_000_000)
            object.__setattr__(self, "nanos", total % 1_000_000_000)

    @classmethod
    def now(cls) -> "Timestamp":
        ns = _time.time_ns()
        return cls(ns // 1_000_000_000, ns % 1_000_000_000)

    @classmethod
    def zero(cls) -> "Timestamp":
        return cls(0, 0)

    def is_zero(self) -> bool:
        return self.seconds == 0 and self.nanos == 0

    def unix_ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos

    def add_ns(self, ns: int) -> "Timestamp":
        return Timestamp(0, self.unix_ns() + ns)

    def add_seconds(self, s: float) -> "Timestamp":
        return self.add_ns(int(s * 1e9))

    def rfc3339(self) -> str:
        """RFC3339Nano formatting (reference TimeFormat, types/canonical.go:13)."""
        dt = datetime.fromtimestamp(self.seconds, tz=timezone.utc)
        base = dt.strftime("%Y-%m-%dT%H:%M:%S")
        if self.nanos:
            frac = f"{self.nanos:09d}".rstrip("0")
            return f"{base}.{frac}Z"
        return base + "Z"

    def __str__(self) -> str:
        return self.rfc3339()


def now() -> Timestamp:
    return Timestamp.now()


def canonical_now_ms() -> Timestamp:
    """Millisecond-truncated now — vote timestamps in tests."""
    ns = _time.time_ns()
    ms = ns // 1_000_000
    return Timestamp(ms // 1000, (ms % 1000) * 1_000_000)
