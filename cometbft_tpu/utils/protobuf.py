"""Minimal protobuf wire-format codec.

The framework's canonical sign-bytes (CanonicalVote / CanonicalProposal /
CanonicalVoteExtension) must be byte-exact with the reference's gogoproto
output (reference: types/canonical.go, proto/tendermint/types/canonical.proto,
libs/protoio/writer.go:93 MarshalDelimited). Rather than depending on
generated bindings, this module hand-rolls the handful of wire rules gogoproto
uses, in ascending-field order, with proto3 omit-if-zero semantics and
gogoproto's always-emit semantics for non-nullable embedded messages.

Wire types: 0=varint, 1=fixed64, 2=length-delimited, 5=fixed32.
"""

from __future__ import annotations

import struct

_U64_MASK = (1 << 64) - 1


def encode_uvarint(v: int) -> bytes:
    """Unsigned LEB128 varint."""
    if v < 0:
        raise ValueError("uvarint of negative value")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_varint_i64(v: int) -> bytes:
    """Protobuf int64/int32 varint: negative values as 64-bit two's complement."""
    return encode_uvarint(v & _U64_MASK)


def encode_zigzag(v: int) -> bytes:
    """sint64 zigzag varint."""
    return encode_uvarint((v << 1) ^ (v >> 63))


def decode_uvarint(data: bytes, pos: int = 0) -> tuple[int, int]:
    """Return (value, new_pos). Raises ValueError on truncation/overlong."""
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        if shift == 63 and b > 1:
            # 10th byte may only carry the final bit (Go binary.Uvarint
            # overflow rule) — reject values >= 2^64
            raise ValueError("varint overflows uint64")
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def decode_varint_i64(data: bytes, pos: int = 0) -> tuple[int, int]:
    v, pos = decode_uvarint(data, pos)
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos


class Writer:
    """Appends protobuf fields in the order methods are called.

    Callers are responsible for ascending field order (matching gogoproto's
    MarshalToSizedBuffer output, e.g. canonical.pb.go CanonicalVote)."""

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def _tag(self, field: int, wire: int) -> None:
        self.buf += encode_uvarint(field << 3 | wire)

    # -- scalar fields (proto3: omitted when zero unless always=True) --

    def uvarint(self, field: int, v: int, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 0)
            self.buf += encode_uvarint(v)
        return self

    def varint_i64(self, field: int, v: int, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 0)
            self.buf += encode_varint_i64(v)
        return self

    def bool(self, field: int, v: bool, always: bool = False) -> "Writer":
        return self.uvarint(field, 1 if v else 0, always)

    def sfixed64(self, field: int, v: int, always: bool = False) -> "Writer":
        """Little-endian two's-complement 8 bytes (canonical height/round)."""
        if v or always:
            self._tag(field, 1)
            self.buf += struct.pack("<q", v)
        return self

    def fixed64(self, field: int, v: int, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 1)
            self.buf += struct.pack("<Q", v)
        return self

    def sfixed32(self, field: int, v: int, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 5)
            self.buf += struct.pack("<i", v)
        return self

    def double(self, field: int, v: float, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 1)
            self.buf += struct.pack("<d", v)
        return self

    # -- length-delimited fields --

    def bytes(self, field: int, v: bytes, always: bool = False) -> "Writer":
        if v or always:
            self._tag(field, 2)
            self.buf += encode_uvarint(len(v))
            self.buf += v
        return self

    def string(self, field: int, v: str, always: bool = False) -> "Writer":
        return self.bytes(field, v.encode("utf-8"), always)

    def message(self, field: int, body: "bytes | Writer | None",
                always: bool = False) -> "Writer":
        """Embedded message. None → omitted (nullable); empty body with
        always=True → tag + zero length (gogoproto non-nullable)."""
        if body is None:
            if always:
                raise ValueError("always-emit message field got None")
            return self
        if isinstance(body, Writer):
            body = bytes(body.buf)
        if body or always:
            self.bytes(field, body, always=True)
        return self

    def output(self) -> bytes:
        return bytes(self.buf)


def marshal_delimited(body: bytes) -> bytes:
    """Varint length-prefix, matching libs/protoio MarshalDelimited
    (reference: libs/protoio/writer.go:93) used for all sign-bytes."""
    return encode_uvarint(len(body)) + body


def unmarshal_delimited(data: bytes, pos: int = 0) -> tuple[bytes, int]:
    n, pos = decode_uvarint(data, pos)
    if pos + n > len(data):
        raise ValueError("truncated delimited message")
    return data[pos:pos + n], pos + n


class Reader:
    """Field-at-a-time protobuf reader for the wire messages we decode
    (privval socket, WAL records, p2p envelopes)."""

    __slots__ = ("data", "pos", "end")

    def __init__(self, data: bytes, pos: int = 0, end: int | None = None):
        self.data = data
        self.pos = pos
        self.end = len(data) if end is None else end

    def at_end(self) -> bool:
        return self.pos >= self.end

    def read_tag(self) -> tuple[int, int]:
        v, self.pos = decode_uvarint(self.data, self.pos)
        return v >> 3, v & 7

    def read_uvarint(self) -> int:
        v, self.pos = decode_uvarint(self.data, self.pos)
        return v

    def read_varint_i64(self) -> int:
        v, self.pos = decode_varint_i64(self.data, self.pos)
        return v

    def read_sfixed64(self) -> int:
        v = struct.unpack_from("<q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def read_fixed64(self) -> int:
        v = struct.unpack_from("<Q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def read_sfixed32(self) -> int:
        v = struct.unpack_from("<i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def read_bytes(self) -> bytes:
        n = self.read_uvarint()
        if self.pos + n > self.end:
            raise ValueError("truncated bytes field")
        v = self.data[self.pos:self.pos + n]
        self.pos += n
        return bytes(v)

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_message(self) -> "Reader":
        body = self.read_bytes()
        return Reader(body)

    def read_timestamp(self) -> tuple[int, int]:
        """Parse an embedded google.protobuf.Timestamp field value that was
        written by timestamp_bytes(): returns (seconds, nanos)."""
        tr = self.read_message()
        seconds = nanos = 0
        while not tr.at_end():
            f, w = tr.read_tag()
            if f == 1:
                seconds = tr.read_varint_i64()
            elif f == 2:
                nanos = tr.read_varint_i64()
            else:
                tr.skip(w)
        return seconds, nanos

    def skip(self, wire: int) -> None:
        if wire == 0:
            self.read_uvarint()
        elif wire == 1:
            self.pos += 8
        elif wire == 2:
            self.read_bytes()
        elif wire == 5:
            self.pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def timestamp_bytes(seconds: int, nanos: int) -> bytes:
    """google.protobuf.Timestamp encoding (gogoproto StdTimeMarshal):
    field 1 seconds int64 varint, field 2 nanos int32 varint, both
    omitted when zero."""
    w = Writer()
    w.varint_i64(1, seconds)
    w.varint_i64(2, nanos)
    return w.output()
