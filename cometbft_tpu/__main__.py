import sys

from cometbft_tpu.cmd import main

sys.exit(main())
