/* Lane-vectorized batch hash cores for the host staging fast path:
 *
 *   keccak_many  — N independent Keccak-f[1600] states advanced under one
 *                  permutation call, 8 states per SIMD vector (the batch
 *                  STROBE transcript in crypto/sr25519_math.py drives this
 *                  from numpy (N, 25)-uint64 state arrays).
 *   sha512_many  — multi-buffer SHA-512: N pre-padded messages of the same
 *                  block count compressed 8 per vector (the ed25519
 *                  challenge path in ops/hashvec.py).
 *
 * Both are written with GCC generic vector extensions (no intrinsics): the
 * scalar reference algorithm on an 8-lane uint64 vector type. The compiler
 * flag ladder in ops/hashvec.py picks the widest ISA /proc/cpuinfo
 * advertises (AVX-512 runs one vector per instruction; AVX2 and baseline
 * split it) — measured on the dev box: 92 ns/row/permutation at AVX-512 vs
 * 2.2 us for the scalar strobe.c path and ~17 us for the numpy fallback.
 *
 * Bit-for-bit equivalence with hashlib.sha512 and the pure-Python
 * keccak_f1600 is asserted by tests/test_hashvec.py (golden + fuzz).
 */

#include <stdint.h>
#include <string.h>

#define LANES 8
typedef uint64_t vec __attribute__((vector_size(8 * LANES)));

/* ----------------------------------------------------------- keccak-f1600 */

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static const int ROTC[5][5] = {{0, 36, 3, 41, 18},
                               {1, 44, 10, 45, 2},
                               {62, 6, 43, 15, 61},
                               {28, 55, 25, 21, 56},
                               {27, 20, 39, 8, 14}};

/* n is a compile-time constant at every use (unrolled loops); the ternary
 * folds away and guards the n==0 lane against the UB 64-bit shift */
#define ROTV(v, n) ((n) ? (((v) << (n)) | ((v) >> (64 - (n)))) : (v))

static void keccakf_v(vec a[25]) { /* lane i = x + 5*y, as strobe.c */
  vec b[25], c[5], d[5];
  for (int r = 0; r < 24; r++) {
    for (int x = 0; x < 5; x++)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ ROTV(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x + 5 * y] ^= d[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = ROTV(a[x + 5 * y], ROTC[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) &
                                       b[(x + 2) % 5 + 5 * y]);
    a[0] ^= RC[r];
  }
}

/* states: n rows of 25 little-endian uint64 lanes, row-major, in place */
void keccak_many(uint64_t *states, long n) {
  long g = 0;
  for (; g < n; g += LANES) {
    int live = (n - g) < LANES ? (int)(n - g) : LANES;
    vec a[25];
    for (int i = 0; i < 25; i++) {
      for (int j = 0; j < live; j++) a[i][j] = states[(g + j) * 25 + i];
      for (int j = live; j < LANES; j++) a[i][j] = 0;
    }
    keccakf_v(a);
    for (int i = 0; i < 25; i++)
      for (int j = 0; j < live; j++) states[(g + j) * 25 + i] = a[i][j];
  }
}

/* --------------------------------------------------- SHA-512 multi-buffer */

static const uint64_t KK[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static const uint64_t H0[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

#define ROTR(v, n) (((v) >> (n)) | ((v) << (64 - (n))))

/* blocks: n rows of nb*128 bytes, pre-padded per FIPS 180-4 by the caller;
 * out: n rows of 64 digest bytes (big-endian words, the hashlib layout) */
void sha512_many(const uint8_t *blocks, long n, long nb, uint8_t *out) {
  for (long g = 0; g < n; g += LANES) {
    int live = (n - g) < LANES ? (int)(n - g) : LANES;
    vec h[8];
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < LANES; j++) h[i][j] = H0[i];
    for (long bi = 0; bi < nb; bi++) {
      vec w[16];
      for (int t = 0; t < 16; t++)
        for (int j = 0; j < LANES; j++) {
          long row = g + (j < live ? j : 0); /* dead lanes mirror row 0 */
          uint64_t x;
          memcpy(&x, blocks + (row * nb + bi) * 128 + t * 8, 8);
          w[t][j] = __builtin_bswap64(x);
        }
      vec a = h[0], b = h[1], c = h[2], d = h[3];
      vec e = h[4], f = h[5], gg = h[6], hh = h[7];
      for (int t = 0; t < 80; t++) {
        if (t >= 16) {
          vec w15 = w[(t - 15) & 15], w2 = w[(t - 2) & 15];
          vec s0 = ROTR(w15, 1) ^ ROTR(w15, 8) ^ (w15 >> 7);
          vec s1 = ROTR(w2, 19) ^ ROTR(w2, 61) ^ (w2 >> 6);
          w[t & 15] = w[t & 15] + s0 + w[(t - 7) & 15] + s1;
        }
        vec S1 = ROTR(e, 14) ^ ROTR(e, 18) ^ ROTR(e, 41);
        vec ch = gg ^ (e & (f ^ gg));
        vec t1 = hh + S1 + ch + KK[t] + w[t & 15];
        vec S0 = ROTR(a, 28) ^ ROTR(a, 34) ^ ROTR(a, 39);
        vec mj = (a & (b | c)) | (b & c);
        vec t2 = S0 + mj;
        hh = gg; gg = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
      }
      h[0] += a; h[1] += b; h[2] += c; h[3] += d;
      h[4] += e; h[5] += f; h[6] += gg; h[7] += hh;
    }
    for (int j = 0; j < live; j++)
      for (int i = 0; i < 8; i++) {
        uint64_t x = __builtin_bswap64(h[i][j]);
        memcpy(out + (g + j) * 64 + i * 8, &x, 8);
      }
  }
}

/* ------------------------------------------- Barrett reduction mod L
 * k = digest mod L (the ed25519 group order) for N 512-bit little-endian
 * values — the wide-reduction step of both schemes' challenge pipelines.
 * HAC Algorithm 14.42 with b = 2^64, k = 4: q3 = floor(floor(x/b^3)*mu /
 * b^5), r = (x - q3*L) mod b^5, then at most two conditional subtractions.
 * Bit-for-bit equal to Python's int.from_bytes(d, "little") % L
 * (fuzzed in tests/test_hashvec.py). */

typedef unsigned __int128 u128;

static const uint64_t MU5[5] = {/* floor(2^512 / L), 261 bits */
    0xed9ce5a30a2c131bULL, 0x2106215d086329a7ULL, 0xffffffffffffffebULL,
    0xffffffffffffffffULL, 0x000000000000000fULL};
static const uint64_t L5[5] = {
    0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0000000000000000ULL,
    0x1000000000000000ULL, 0x0000000000000000ULL};

/* in: n rows of 64 little-endian bytes; out: n rows of 32 bytes (mod L) */
void reduce512_mod_l_many(const uint8_t *in, long n, uint8_t *out) {
  for (long row = 0; row < n; row++) {
    uint64_t x[8];
    memcpy(x, in + row * 64, 64);
    const uint64_t *q1 = x + 3; /* floor(x / b^3): 5 limbs */
    uint64_t q2[10] = {0};
    for (int i = 0; i < 5; i++) { /* q2 = q1 * mu */
      u128 c = 0;
      for (int j = 0; j < 5; j++) {
        u128 s = (u128)q1[i] * MU5[j] + q2[i + j] + c;
        q2[i + j] = (uint64_t)s;
        c = s >> 64;
      }
      q2[i + 5] = (uint64_t)c;
    }
    const uint64_t *q3 = q2 + 5; /* floor(q2 / b^5): 5 limbs */
    uint64_t r2[5] = {0};
    for (int i = 0; i < 5; i++) { /* r2 = q3 * L mod b^5 */
      u128 c = 0;
      for (int j = 0; j + i < 5; j++) {
        u128 s = (u128)q3[i] * L5[j] + r2[i + j] + c;
        r2[i + j] = (uint64_t)s;
        c = s >> 64;
      }
    }
    uint64_t r[5];
    uint64_t borrow = 0;
    for (int j = 0; j < 5; j++) { /* r = x - r2 mod b^5 */
      u128 d = (u128)x[j] - r2[j] - borrow;
      r[j] = (uint64_t)d;
      borrow = (uint64_t)(d >> 64) & 1;
    }
    for (int pass = 0; pass < 2; pass++) { /* r < 3L: subtract L <= twice */
      uint64_t t[5];
      borrow = 0;
      for (int j = 0; j < 5; j++) {
        u128 d = (u128)r[j] - L5[j] - borrow;
        t[j] = (uint64_t)d;
        borrow = (uint64_t)(d >> 64) & 1;
      }
      if (!borrow) memcpy(r, t, sizeof(r));
    }
    memcpy(out + row * 32, r, 32);
  }
}
