"""Native (C) runtime components, built on demand with the system cc.

The reference leans on native code for its byte-crunching hot paths (Go
with assembly fast paths in curve25519-voi, merlin in Rust under
schnorrkel). This package holds the framework's equivalents: small C
libraries compiled once into the package directory and loaded via ctypes,
each with a pure-Python fallback so a missing toolchain degrades to slow,
never to broken.

Currently: strobe.c — the STROBE-128 duplex behind Merlin transcripts
(sr25519 signing/verification challenges) — and hashvec.c — the 8-lane
SIMD batch SHA-512 / Keccak-f[1600] / Barrett-mod-L cores behind the
staging fast path (ops/hashvec.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_failed: set[str] = set()
_loaded: dict[str, ctypes.CDLL] = {}


def load(name: str, cflags_ladder: tuple = (("-O2",),)) -> ctypes.CDLL | None:
    """Compile (if stale) and load lib `name` (from {name}.c). Returns None
    when no working C toolchain is available — callers keep their Python
    fallback.

    cflags_ladder: candidate optimization-flag tuples tried in order (the
    SIMD hash cores pass an ISA ladder like -mavx512f > -mavx2 > none and
    degrade gracefully on a compiler too old for the wider flags). A
    non-default ladder is part of the artifact's cache name: the ladder is
    derived from the RUNNING host's /proc/cpuinfo, so a .so baked into an
    image on a wider-ISA build host is never loaded on a narrower machine
    (which would SIGILL instead of degrading) — the narrower host sees a
    different name and rebuilds, or falls back to pure Python."""
    if name in _loaded:
        return _loaded[name]
    if name in _failed:
        return None
    src = os.path.join(_DIR, f"{name}.c")
    suffix = ""
    if cflags_ladder != (("-O2",),):
        import hashlib

        suffix = "." + hashlib.sha256(
            repr(cflags_ladder).encode()).hexdigest()[:8]
    so = os.path.join(_DIR, f"_{name}{suffix}.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            try:
                built = None
                for flags in cflags_ladder:
                    try:
                        subprocess.run(
                            ["cc", *flags, "-shared", "-fPIC", "-o", tmp, src],
                            check=True, capture_output=True, timeout=120)
                        built = flags
                        break
                    except subprocess.CalledProcessError:
                        continue
                if built is None:
                    raise RuntimeError(f"no cflags candidate built {name}")
                os.replace(tmp, so)  # atomic vs concurrent builders
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        lib = ctypes.CDLL(so)
    except Exception:  # noqa: BLE001 - no cc / sandboxed fs: fall back
        _failed.add(name)
        return None
    _loaded[name] = lib
    return lib
