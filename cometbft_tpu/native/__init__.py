"""Native (C) runtime components, built on demand with the system cc.

The reference leans on native code for its byte-crunching hot paths (Go
with assembly fast paths in curve25519-voi, merlin in Rust under
schnorrkel). This package holds the framework's equivalents: small C
libraries compiled once into the package directory and loaded via ctypes,
each with a pure-Python fallback so a missing toolchain degrades to slow,
never to broken.

Currently: strobe.c — the STROBE-128 duplex behind Merlin transcripts
(sr25519 signing/verification challenges).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_failed: set[str] = set()
_loaded: dict[str, ctypes.CDLL] = {}


def load(name: str) -> ctypes.CDLL | None:
    """Compile (if stale) and load lib `name` (from {name}.c). Returns None
    when no working C toolchain is available — callers keep their Python
    fallback."""
    if name in _loaded:
        return _loaded[name]
    if name in _failed:
        return None
    src = os.path.join(_DIR, f"{name}.c")
    so = os.path.join(_DIR, f"_{name}.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            try:
                subprocess.run(
                    ["cc", "-O2", "-shared", "-fPIC", "-o", tmp, src],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)  # atomic vs concurrent builders
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        lib = ctypes.CDLL(so)
    except Exception:  # noqa: BLE001 - no cc / sandboxed fs: fall back
        _failed.add(name)
        return None
    _loaded[name] = lib
    return lib
