/* STROBE-128 duplex core (the subset merlin uses: meta-AD, AD, PRF, KEY)
 * as a tiny C library behind ctypes — the native replacement for the
 * pure-Python Keccak in crypto/sr25519_math.py, whose ~1.4 ms per Merlin
 * challenge dominated mixed mega-commit verification wall time. Semantics
 * mirror the Python Strobe128 class byte-for-byte (cross-checked by
 * tests/test_sr25519.py transcript vectors).
 *
 * State layout (packed, 203 bytes, shared with Python as a raw buffer):
 *   [0..199]  keccak-f1600 state
 *   [200]     pos
 *   [201]     pos_begin
 *   [202]     cur_flags
 */

#include <stdint.h>
#include <string.h>

#define R_RATE 166 /* 1600/8 - 2*128/8 - 2 */

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static const int ROTC[5][5] = {{0, 36, 3, 41, 18},
                               {1, 44, 10, 45, 2},
                               {62, 6, 43, 15, 61},
                               {28, 55, 25, 21, 56},
                               {27, 20, 39, 8, 14}};

static inline uint64_t rotl(uint64_t v, int n) {
  return n ? (v << n) | (v >> (64 - n)) : v;
}

static void keccakf(uint64_t a[25]) { /* lane i = x + 5*y, little-endian */
  uint64_t b[25], c[5], d[5];
  for (int r = 0; r < 24; r++) {
    for (int x = 0; x < 5; x++)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x + 5 * y] ^= d[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(a[x + 5 * y], ROTC[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) &
                                       b[(x + 2) % 5 + 5 * y]);
    a[0] ^= RC[r];
  }
}

typedef struct {
  uint8_t st[200];
  uint8_t pos;
  uint8_t pos_begin;
  uint8_t cur_flags;
} strobe_t;

static void perm(strobe_t *s) {
  uint64_t lanes[25];
  memcpy(lanes, s->st, 200);
  keccakf(lanes);
  memcpy(s->st, lanes, 200);
}

static void run_f(strobe_t *s) {
  s->st[s->pos] ^= s->pos_begin;
  s->st[s->pos + 1] ^= 0x04;
  s->st[R_RATE + 1] ^= 0x80;
  perm(s);
  s->pos = 0;
  s->pos_begin = 0;
}

static void absorb(strobe_t *s, const uint8_t *d, long n) {
  for (long i = 0; i < n; i++) {
    s->st[s->pos] ^= d[i];
    if (++s->pos == R_RATE) run_f(s);
  }
}

/* flags: I=1 A=2 C=4 T=8 M=16 K=32 */
static void begin_op(strobe_t *s, uint8_t flags, int more) {
  if (more) return; /* caller guarantees same flags (Python asserts) */
  uint8_t hdr[2];
  hdr[0] = s->pos_begin;
  hdr[1] = flags;
  s->pos_begin = s->pos + 1;
  s->cur_flags = flags;
  absorb(s, hdr, 2);
  if ((flags & 0x24) && s->pos != 0) run_f(s);
}

void strobe_new(strobe_t *s, const uint8_t *label, long label_len) {
  static const uint8_t seed[18] = {0x01, R_RATE + 2, 0x01, 0x00, 0x01, 0x60,
                                   'S',  'T',        'R',  'O',  'B',  'E',
                                   'v',  '1',        '.',  '0',  '.',  '2'};
  memset(s, 0, sizeof(*s));
  memcpy(s->st, seed, sizeof(seed));
  perm(s);
  begin_op(s, 0x12 /* M|A */, 0);
  absorb(s, label, label_len);
}

void strobe_meta_ad(strobe_t *s, const uint8_t *d, long n, int more) {
  begin_op(s, 0x12 /* M|A */, more);
  absorb(s, d, n);
}

void strobe_ad(strobe_t *s, const uint8_t *d, long n, int more) {
  begin_op(s, 0x02 /* A */, more);
  absorb(s, d, n);
}

void strobe_prf(strobe_t *s, uint8_t *out, long n, int more) {
  begin_op(s, 0x07 /* I|A|C */, more);
  for (long i = 0; i < n; i++) {
    out[i] = s->st[s->pos];
    s->st[s->pos] = 0;
    if (++s->pos == R_RATE) run_f(s);
  }
}

void strobe_key(strobe_t *s, const uint8_t *d, long n, int more) {
  begin_op(s, 0x06 /* A|C */, more);
  for (long i = 0; i < n; i++) {
    s->st[s->pos] = d[i];
    if (++s->pos == R_RATE) run_f(s);
  }
}

/* ---- batch schnorrkel verification challenges --------------------------
 * One C call for N rows replaces N Python->ctypes round trips of ~6 STROBE
 * ops each; the per-row Merlin transcript cost drops from ~30 us to a few
 * us, which is what the mixed mega-commit's host staging is made of.
 * Transcript sequence mirrors sr25519_math.compute_challenge exactly
 * (reference seam: crypto/sr25519 verify via schnorrkel's
 * SigningContext("").bytes(msg) transcript). */

static void append_message(strobe_t *s, const uint8_t *label, long ll,
                           const uint8_t *msg, long ml) {
  uint8_t len4[4] = {(uint8_t)(ml & 0xff), (uint8_t)((ml >> 8) & 0xff),
                     (uint8_t)((ml >> 16) & 0xff), (uint8_t)((ml >> 24) & 0xff)};
  strobe_meta_ad(s, label, ll, 0);
  strobe_meta_ad(s, len4, 4, 1);
  strobe_ad(s, msg, ml, 0);
}

void sr25519_batch_challenge(const uint8_t *pubs, /* n*32 */
                             const uint8_t *rs,   /* n*32 */
                             const uint8_t *msg_buf,
                             const int64_t *msg_off, /* n+1 offsets */
                             long n,
                             uint8_t *out /* n*64 */) {
  /* shared transcript prefix: Transcript("SigningContext") + empty ctx */
  strobe_t base;
  strobe_new(&base, (const uint8_t *)"Merlin v1.0", 11);
  append_message(&base, (const uint8_t *)"dom-sep", 7,
                 (const uint8_t *)"SigningContext", 14);
  append_message(&base, (const uint8_t *)"", 0, (const uint8_t *)"", 0);
  for (long i = 0; i < n; i++) {
    strobe_t s = base;
    append_message(&s, (const uint8_t *)"sign-bytes", 10,
                   msg_buf + msg_off[i], msg_off[i + 1] - msg_off[i]);
    append_message(&s, (const uint8_t *)"proto-name", 10,
                   (const uint8_t *)"Schnorr-sig", 11);
    append_message(&s, (const uint8_t *)"sign:pk", 7, pubs + 32 * i, 32);
    append_message(&s, (const uint8_t *)"sign:R", 6, rs + 32 * i, 32);
    /* challenge_bytes("sign:c", 64) */
    uint8_t len4[4] = {64, 0, 0, 0};
    strobe_meta_ad(&s, (const uint8_t *)"sign:c", 6, 0);
    strobe_meta_ad(&s, len4, 4, 1);
    strobe_prf(&s, out + 64 * i, 64, 0);
  }
}
