"""Proxy: 4 logical ABCI connections over one client creator.

Reference: proxy/app_conn.go:18-56, proxy/multi_app_conn.go. Consensus,
mempool, query, and snapshot traffic each get a connection facade; local
creators share one lock (the app is one non-reentrant state machine),
socket creators open 4 sockets.
"""

from cometbft_tpu.proxy.app_conns import (  # noqa: F401
    AppConns,
    ClientCreator,
    grpc_client_creator,
    local_client_creator,
    socket_client_creator,
)
