"""AppConns: the engine's view of its application (async).

reference: proxy/multi_app_conn.go (4 named connections), proxy/app_conn.go
(per-connection facades). Each logical connection is its own Client so a
slow FinalizeBlock cannot block CheckTx — the isolation the reference gets
from 4 sockets.
"""

from __future__ import annotations

import threading
from typing import Callable

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import Client, LocalClient, SocketClient
from cometbft_tpu.libs.service import BaseService

ClientCreator = Callable[[], Client]


def local_client_creator(app: abci.Application) -> ClientCreator:
    """All 4 connections share one lock + app instance
    (reference: proxy/client.go NewLocalClientCreator)."""
    lock = threading.Lock()
    return lambda: LocalClient(app, lock=lock)


def socket_client_creator(addr: str) -> ClientCreator:
    return lambda: SocketClient(addr)


def grpc_client_creator(addr: str) -> ClientCreator:
    """proxy/client.go NewRemoteClientCreator with transport=grpc."""
    def make():
        from cometbft_tpu.abci.grpc import GRPCClient

        return GRPCClient(addr)

    return make


class AppConns(BaseService):
    """Owns the 4 logical connections (consensus/mempool/query/snapshot)."""

    def __init__(self, creator: ClientCreator):
        super().__init__("AppConns")
        self._creator = creator
        self.consensus: Client | None = None
        self.mempool: Client | None = None
        self.query: Client | None = None
        self.snapshot: Client | None = None

    async def on_start(self) -> None:
        self.query = self._creator()
        self.snapshot = self._creator()
        self.mempool = self._creator()
        self.consensus = self._creator()
        # liveness probe, as the reference pings with Echo on connect
        await self.query.echo("hello")

    async def on_stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                await c.close()
