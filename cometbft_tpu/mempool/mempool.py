"""CListMempool — the validated tx pool (reference: mempool/clist_mempool.go).

Semantics preserved: txs enter only after an app CheckTx OK
(clist_mempool.go:251,376); an LRU cache short-circuits duplicates (the
cache also remembers invalid txs, config keep-invalid-txs-in-cache aside);
reap returns txs under byte/gas budgets in FIFO order
(clist_mempool.go:527); update removes committed txs and re-checks the
remainder against the post-block app state (clist_mempool.go:586).

Async design: one asyncio.Lock serializes structural mutation; a Condition
wakes gossip/proposal waiters when txs arrive — the clist
"wait-for-next" blocking iteration, minus the hand-rolled linked list.
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from dataclasses import dataclass, field

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import Client
from cometbft_tpu.types.block import tx_hash


class ErrTxInCache(Exception):
    pass


class ErrMempoolIsFull(Exception):
    """Admission shed: the pool (or the plane behind it) cannot absorb
    the tx right now. `plane` names WHICH pressure shed it ("mempool" =
    pool caps / saturated watermark, "sched" = verify-scheduler
    backpressure) so the RPC surface can serve the unified -32005 wire
    shape; `retry_after_ms` is the overload registry's hint when one
    was attached."""

    def __init__(self, *args, plane: str = "mempool",
                 retry_after_ms: int = 0):
        super().__init__(*args)
        self.plane = plane
        self.retry_after_ms = retry_after_ms


class ErrTxTooLarge(Exception):
    pass


class ErrTxBadSignature(Exception):
    """Admission-time signature gate rejected the tx (mempool tx_verify):
    either structurally unparseable or the signature failed the batched
    verification — the tx never buys an ABCI round-trip."""


# tx wire layout under tx_verify="ed25519": pub(32) || sig(64) || payload
TX_SIG_PUB = 32
TX_SIG_OVERHEAD = 96


class TxCache:
    """LRU of tx hashes (reference: mempool/cache.go LRUTxCache)."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def push(self, tx: bytes) -> bool:
        """False if already present (moves to front either way)."""
        h = tx_hash(tx)
        if h in self._map:
            self._map.move_to_end(h)
            return False
        self._map[h] = None
        if len(self._map) > self.size:
            self._map.popitem(last=False)
        return True

    def remove(self, tx: bytes) -> None:
        self._map.pop(tx_hash(tx), None)

    def has(self, tx: bytes) -> bool:
        return tx_hash(tx) in self._map

    def reset(self) -> None:
        self._map.clear()


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height at which the tx entered the pool
    gas_wanted: int
    sender: str = ""  # peer that first sent it (gossip loop suppression)
    seq: int = 0


@dataclass
class MempoolConfig:
    size: int = 5000  # max txs (config/config.go:838)
    max_txs_bytes: int = 1 << 30  # 1 GB
    cache_size: int = 10000
    max_tx_bytes: int = 1048576
    recheck: bool = True
    keep_invalid_txs_in_cache: bool = False
    # admission-time signature gate: "" = off (reference behavior);
    # "ed25519" = txs are `pub(32) || sig(64) || payload`, the signature
    # (over payload) verifies through the global verify scheduler's
    # mempool class BEFORE the ABCI round-trip — concurrent admissions
    # coalesce into one device batch or ride a consensus flush as filler
    tx_verify: str = ""
    # post-commit recheck storms are bounded into windows of this many
    # txs, yielding the event loop between windows so admission and
    # consensus are never starved by one monolithic sweep after a big
    # block (the overload plane's pressure ladder); 0 = the reference's
    # single-sweep behavior
    recheck_window: int = 512

    def validate_basic(self) -> None:
        if self.tx_verify not in ("", "ed25519"):
            raise ValueError(f"unknown mempool tx_verify {self.tx_verify!r}")
        if self.size < 0 or self.max_txs_bytes < 0 or self.cache_size < 0:
            raise ValueError("mempool sizes cannot be negative")
        if self.recheck_window < 0:
            raise ValueError("recheck_window cannot be negative")


class CListMempool:
    def __init__(
        self,
        config: MempoolConfig,
        app_conn: Client,
        height: int = 0,
    ):
        self.config = config
        self.app_conn = app_conn
        self.height = height
        self.cache = TxCache(config.cache_size)
        self._txs: OrderedDict[bytes, MempoolTx] = OrderedDict()  # hash -> tx
        self._txs_bytes = 0
        self._seq = 0
        self._lock = asyncio.Lock()
        self._tx_available = asyncio.Event()
        self.notify_available = True
        self.metrics = None  # libs.metrics.MempoolMetrics | None (node wires it)
        # overload resilience plane (libs/overload.py; node wires it via
        # attach_overload): saturated watermark sheds CheckTx BEFORE the
        # ABCI round-trip, elevated triggers eager expiry + gossip
        # throttling. None = the pre-overload ad-hoc behavior.
        self.overload = None
        # pressure-ladder accounting (assertion surface for the soak)
        self.recheck_windows_last = 0
        self.recheck_windows_total = 0
        self.eager_expired = 0
        # in-flight CheckTx dedup: tx hash -> future of the FIRST
        # submission's result; concurrent duplicates await it instead of
        # paying a second ABCI round-trip (or racing the cache)
        self._inflight: dict[bytes, asyncio.Future] = {}

    def attach_overload(self, registry) -> None:
        """Wire the node's overload registry: registers this pool's
        utilization signal and enables the pressure ladder."""
        self.overload = registry
        registry.register("mempool", self._overload_utilization)

    def _overload_utilization(self) -> float:
        """Pool pressure as a fraction of capacity (txs or bytes,
        whichever is tighter)."""
        return max(
            len(self._txs) / max(1, self.config.size),
            self._txs_bytes / max(1, self.config.max_txs_bytes),
        )

    def _update_metrics(self) -> None:
        if self.metrics is not None:
            self.metrics.size.set(self.size())
            self.metrics.size_bytes.set(self.size_bytes())

    # ------------------------------------------------------------- sizes

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._txs_bytes

    def is_full(self, tx_len: int) -> bool:
        return (
            len(self._txs) >= self.config.size
            or self._txs_bytes + tx_len > self.config.max_txs_bytes
        )

    # ------------------------------------------------------------ checktx

    async def check_tx(self, tx: bytes, sender: str = "") -> abci.ResponseCheckTx:
        """Gate a tx into the pool (clist_mempool.go:251-300 CheckTx +
        resCbFirstTime). Raises for structural rejects; returns the app
        response (which may be a rejection) otherwise.

        A duplicate submitted while the first copy's CheckTx is still in
        flight resolves from the FIRST result — same response object, no
        second ABCI round-trip (the reference rejects such duplicates via
        the cache; resolving is strictly more useful to the submitter and
        costs nothing)."""
        if len(tx) > self.config.max_tx_bytes:
            raise ErrTxTooLarge(f"tx size {len(tx)} > max {self.config.max_tx_bytes}")
        if self.is_full(len(tx)):
            if self.overload is not None:
                self.overload.shed("mempool")
            raise ErrMempoolIsFull(
                f"{len(self._txs)} txs, {self._txs_bytes} bytes"
            )
        if self.overload is not None:
            # the pressure ladder's saturated rung: shed NEW work at the
            # door while the pool is at its high watermark — before the
            # tx buys a signature batch or an ABCI round-trip. Duplicates
            # of in-flight/pooled txs still resolve below (they cost
            # nothing and the submitter learns the first result).
            from cometbft_tpu.libs import overload as _ovl

            if (self.overload.level("mempool") >= _ovl.SATURATED
                    and tx_hash(tx) not in self._inflight):
                self.overload.shed("mempool")
                raise ErrMempoolIsFull(
                    f"mempool saturated ({len(self._txs)}/"
                    f"{self.config.size} txs)",
                    retry_after_ms=self.overload.retry_after_ms("mempool"),
                )
        h = tx_hash(tx)
        first = self._inflight.get(h)
        if first is not None:
            try:
                res = await asyncio.shield(first)
            except asyncio.CancelledError:
                if not first.cancelled():
                    raise  # WE were cancelled, not the first submitter
                # the first submitter was cancelled mid-flight: its result
                # is unknown; fall through to the normal path (typically
                # ErrTxInCache — the pre-dedup behavior) instead of
                # propagating a foreign cancellation into this caller
                first = None
            else:
                async with self._lock:
                    if h in self._txs and sender and not self._txs[h].sender:
                        self._txs[h].sender = sender
                return res
        if not self.cache.push(tx):
            # Record the extra sender, as the reference does, then reject.
            async with self._lock:
                if h in self._txs and sender and not self._txs[h].sender:
                    self._txs[h].sender = sender
            raise ErrTxInCache()

        fut = asyncio.get_running_loop().create_future()
        self._inflight[h] = fut
        try:
            res = await self._check_tx_new(tx, sender)
        except BaseException as e:
            if not fut.done():
                if isinstance(e, Exception):
                    fut.set_exception(e)
                    fut.exception()  # consumed: no never-retrieved warning
                else:  # CancelledError: waiters retry on their own
                    fut.cancel()
            raise
        else:
            fut.set_result(res)
            return res
        finally:
            self._inflight.pop(h, None)

    async def _check_tx_new(self, tx: bytes, sender: str) -> abci.ResponseCheckTx:
        """First-copy admission: optional batched signature gate, the app
        CheckTx round-trip, then pool insertion."""
        if self.config.tx_verify:
            await self._verify_tx_signature(tx)
        res = await self.app_conn.check_tx(abci.RequestCheckTx(tx=tx, type_=abci.CheckTxType.NEW))
        if res.is_ok():
            async with self._lock:
                if self.is_full(len(tx)):
                    self.cache.remove(tx)
                    raise ErrMempoolIsFull()
                self._seq += 1
                self._txs[tx_hash(tx)] = MempoolTx(
                    tx=tx, height=self.height, gas_wanted=res.gas_wanted, sender=sender,
                    seq=self._seq,
                )
                self._txs_bytes += len(tx)
                if self.notify_available:
                    self._tx_available.set()
        else:
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
        return res

    async def _verify_tx_signature(self, tx: bytes) -> None:
        """The batched mempool-admission path (tx_verify="ed25519"): the
        tx's signature row goes to the global verify scheduler as
        MEMPOOL-class work — it rides the next consensus/sync flush as
        filler or the deadline worker flushes it within
        sched_mempool_deadline. Scheduler backpressure (saturated queues
        while consensus is busy) surfaces as ErrMempoolIsFull: admission
        sheds load instead of queuing unboundedly."""
        from cometbft_tpu import sched
        from cometbft_tpu.crypto import ed25519 as _ed
        from cometbft_tpu.libs import trace

        if len(tx) < TX_SIG_OVERHEAD + 1:
            self.cache.remove(tx)
            raise ErrTxBadSignature(
                f"tx of {len(tx)} bytes cannot carry pub+sig+payload")
        pub, sig = tx[:TX_SIG_PUB], tx[TX_SIG_PUB:TX_SIG_OVERHEAD]
        payload = tx[TX_SIG_OVERHEAD:]
        # admission timeline: submit -> (queue wait inside the scheduler,
        # attributed there) -> resolved future. A slow admit is a root
        # span, so it lands in the slow capture ring with its batch tree.
        # the `with` covers EVERY exit below: an exception escaping an
        # unfinished span would leak it on this task's contextvar,
        # silently reparenting every later span on the connection
        with trace.span("mempool.admit", cat="mempool",
                        tx_bytes=len(tx)) as admit_sp:
            try:
                futs = sched.get().submit(
                    [(_ed.PubKey(pub), payload, sig)], klass=sched.MEMPOOL)
            except sched.SchedulerSaturated as e:
                admit_sp.set(outcome="saturated")
                self.cache.remove(tx)
                retry = 0
                if self.overload is not None:
                    self.overload.shed("sched")
                    retry = self.overload.retry_after_ms("sched")
                raise ErrMempoolIsFull(
                    f"verify scheduler saturated: {e}",
                    plane="sched", retry_after_ms=retry) from e
            # bounded wait: the scheduler resolves within its deadline
            # plus, worst case, one device-watchdog window (hang ->
            # supervisor -> host oracle). A timeout here means something
            # is deeply wrong — shed the tx rather than wedging this RPC
            # coroutine forever.
            from cometbft_tpu.ops import dispatch as _dispatch

            try:
                ok = await asyncio.wait_for(
                    asyncio.wrap_future(futs[0]),
                    timeout=_dispatch.watchdog_timeout() + 5.0)
            except asyncio.TimeoutError:
                admit_sp.set(outcome="timeout")
                self.cache.remove(tx)
                raise ErrMempoolIsFull("verify scheduler timed out") from None
            admit_sp.set(outcome="ok" if ok else "bad_signature")
        if not ok:
            if self.metrics is not None:
                self.metrics.failed_txs.inc()
            self.cache.remove(tx)
            raise ErrTxBadSignature("tx signature failed batched verification")

    async def wait_for_txs(self) -> None:
        """Block until the pool is non-empty (consensus txNotifier +
        gossip wakeup; clist WaitChan analog)."""
        await self._tx_available.wait()

    def has_txs(self) -> bool:
        return bool(self._txs)

    # -------------------------------------------------------------- reap

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """FIFO reap under budgets (clist_mempool.go:527-560). Byte budget
        counts raw tx bytes; -1 = unlimited."""
        out: list[bytes] = []
        total_bytes = total_gas = 0
        for mtx in self._txs.values():
            if max_bytes >= 0 and total_bytes + len(mtx.tx) > max_bytes:
                break
            if max_gas >= 0 and total_gas + mtx.gas_wanted > max_gas:
                break
            total_bytes += len(mtx.tx)
            total_gas += mtx.gas_wanted
            out.append(mtx.tx)
        return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        if n < 0:
            return [m.tx for m in self._txs.values()]
        return [m.tx for m in list(self._txs.values())[:n]]

    def iter_txs(self) -> list[MempoolTx]:
        """Snapshot for the gossip routine."""
        return list(self._txs.values())

    # ------------------------------------------------------------- update

    async def update(
        self,
        height: int,
        txs: list[bytes],
        tx_results: list[abci.ExecTxResult],
    ) -> None:
        """Post-commit maintenance (clist_mempool.go:586-650): drop
        committed txs (valid ones stay cached for dedup; invalid ones leave
        the cache so they can be resubmitted), then re-check survivors.
        Caller must hold the commit lock (consensus does, via lock())."""
        self.height = height
        for tx, res in zip(txs, tx_results):
            if res.is_ok():
                self.cache.push(tx)
            elif not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(tx)
            mtx = self._txs.pop(tx_hash(tx), None)
            if mtx is not None:
                self._txs_bytes -= len(mtx.tx)
        if self.overload is not None:
            from cometbft_tpu.libs import overload as _ovl

            if self.overload.level("mempool") >= _ovl.ELEVATED:
                self._eager_expire()
        if self.config.recheck and self._txs:
            if self.metrics is not None:
                self.metrics.recheck_times.inc()
            await self._recheck_txs()
        if not self._txs:
            self._tx_available.clear()
        self._update_metrics()

    def _eager_expire(self) -> None:
        """The pressure ladder's elevated rung: TTL-style expiry of the
        OLDEST queued txs (longest-waiting = most likely stale against
        post-block state, and the bulk of the next recheck storm) until
        the pool is back under the elevated watermark's hysteresis
        floor. Expired txs leave the cache so a submitter that still
        wants one can resubmit once pressure clears."""
        target = max(
            1, int(self.config.size
                   * (self.overload.elevated - self.overload.hysteresis)))
        expired = 0
        while len(self._txs) > target:
            h, mtx = next(iter(self._txs.items()))
            self._txs.pop(h, None)
            self._txs_bytes -= len(mtx.tx)
            self.cache.remove(mtx.tx)
            expired += 1
        if expired:
            self.eager_expired += expired
            self.overload.shed("mempool", expired)

    async def _recheck_txs(self) -> None:
        """Re-validate remaining txs against post-block state
        (clist_mempool.go recheckTxs) — in bounded windows of
        config.recheck_window txs, yielding the event loop between
        windows so a post-big-block recheck storm never starves
        admission or consensus (each window is roughly one scheduler
        batch budget of app round-trips)."""
        items = list(self._txs.items())
        window = self.config.recheck_window or len(items) or 1
        self.recheck_windows_last = 0
        for start in range(0, len(items), window):
            self.recheck_windows_last += 1
            self.recheck_windows_total += 1
            batch = [(h, mtx) for h, mtx in items[start:start + window]
                     if h in self._txs]  # expired/committed mid-storm
            # the window's re-checks fly CONCURRENTLY — the reference
            # fires every recheck request without awaiting responses
            # one-by-one (clist_mempool.go recheckTxs), and a sequential
            # sweep here costs one event-loop round-trip per tx: under
            # admission load that stretches finalize past the rest of
            # the net's next round, which is exactly the liveness hole
            # the overload plane exists to close
            results = await asyncio.gather(*(
                self.app_conn.check_tx(
                    abci.RequestCheckTx(tx=mtx.tx,
                                        type_=abci.CheckTxType.RECHECK))
                for _, mtx in batch))
            for (h, mtx), res in zip(batch, results):
                if not res.is_ok() and h in self._txs:
                    self._txs.pop(h, None)
                    self._txs_bytes -= len(mtx.tx)
                    if not self.config.keep_invalid_txs_in_cache:
                        self.cache.remove(mtx.tx)
            if start + window < len(items):
                # yield: queued admissions and consensus work interleave
                # between windows instead of waiting out the whole sweep
                await asyncio.sleep(0)

    async def flush(self) -> None:
        """Drop everything (RPC unsafe_flush_mempool)."""
        async with self._lock:
            self._txs.clear()
            self._txs_bytes = 0
            self.cache.reset()
            self._tx_available.clear()
