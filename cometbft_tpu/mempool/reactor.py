"""Mempool reactor: gossips valid transactions to peers.

Reference: mempool/reactor.go — one channel (0x30), one per-peer
broadcast routine (reactor.go:210 broadcastTxRoutine) walking the tx list
in arrival order and suppressing echo back to the tx's original sender.
Received txs go through CheckTx with the peer recorded as sender.

Wire: Txs message {1: repeated tx bytes} (proto/tendermint/mempool/types.proto).
"""

from __future__ import annotations

import asyncio

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.mempool.mempool import CListMempool, ErrMempoolIsFull, ErrTxInCache
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.utils.protobuf import Reader, Writer

MEMPOOL_CHANNEL = 0x30


def encode_txs(txs: list[bytes]) -> bytes:
    w = Writer()
    for tx in txs:
        w.bytes(1, tx, always=True)
    return w.output()


def decode_txs(data: bytes) -> list[bytes]:
    r = Reader(data)
    txs = []
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            txs.append(r.read_bytes())
        else:
            r.skip(w)
    return txs


class MempoolReactor(Reactor):
    def __init__(
        self,
        mempool: CListMempool,
        broadcast: bool = True,
        logger: cmtlog.Logger | None = None,
    ):
        super().__init__("Mempool", logger)
        self.mempool = mempool
        self.broadcast = broadcast
        self._peer_tasks: dict[object, asyncio.Task] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=5,
                                  recv_message_capacity=1 << 22)]

    async def add_peer(self, peer) -> None:
        if self.broadcast:
            self._peer_tasks[peer] = asyncio.get_running_loop().create_task(
                self._broadcast_tx_routine(peer)
            )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer, None)
        if t is not None:
            t.cancel()

    async def receive(self, e: Envelope) -> None:
        """reactor.go:93-130 Receive: CheckTx each, recording the sender."""
        for tx in decode_txs(e.message):
            try:
                await self.mempool.check_tx(tx, sender=e.src.id)
            except (ErrTxInCache, ErrMempoolIsFull):
                pass  # expected duplicates/backpressure, not peer misbehavior
            except Exception as err:  # noqa: BLE001
                self.logger.info("checktx from peer failed", err=str(err))

    def _gossip_budget(self) -> tuple[int, float]:
        """(batch cap, idle sleep) under the overload policy: at the
        elevated/saturated watermarks gossip is the first optional work
        to shrink — smaller batches, longer pauses — so admission and
        consensus keep their share of the loop."""
        reg = getattr(self.mempool, "overload", None)
        if reg is None:
            return 64, 0.05
        from cometbft_tpu.libs import overload as _ovl

        lvl = reg.level("mempool")
        if lvl >= _ovl.SATURATED:
            return 8, 0.25
        if lvl >= _ovl.ELEVATED:
            return 16, 0.1
        return 64, 0.05

    async def _broadcast_tx_routine(self, peer) -> None:
        """reactor.go:210: walk txs in seq order; echo suppression by
        sender; batch a few per message. last_seq only advances once the
        batch is actually delivered (the reference blocks in Send until
        success) so a full/slow channel never drops txs for this peer.
        A peer whose channel refuses the batch is signaling ITS
        saturation — the retry backoff doubles per consecutive refusal
        (capped) instead of hammering a drowning peer at a fixed 50 ms."""
        last_seq = 0
        peer_backoff = 0.05
        try:
            while peer.is_running:
                batch = []
                batch_last_seq = last_seq
                batch_cap, idle = self._gossip_budget()
                for mtx in self.mempool.iter_txs():
                    if mtx.seq <= last_seq:
                        continue
                    batch_last_seq = mtx.seq
                    if mtx.sender == peer.id:
                        continue  # don't echo a tx to where it came from
                    batch.append(mtx.tx)
                    if len(batch) >= batch_cap:
                        break
                if batch:
                    if await peer.send(MEMPOOL_CHANNEL, encode_txs(batch)):
                        last_seq = batch_last_seq
                        peer_backoff = 0.05
                    else:
                        # retry the same batch, backing off toward a
                        # saturated peer
                        await asyncio.sleep(peer_backoff)
                        peer_backoff = min(peer_backoff * 2, 0.8)
                else:
                    last_seq = batch_last_seq  # only sender-suppressed txs
                    await asyncio.sleep(idle)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            self.logger.error("mempool broadcast routine failed",
                              peer=peer.id[:10], err=str(e))
