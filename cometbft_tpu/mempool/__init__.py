"""Mempool (reference: mempool/).

CheckTx-gated concurrent tx pool with LRU dedup cache, reap for proposals,
post-commit update + recheck (SURVEY.md §2.1 row Mempool). The gossip
reactor lives in p2p-land (mempool/reactor.py) and consumes the pool's
async iteration (the clist analog).
"""

from cometbft_tpu.mempool.mempool import CListMempool, TxCache  # noqa: F401
