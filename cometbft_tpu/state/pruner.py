"""Background pruning service driven by retain heights.

Reference: state/pruner.go:17-140 — a service that periodically reads the
application / data-companion / ABCI-results retain heights from the state
store and prunes blocks, state rows and finalize responses up to the
minimum allowed height. Retain heights only ever move up (monotonic,
pruner.go SetApplicationBlockRetainHeight), are bounds-checked against the
block store, and survive restarts (persisted rows, state/store.py).

The application's retain height arrives from FinalizeBlock's
retain_height field via BlockExecutor (state/execution.go:305); the
companion height via the pruning gRPC/RPC surface. When the companion is
disabled (the default) only the application height drives pruning.
"""

from __future__ import annotations

import asyncio

from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.libs.service import BaseService

APP_RETAIN = "app_block"
COMPANION_RETAIN = "companion_block"
ABCI_RES_RETAIN = "abci_results"
TX_INDEX_RETAIN = "tx_index"
BLOCK_INDEX_RETAIN = "block_index"

DEFAULT_INTERVAL = 10.0  # config.DefaultPruningInterval


class Pruner(BaseService):
    def __init__(
        self,
        state_store,
        block_store,
        tx_indexer=None,
        block_indexer=None,
        cert_store=None,
        interval: float = DEFAULT_INTERVAL,
        companion_enabled: bool = False,
        logger: cmtlog.Logger | None = None,
        metrics=None,
    ):
        super().__init__("Pruner", logger)
        self.state_store = state_store
        self.block_store = block_store
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.cert_store = cert_store
        self.interval = interval
        self.companion_enabled = companion_enabled
        self.metrics = metrics
        self._task: asyncio.Task | None = None
        self._kick = asyncio.Event()
        self.blocks_pruned = 0
        self.abci_responses_pruned = 0
        self.certs_pruned = 0

    # ------------------------------------------------------ retain heights

    def _set_retain(self, which: str, height: int) -> None:
        """Monotonic, bounds-checked set (pruner.go:139-199)."""
        base = self.block_store.base()
        top = self.block_store.height()
        if height < base or height > top + 1:
            raise ValueError(
                f"retain height {height} out of bounds [{base}, {top + 1}]")
        cur = self.state_store.load_retain_height(which)
        if height < cur:
            raise ValueError(
                f"cannot lower {which} retain height {cur} -> {height}")
        self.state_store.save_retain_height(which, height)
        self._kick.set()

    def set_application_block_retain_height(self, height: int) -> None:
        self._set_retain(APP_RETAIN, height)

    def set_companion_block_retain_height(self, height: int) -> None:
        self._set_retain(COMPANION_RETAIN, height)

    def set_abci_res_retain_height(self, height: int) -> None:
        self._set_retain(ABCI_RES_RETAIN, height)

    def set_tx_indexer_retain_height(self, height: int) -> None:
        self._set_retain(TX_INDEX_RETAIN, height)

    def set_block_indexer_retain_height(self, height: int) -> None:
        self._set_retain(BLOCK_INDEX_RETAIN, height)

    def get_block_retain_height(self) -> int:
        return self._effective_block_retain()

    def get_abci_res_retain_height(self) -> int:
        return self.state_store.load_retain_height(ABCI_RES_RETAIN)

    def get_tx_indexer_retain_height(self) -> int:
        return self.state_store.load_retain_height(TX_INDEX_RETAIN)

    def get_block_indexer_retain_height(self) -> int:
        return self.state_store.load_retain_height(BLOCK_INDEX_RETAIN)

    def _effective_block_retain(self) -> int:
        """min(app, companion) when the companion is enabled; the app's
        height alone otherwise (pruner.go findMinRetainHeight shape)."""
        app = self.state_store.load_retain_height(APP_RETAIN)
        if not self.companion_enabled:
            return app
        comp = self.state_store.load_retain_height(COMPANION_RETAIN)
        if app == 0 or comp == 0:
            return 0  # one side has not spoken yet: prune nothing
        return min(app, comp)

    # ------------------------------------------------------------ service

    async def on_start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="pruner")

    async def on_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                self.prune_once()
            except Exception as e:  # noqa: BLE001 - pruning must not kill the node
                self.logger.error("pruning pass failed", err=str(e))
            self._kick.clear()
            try:
                await asyncio.wait_for(self._kick.wait(), self.interval)
            except asyncio.TimeoutError:
                pass

    def prune_once(self) -> tuple[int, int]:
        """One synchronous pruning pass; returns (blocks, responses)
        pruned. Exposed for tests and the inspect surface."""
        blocks = responses = 0
        retain = self._effective_block_retain()
        if retain > self.block_store.base():
            blocks = self.block_store.prune_blocks(retain)
            self.state_store.prune_states(retain)
            if blocks:
                self.logger.info("pruned blocks", to_height=retain, n=blocks)
        # commit certificates follow the BLOCK retain height exactly (a
        # cert without its block is undecodable context; a block without
        # its cert just re-certifies) — and, like the index rows below,
        # prune independently of whether block pruning fired this pass,
        # so a crash between block- and cert-pruning converges on the
        # next pass after restart instead of orphaning rows
        if self.cert_store is not None and retain > 0:
            try:
                self.certs_pruned += self.cert_store.prune(retain)
            except Exception as e:  # noqa: BLE001 - cert loss is re-derivable
                self.logger.error("cert pruning failed", err=str(e))
        # index rows follow their own retain heights when the pruning
        # service set them, else the block retain height — and prune
        # INDEPENDENTLY of whether block pruning fired this pass
        tx_retain = self.get_tx_indexer_retain_height() or retain
        bl_retain = self.get_block_indexer_retain_height() or retain
        if self.tx_indexer is not None and tx_retain > 0:
            self.tx_indexer.prune(tx_retain)
        if self.block_indexer is not None and bl_retain > 0:
            self.block_indexer.prune(bl_retain)
        res_retain = self.state_store.load_retain_height(ABCI_RES_RETAIN)
        if res_retain == 0 and not self.companion_enabled:
            # no companion and no explicit ABCI-results height: follow the
            # block retain height so finalize responses cannot grow
            # unboundedly (framework policy; the reference leaves results
            # pruning entirely to the pruning-service API)
            res_retain = retain
        if res_retain > 0:
            responses = self.state_store.prune_abci_responses(res_retain)
        self.blocks_pruned += blocks
        self.abci_responses_pruned += responses
        return blocks, responses
