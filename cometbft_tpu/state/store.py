"""StateStore (reference: state/store.go:61-600).

Persists: the State snapshot, FinalizeBlock responses per height (for
replay/indexing/rpc), validator sets per height (evidence + light client
lookups), consensus params per height.
"""

from __future__ import annotations

import base64
import json

from cometbft_tpu.state.state import State
from cometbft_tpu.store.db import KVStore
from cometbft_tpu.types.validator import Validator, ValidatorSet, pub_key_from_proto, pub_key_to_proto


def _hkey(prefix: bytes, height: int) -> bytes:
    return prefix + height.to_bytes(8, "big")


class StateStore:
    def __init__(self, db: KVStore):
        self.db = db

    # -------------------------------------------------------------- state

    def save(self, state: State) -> None:
        """Persist the snapshot + per-height valset/params rows
        (state/store.go Save)."""
        pairs: list[tuple[bytes, bytes | None]] = [(b"state", state.to_bytes())]
        # validators at H+1 (state.Validators) and H+2 (NextValidators)
        next_h = state.last_block_height + 1
        pairs.append((_hkey(b"V:", next_h + 1), _valset_bytes(state.next_validators)))
        if state.last_block_height == 0:
            # genesis: also record the initial set at initial_height
            pairs.append((_hkey(b"V:", state.initial_height), _valset_bytes(state.validators)))
        else:
            pairs.append((_hkey(b"V:", next_h), _valset_bytes(state.validators)))
        pairs.append((_hkey(b"CP:", next_h), state.to_bytes()))
        self.db.batch_set(pairs)

    def load(self) -> State | None:
        raw = self.db.get(b"state")
        return State.from_bytes(raw) if raw is not None else None

    def bootstrap(self, state: State) -> None:
        """Out-of-band state injection (statesync; state/store.go Bootstrap)."""
        if state.last_block_height > 0 and state.last_validators is not None:
            self.db.set(_hkey(b"V:", state.last_block_height), _valset_bytes(state.last_validators))
        self.save(state)

    # -------------------------------------------------- finalize responses

    def save_finalize_block_response(self, height: int, resp) -> None:
        from cometbft_tpu.abci import codec

        self.db.set(_hkey(b"FBR:", height), json.dumps(codec._to_jsonable(resp)).encode())

    def load_finalize_block_response(self, height: int):
        from cometbft_tpu.abci import codec
        from cometbft_tpu.abci.types import ResponseFinalizeBlock

        raw = self.db.get(_hkey(b"FBR:", height))
        if raw is None:
            return None
        return codec._from_jsonable(ResponseFinalizeBlock, json.loads(raw))

    # ---------------------------------------------------- consensus params

    def load_consensus_params(self, height: int):
        """Consensus params in effect AT `height` (state/store.go
        LoadConsensusParams). save() writes a CP: row per height holding the
        state snapshot whose params apply to that height."""
        raw = self.db.get(_hkey(b"CP:", height))
        if raw is None:
            return None
        return State.from_bytes(raw).consensus_params

    # --------------------------------------------------------- validators

    def load_validators(self, height: int) -> ValidatorSet | None:
        raw = self.db.get(_hkey(b"V:", height))
        return _valset_from_bytes(raw) if raw is not None else None

    def save_validators(self, height: int, vals: ValidatorSet) -> None:
        """Historical valset row (state/store.go saveValidatorsInfo) —
        blocksync/statesync backfill and test fixtures."""
        self.db.set(_hkey(b"V:", height), _valset_bytes(vals))

    # ----------------------------------------------------- retain heights
    # Persisted so the pruner service resumes where it left off across
    # restarts (state/pruner.go keys; monotonicity enforced by the pruner).

    def save_retain_height(self, which: str, height: int) -> None:
        self.db.set(b"RH:" + which.encode(), height.to_bytes(8, "big"))

    def load_retain_height(self, which: str) -> int:
        raw = self.db.get(b"RH:" + which.encode())
        return int.from_bytes(raw, "big") if raw is not None else 0

    # ------------------------------------------------------------- prune

    def prune_abci_responses(self, retain_height: int) -> int:
        """Delete FinalizeBlock responses below retain_height only (the
        ABCI-results retain height moves independently of state rows,
        state/pruner.go:201-222)."""
        pruned = 0
        pairs: list[tuple[bytes, bytes | None]] = []
        for k, _ in list(self.db.iterate(b"FBR:", _hkey(b"FBR:", retain_height))):
            pairs.append((k, None))
            pruned += 1
        self.db.batch_set(pairs)
        return pruned

    def prune_states(self, retain_height: int) -> int:
        """Valset + params rows below retain_height. FinalizeBlock
        responses are NOT touched here — they live under the independent
        ABCI-results retain height (prune_abci_responses)."""
        pruned = 0
        pairs: list[tuple[bytes, bytes | None]] = []
        for prefix in (b"V:", b"CP:"):
            for k, _ in list(self.db.iterate(prefix, _hkey(prefix, retain_height))):
                pairs.append((k, None))
                pruned += 1
        self.db.batch_set(pairs)
        return pruned


def _valset_bytes(vs: ValidatorSet | None) -> bytes:
    doc = {
        "validators": [
            {
                "pub_key": base64.b64encode(pub_key_to_proto(v.pub_key)).decode(),
                "power": v.voting_power,
                "priority": v.proposer_priority,
            }
            for v in (vs.validators if vs else [])
        ],
        "proposer": vs.proposer.address.hex() if vs and vs.proposer else None,
    }
    return json.dumps(doc, separators=(",", ":")).encode()


def _valset_from_bytes(raw: bytes) -> ValidatorSet:
    doc = json.loads(raw)
    vs = ValidatorSet.__new__(ValidatorSet)
    vs.validators = []
    for vd in doc["validators"]:
        pk = pub_key_from_proto(base64.b64decode(vd["pub_key"]))
        vs.validators.append(
            Validator(
                address=pk.address(),
                pub_key=pk,
                voting_power=vd["power"],
                proposer_priority=vd["priority"],
            )
        )
    vs._total_voting_power = None
    vs.proposer = None
    if doc.get("proposer"):
        addr = bytes.fromhex(doc["proposer"])
        for v in vs.validators:
            if v.address == addr:
                vs.proposer = v
                break
    return vs
