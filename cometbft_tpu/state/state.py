"""State — the committed-chain snapshot (reference: state/state.go).

Validator-set offsets (state/state.go:41-60): after applying block H,
`validators` is the set for H+1, `next_validators` for H+2, and
`last_validators` the set that signed H (used to verify H's LastCommit and
sent to the app as CommitInfo).
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field, replace

from cometbft_tpu.types.basic import BlockID
from cometbft_tpu.types.block import BLOCK_PROTOCOL, Block, Consensus, Data, EvidenceData, Header
from cometbft_tpu.types.commit import Commit
from cometbft_tpu.types.genesis import GenesisDoc
from cometbft_tpu.types.params import ConsensusParams, default_consensus_params
from cometbft_tpu.types.validator import Validator, ValidatorSet, pub_key_from_proto, pub_key_to_proto
from cometbft_tpu.utils import cmttime


@dataclass
class State:
    chain_id: str
    initial_height: int
    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    validators: ValidatorSet | None = None
    next_validators: ValidatorSet | None = None
    last_validators: ValidatorSet | None = None
    last_height_validators_changed: int = 0
    consensus_params: ConsensusParams = field(default_factory=default_consensus_params)
    last_height_consensus_params_changed: int = 0
    last_results_hash: bytes = b""
    app_hash: bytes = b""
    app_version: int = 0

    def copy(self) -> "State":
        return replace(
            self,
            validators=self.validators.copy() if self.validators else None,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    @classmethod
    def from_genesis(cls, gdoc: GenesisDoc) -> "State":
        """state/state.go MakeGenesisState."""
        val_set = gdoc.validator_set()
        next_vals = val_set.copy()
        next_vals.increment_proposer_priority(1)
        return cls(
            chain_id=gdoc.chain_id,
            initial_height=gdoc.initial_height,
            last_block_height=0,
            last_block_time=gdoc.genesis_time,
            validators=val_set,
            next_validators=next_vals,
            last_validators=ValidatorSet([]),
            last_height_validators_changed=gdoc.initial_height,
            consensus_params=gdoc.consensus_params,
            last_height_consensus_params_changed=gdoc.initial_height,
            app_hash=gdoc.app_hash,
        )

    # ------------------------------------------------------------ blocks

    def make_block(
        self,
        height: int,
        txs: list[bytes],
        last_commit: Commit,
        evidence: list,
        proposer_address: bytes,
        block_time: cmttime.Timestamp | None = None,
    ) -> Block:
        """state/state.go MakeBlock: header populated from this state."""
        header = Header(
            version=Consensus(block=BLOCK_PROTOCOL, app=self.app_version),
            chain_id=self.chain_id,
            height=height,
            time=block_time or cmttime.now(),
            last_block_id=self.last_block_id,
            validators_hash=self.validators.hash(),
            next_validators_hash=self.next_validators.hash(),
            consensus_hash=self.consensus_params.hash(),
            app_hash=self.app_hash,
            last_results_hash=self.last_results_hash,
            proposer_address=proposer_address,
        )
        block = Block(
            header=header,
            data=Data(txs=list(txs)),
            evidence=EvidenceData(evidence=list(evidence)),
            last_commit=last_commit,
        )
        block.fill_header()
        return block

    # ------------------------------------------------------ serialization

    def to_bytes(self) -> bytes:
        def valset(vs: ValidatorSet | None):
            if vs is None:
                return None
            return {
                "validators": [
                    {
                        "pub_key": base64.b64encode(pub_key_to_proto(v.pub_key)).decode(),
                        "power": v.voting_power,
                        "priority": v.proposer_priority,
                    }
                    for v in vs.validators
                ],
                "proposer": vs.proposer.address.hex() if vs.proposer else None,
            }

        doc = {
            "chain_id": self.chain_id,
            "initial_height": self.initial_height,
            "last_block_height": self.last_block_height,
            "last_block_id": base64.b64encode(self.last_block_id.to_proto()).decode(),
            "last_block_time": [self.last_block_time.seconds, self.last_block_time.nanos],
            "validators": valset(self.validators),
            "next_validators": valset(self.next_validators),
            "last_validators": valset(self.last_validators),
            "last_height_validators_changed": self.last_height_validators_changed,
            "consensus_params": {
                "block_max_bytes": self.consensus_params.block.max_bytes,
                "block_max_gas": self.consensus_params.block.max_gas,
                "evidence_max_age_num_blocks": self.consensus_params.evidence.max_age_num_blocks,
                "evidence_max_age_duration_ns": self.consensus_params.evidence.max_age_duration_ns,
                "evidence_max_bytes": self.consensus_params.evidence.max_bytes,
                "pub_key_types": self.consensus_params.validator.pub_key_types,
                "app_version": self.consensus_params.version.app,
                "vote_extensions_enable_height": self.consensus_params.abci.vote_extensions_enable_height,
            },
            "last_height_consensus_params_changed": self.last_height_consensus_params_changed,
            "last_results_hash": self.last_results_hash.hex(),
            "app_hash": self.app_hash.hex(),
            "app_version": self.app_version,
        }
        return json.dumps(doc, separators=(",", ":")).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "State":
        doc = json.loads(raw)

        def valset(d) -> ValidatorSet | None:
            if d is None:
                return None
            vs = ValidatorSet.__new__(ValidatorSet)
            vs.validators = []
            for vd in d["validators"]:
                pk = pub_key_from_proto(base64.b64decode(vd["pub_key"]))
                vs.validators.append(
                    Validator(
                        address=pk.address(),
                        pub_key=pk,
                        voting_power=vd["power"],
                        proposer_priority=vd["priority"],
                    )
                )
            vs._total_voting_power = None
            vs.proposer = None
            if d.get("proposer"):
                addr = bytes.fromhex(d["proposer"])
                for v in vs.validators:
                    if v.address == addr:
                        vs.proposer = v
                        break
            return vs

        cp = default_consensus_params()
        cpd = doc["consensus_params"]
        cp.block.max_bytes = cpd["block_max_bytes"]
        cp.block.max_gas = cpd["block_max_gas"]
        cp.evidence.max_age_num_blocks = cpd["evidence_max_age_num_blocks"]
        cp.evidence.max_age_duration_ns = cpd["evidence_max_age_duration_ns"]
        cp.evidence.max_bytes = cpd["evidence_max_bytes"]
        cp.validator.pub_key_types = cpd["pub_key_types"]
        cp.version.app = cpd["app_version"]
        cp.abci.vote_extensions_enable_height = cpd["vote_extensions_enable_height"]
        return cls(
            chain_id=doc["chain_id"],
            initial_height=doc["initial_height"],
            last_block_height=doc["last_block_height"],
            last_block_id=BlockID.from_proto(base64.b64decode(doc["last_block_id"])),
            last_block_time=cmttime.Timestamp(*doc["last_block_time"]),
            validators=valset(doc["validators"]),
            next_validators=valset(doc["next_validators"]),
            last_validators=valset(doc["last_validators"]),
            last_height_validators_changed=doc["last_height_validators_changed"],
            consensus_params=cp,
            last_height_consensus_params_changed=doc["last_height_consensus_params_changed"],
            last_results_hash=bytes.fromhex(doc["last_results_hash"]),
            app_hash=bytes.fromhex(doc["app_hash"]),
            app_version=doc.get("app_version", 0),
        )
