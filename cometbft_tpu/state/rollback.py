"""State rollback (reference: state/rollback.go).

Overwrites the current state (height n) with the reconstructed state at
n-1 — the recovery tool for an app that needs to re-run the last block
(e.g. after a faulty upgrade). Does NOT touch application state; with
remove_block the block at n is also deleted so both stores sit at n-1.
"""

from __future__ import annotations

from cometbft_tpu.state.state import State
from cometbft_tpu.state.store import StateStore, _hkey


class ErrRollback(Exception):
    pass


def rollback(block_store, state_store: StateStore,
             remove_block: bool = False) -> tuple[int, bytes]:
    """rollback.go:15-130 -> (new height, app hash)."""
    invalid_state = state_store.load()
    if invalid_state is None:
        raise ErrRollback("no state found")
    height = block_store.height()

    # state/blocks persist non-atomically: a pending extra block can exist
    if height == invalid_state.last_block_height + 1:
        if remove_block:
            block_store.delete_latest_block()
        return invalid_state.last_block_height, invalid_state.app_hash

    if height != invalid_state.last_block_height:
        raise ErrRollback(
            f"statestore height ({invalid_state.last_block_height}) is not one "
            f"below or equal to blockstore height ({height})")

    rollback_height = invalid_state.last_block_height - 1
    rollback_meta = block_store.load_block_meta(rollback_height)
    if rollback_meta is None:
        raise ErrRollback(f"block at height {rollback_height} not found")
    # app hash and last-results hash for n-1 are agreed in block n
    latest_meta = block_store.load_block_meta(invalid_state.last_block_height)
    if latest_meta is None:
        raise ErrRollback(f"block at height {invalid_state.last_block_height} not found")

    prev_last_vals = state_store.load_validators(rollback_height)
    if prev_last_vals is None:
        raise ErrRollback(f"no validator set at height {rollback_height}")

    # consensus params as-of rollback_height+1 (CP rows carry full snapshots)
    raw_cp = state_store.db.get(_hkey(b"CP:", rollback_height + 1))
    prev_params = (
        State.from_bytes(raw_cp).consensus_params if raw_cp is not None
        else invalid_state.consensus_params
    )

    val_change = min(invalid_state.last_height_validators_changed, rollback_height + 1)
    params_change = min(
        invalid_state.last_height_consensus_params_changed, rollback_height + 1)

    rolled = State(
        chain_id=invalid_state.chain_id,
        initial_height=invalid_state.initial_height,
        last_block_height=rollback_meta.header.height,
        last_block_id=rollback_meta.block_id,
        last_block_time=rollback_meta.header.time,
        next_validators=invalid_state.validators,
        validators=invalid_state.last_validators,
        last_validators=prev_last_vals,
        last_height_validators_changed=val_change,
        consensus_params=prev_params,
        last_height_consensus_params_changed=params_change,
        last_results_hash=latest_meta.header.last_results_hash,
        app_hash=latest_meta.header.app_hash,
        app_version=invalid_state.app_version,
    )
    state_store.save(rolled)
    if remove_block:
        block_store.delete_latest_block()
    return rolled.last_block_height, rolled.app_hash
