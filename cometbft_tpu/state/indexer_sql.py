"""SQL event sink: the psql-sink schema over sqlite.

Reference: state/indexer/sink/psql (psql.go:40-120 + schema.sql) — a
relational event sink for operators who query events with SQL instead of
the KV indexer's query language. Same four tables + joined views
(blocks / tx_results / events / attributes, event_attributes /
block_events / tx_events); the engine is sqlite (in this image there is
no PostgreSQL server — the schema and write paths are engine-portable,
so pointing it at psql is a connection-string change).

Like the reference's psql sink it is WRITE-ONLY from the node's
perspective: tx_search/block_search stay on the KV indexer; SQL consumers
query the database directly (sink/psql/psql.go:33-38 documents the same
contract).
"""

from __future__ import annotations

import sqlite3
import time

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     INTEGER NOT NULL,
  chain_id   TEXT NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);

CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   INTEGER NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash    TEXT NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);

CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id INTEGER NOT NULL REFERENCES blocks(rowid),
  tx_id    INTEGER NULL REFERENCES tx_results(rowid),
  type     TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS attributes (
  event_id      INTEGER NOT NULL REFERENCES events(rowid),
  key           TEXT NOT NULL,
  composite_key TEXT NOT NULL,
  value         TEXT NULL,
  UNIQUE (event_id, key)
);

CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);

CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;

CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id);
"""


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class SQLEventSink:
    """psql.go EventSink: IndexBlockEvents + IndexTxEvents."""

    def __init__(self, path: str, chain_id: str):
        self.chain_id = chain_id
        self._db = sqlite3.connect(path)
        self._db.executescript(_SCHEMA)
        self._db.commit()

    # --------------------------------------------------------------- write

    def _block_rowid(self, cur, height: int) -> int:
        cur.execute(
            "INSERT INTO blocks (height, chain_id, created_at) VALUES (?,?,?) "
            "ON CONFLICT (height, chain_id) DO UPDATE SET created_at = created_at "
            "RETURNING rowid",
            (height, self.chain_id, _now()))
        return cur.fetchone()[0]

    def _insert_events(self, cur, block_rowid: int, tx_rowid, events) -> None:
        for ev in events or []:
            if not ev.type_:
                continue
            cur.execute(
                "INSERT INTO events (block_id, tx_id, type) VALUES (?,?,?)",
                (block_rowid, tx_rowid, ev.type_))
            event_id = cur.lastrowid
            for attr in ev.attributes:
                if not attr.key:
                    continue
                cur.execute(
                    "INSERT OR IGNORE INTO attributes "
                    "(event_id, key, composite_key, value) VALUES (?,?,?,?)",
                    (event_id, attr.key, f"{ev.type_}.{attr.key}", attr.value))

    def index_block_events(self, height: int, events) -> None:
        """psql.go IndexBlockEvents. Idempotent under re-delivery (indexer
        re-feed after a crash): prior block-level events for the height are
        replaced, not duplicated."""
        cur = self._db.cursor()
        rowid = self._block_rowid(cur, height)
        cur.execute(
            "DELETE FROM attributes WHERE event_id IN "
            "(SELECT rowid FROM events WHERE block_id = ? AND tx_id IS NULL)",
            (rowid,))
        cur.execute(
            "DELETE FROM events WHERE block_id = ? AND tx_id IS NULL",
            (rowid,))
        self._insert_events(cur, rowid, None, events)
        self._db.commit()

    def index_tx_events(self, tx_results) -> None:
        """psql.go IndexTxEvents: tx_results carry (height, index, tx,
        result) — the state.txindex.TxResult shape."""
        from cometbft_tpu.abci import codec as abci_codec
        from cometbft_tpu.types.block import tx_hash

        import json as _json

        cur = self._db.cursor()
        for res in tx_results:
            rowid = self._block_rowid(cur, res.height)
            cur.execute(
                "INSERT OR IGNORE INTO tx_results "
                "(block_id, \"index\", created_at, tx_hash, tx_result) "
                "VALUES (?,?,?,?,?)",
                (rowid, res.index, _now(), tx_hash(res.tx).hex().upper(),
                 _json.dumps(abci_codec._to_jsonable(res.result)).encode()))
            if cur.rowcount == 0:
                continue  # re-delivered tx: events already recorded
            tx_rowid = cur.lastrowid
            self._insert_events(
                cur, rowid, tx_rowid, getattr(res.result, "events", []))
        self._db.commit()

    def close(self) -> None:
        self._db.close()
