"""SQL event sink: the psql-sink schema over sqlite.

Reference: state/indexer/sink/psql (psql.go:40-120 + schema.sql) — a
relational event sink for operators who query events with SQL instead of
the KV indexer's query language. Same four tables + joined views
(blocks / tx_results / events / attributes, event_attributes /
block_events / tx_events).

Engine portability is a first-class contract, not a comment: every DML
statement lives in _STMTS using only the SQL subset both engines accept
(RETURNING instead of lastrowid, ON CONFLICT instead of INSERT OR IGNORE),
and schema_sql()/statements() render the DDL/DML for a named dialect —
"sqlite" (executed here; no PostgreSQL server exists in this image) or
"postgresql" (AUTOINCREMENT->BIGSERIAL, BLOB->BYTEA, ?->%s).
tests/test_indexer_sql.py guards the postgresql rendering against
sqlite-isms so the sink stays a connection-string change away from psql.

Like the reference's psql sink it is WRITE-ONLY from the node's
perspective: tx_search/block_search stay on the KV indexer; SQL consumers
query the database directly (sink/psql/psql.go:33-38 documents the same
contract).
"""

from __future__ import annotations

import sqlite3
import time

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     INTEGER NOT NULL,
  chain_id   TEXT NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);

CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   INTEGER NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash    TEXT NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);

CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id INTEGER NOT NULL REFERENCES blocks(rowid),
  tx_id    INTEGER NULL REFERENCES tx_results(rowid),
  type     TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS attributes (
  event_id      INTEGER NOT NULL REFERENCES events(rowid),
  key           TEXT NOT NULL,
  composite_key TEXT NOT NULL,
  value         TEXT NULL,
  UNIQUE (event_id, key)
);

CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);

CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;

CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id);
"""


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


# every DML statement, in the engine-portable subset ("?" placeholders are
# rendered per dialect)
_STMTS = {
    "upsert_block": (
        "INSERT INTO blocks (height, chain_id, created_at) VALUES (?,?,?) "
        "ON CONFLICT (height, chain_id) DO UPDATE SET created_at = "
        "blocks.created_at RETURNING rowid"),
    "delete_block_attrs": (
        "DELETE FROM attributes WHERE event_id IN "
        "(SELECT rowid FROM events WHERE block_id = ? AND tx_id IS NULL)"),
    "delete_block_events": (
        "DELETE FROM events WHERE block_id = ? AND tx_id IS NULL"),
    "insert_event": (
        "INSERT INTO events (block_id, tx_id, type) VALUES (?,?,?) "
        "RETURNING rowid"),
    "insert_attr": (
        "INSERT INTO attributes (event_id, key, composite_key, value) "
        "VALUES (?,?,?,?) ON CONFLICT (event_id, key) DO NOTHING"),
    "insert_tx": (
        'INSERT INTO tx_results (block_id, "index", created_at, tx_hash, '
        "tx_result) VALUES (?,?,?,?,?) "
        'ON CONFLICT (block_id, "index") DO NOTHING RETURNING rowid'),
}

_DIALECTS = ("sqlite", "postgresql")

# RETURNING landed in SQLite 3.35 (2021); older embedded libsqlite still
# ships on some hosts. The sink degrades to lastrowid/SELECT lookups there
# — same rows, one extra statement per upsert.
_RETURNING_OK = sqlite3.sqlite_version_info >= (3, 35, 0)

_STMTS_NO_RETURNING = {
    k: v.replace(" RETURNING rowid", "") for k, v in _STMTS.items()
}
_SELECT_BLOCK_ROWID = (
    "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?")


def schema_sql(dialect: str = "sqlite") -> str:
    """The sink DDL rendered for `dialect`."""
    if dialect not in _DIALECTS:
        raise ValueError(f"unknown SQL dialect {dialect!r}")
    if dialect == "sqlite":
        return _SCHEMA
    return (_SCHEMA
            .replace("INTEGER PRIMARY KEY AUTOINCREMENT",
                     "BIGSERIAL PRIMARY KEY")
            .replace("BLOB", "BYTEA")
            # PostgreSQL has no IF NOT EXISTS for plain views
            .replace("CREATE VIEW IF NOT EXISTS", "CREATE OR REPLACE VIEW"))


def statements(dialect: str = "sqlite") -> dict[str, str]:
    """Every DML statement the sink executes, rendered for `dialect`
    (placeholder style is the only difference — the statements themselves
    are restricted to the engine-portable subset)."""
    if dialect not in _DIALECTS:
        raise ValueError(f"unknown SQL dialect {dialect!r}")
    if dialect == "sqlite":
        return dict(_STMTS)
    return {k: v.replace("?", "%s") for k, v in _STMTS.items()}


class SQLEventSink:
    """psql.go EventSink: IndexBlockEvents + IndexTxEvents."""

    def __init__(self, path: str, chain_id: str):
        self.chain_id = chain_id
        self._db = sqlite3.connect(path)
        self._db.executescript(_SCHEMA)
        self._db.commit()

    # --------------------------------------------------------------- write

    def _block_rowid(self, cur, height: int) -> int:
        if _RETURNING_OK:
            cur.execute(_STMTS["upsert_block"],
                        (height, self.chain_id, _now()))
            return cur.fetchone()[0]
        cur.execute(_STMTS_NO_RETURNING["upsert_block"],
                    (height, self.chain_id, _now()))
        cur.execute(_SELECT_BLOCK_ROWID, (height, self.chain_id))
        return cur.fetchone()[0]

    def _insert_events(self, cur, block_rowid: int, tx_rowid, events) -> None:
        for ev in events or []:
            if not ev.type_:
                continue
            if _RETURNING_OK:
                cur.execute(_STMTS["insert_event"],
                            (block_rowid, tx_rowid, ev.type_))
                event_id = cur.fetchone()[0]
            else:
                cur.execute(_STMTS_NO_RETURNING["insert_event"],
                            (block_rowid, tx_rowid, ev.type_))
                event_id = cur.lastrowid
            for attr in ev.attributes:
                if not attr.key:
                    continue
                cur.execute(
                    _STMTS["insert_attr"],
                    (event_id, attr.key, f"{ev.type_}.{attr.key}", attr.value))

    def index_block_events(self, height: int, events) -> None:
        """psql.go IndexBlockEvents. Idempotent under re-delivery (indexer
        re-feed after a crash): prior block-level events for the height are
        replaced, not duplicated."""
        cur = self._db.cursor()
        rowid = self._block_rowid(cur, height)
        cur.execute(_STMTS["delete_block_attrs"], (rowid,))
        cur.execute(_STMTS["delete_block_events"], (rowid,))
        self._insert_events(cur, rowid, None, events)
        self._db.commit()

    def index_tx_events(self, tx_results) -> None:
        """psql.go IndexTxEvents: tx_results carry (height, index, tx,
        result) — the state.txindex.TxResult shape."""
        from cometbft_tpu.abci import codec as abci_codec
        from cometbft_tpu.types.block import tx_hash

        import json as _json

        cur = self._db.cursor()
        for res in tx_results:
            rowid = self._block_rowid(cur, res.height)
            params = (
                rowid, res.index, _now(), tx_hash(res.tx).hex().upper(),
                _json.dumps(abci_codec._to_jsonable(res.result)).encode())
            if _RETURNING_OK:
                cur.execute(_STMTS["insert_tx"], params)
                row = cur.fetchone()
                if row is None:
                    continue  # re-delivered tx: events already recorded
                tx_rowid = row[0]
            else:
                cur.execute(_STMTS_NO_RETURNING["insert_tx"], params)
                if cur.rowcount == 0:
                    continue  # conflict DO NOTHING: re-delivered tx
                tx_rowid = cur.lastrowid
            self._insert_events(
                cur, rowid, tx_rowid, getattr(res.result, "events", []))
        self._db.commit()

    def close(self) -> None:
        self._db.close()
