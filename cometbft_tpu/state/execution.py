"""BlockExecutor — drives ABCI through the block lifecycle.

Reference: state/execution.go. Four verbs:
  create_proposal_block  (execution.go:109)  reap mempool -> PrepareProposal
  process_proposal       (execution.go:169)  app accept/reject
  apply_block            (execution.go:211)  FinalizeBlock -> update state
                                             -> Commit -> mempool update
  validate_block         (state/validation.go) header/commit checks incl.
                         verify_commit over the TPU batch boundary
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import Client
from cometbft_tpu.crypto import merkle
from cometbft_tpu.libs import fail
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.mempool.mempool import CListMempool
from cometbft_tpu.state.state import State
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.types import validation
from cometbft_tpu.types.basic import BlockID, BlockIDFlag
from cometbft_tpu.types.block import Block
from cometbft_tpu.types.commit import Commit, ExtendedCommit
from cometbft_tpu.types.params import ConsensusParams
from cometbft_tpu.types.validator import Validator, ValidatorSet, pub_key_from_proto
from cometbft_tpu.utils import cmttime


class ErrInvalidBlock(Exception):
    pass


class ErrProposalRejected(Exception):
    pass


class ErrVoteExtensionRejected(Exception):
    pass


def _abci_commit_info(block: Block, last_val_set: ValidatorSet | None) -> abci.CommitInfo:
    """Build CommitInfo from the block's LastCommit
    (state/execution.go buildLastCommitInfo)."""
    if block.header.height == 1 or block.last_commit is None or last_val_set is None:
        return abci.CommitInfo(round_=0)
    votes = []
    for i, cs in enumerate(block.last_commit.signatures):
        val = last_val_set.validators[i]
        votes.append(
            abci.VoteInfo(
                validator_address=val.address,
                validator_power=val.voting_power,
                block_id_flag=int(cs.block_id_flag),
            )
        )
    return abci.CommitInfo(round_=block.last_commit.round_, votes=votes)


def _extended_commit_info(ec: ExtendedCommit | None, val_set: ValidatorSet | None) -> abci.ExtendedCommitInfo:
    if ec is None or val_set is None:
        return abci.ExtendedCommitInfo(round_=0)
    votes = []
    for i, es in enumerate(ec.extended_signatures):
        val = val_set.validators[i]
        votes.append(
            abci.ExtendedVoteInfo(
                validator_address=val.address,
                validator_power=val.voting_power,
                block_id_flag=int(es.commit_sig.block_id_flag),
                vote_extension=es.extension,
                extension_signature=es.extension_signature,
            )
        )
    return abci.ExtendedCommitInfo(round_=ec.round_, votes=votes)


def _abci_misbehavior(evidence: list) -> list[abci.Misbehavior]:
    out = []
    for ev in evidence:
        for m in ev.abci():
            out.append(
                abci.Misbehavior(
                    type_=m["type"],
                    validator_address=m["validator_address"],
                    validator_power=m["validator_power"],
                    height=m["height"],
                    time=m["time"],
                    total_voting_power=m["total_voting_power"],
                )
            )
    return out


def _validator_updates_to_vals(updates: list[abci.ValidatorUpdate]) -> list[Validator]:
    from cometbft_tpu.utils import protobuf as pb

    out = []
    for u in updates:
        w = pb.Writer()
        field_num = {"ed25519": 1, "secp256k1": 2, "sr25519": 3}[u.pub_key_type]
        w.bytes(field_num, u.pub_key_bytes, always=True)
        pk = pub_key_from_proto(w.output())
        out.append(Validator.new(pk, u.power))
    return out


def results_hash(tx_results: list[abci.ExecTxResult]) -> bytes:
    """LastResultsHash: merkle over deterministic result encodings
    (reference: types/results.go)."""
    return merkle.hash_from_byte_slices([r.hash_bytes() for r in tx_results])


class BlockExecutor:
    def __init__(
        self,
        state_store: StateStore,
        app_conn: Client,  # consensus connection
        mempool: CListMempool,
        evidence_pool=None,
        event_bus=None,
        logger: cmtlog.Logger | None = None,
        pruner=None,
    ):
        self.state_store = state_store
        self.app_conn = app_conn
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.event_bus = event_bus
        self.logger = logger or cmtlog.nop()
        self.pruner = pruner  # state.Pruner | None, set by node assembly

    # ------------------------------------------------------------ propose

    async def create_proposal_block(
        self,
        height: int,
        state: State,
        last_extended_commit: ExtendedCommit,
        proposer_addr: bytes,
        block_time: cmttime.Timestamp | None = None,
    ) -> Block:
        """execution.go:109-167."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = []
        ev_size = 0
        if self.evidence_pool is not None:
            evidence, ev_size = self.evidence_pool.pending_evidence(
                state.consensus_params.evidence.max_bytes
            )
        # max data bytes (types/block.go MaxDataBytes approximation)
        max_data_bytes = (max_bytes if max_bytes > 0 else 22020096) - 2048 - ev_size
        txs = self.mempool.reap_max_bytes_max_gas(max_data_bytes, max_gas)
        commit = last_extended_commit.to_commit()

        req = abci.RequestPrepareProposal(
            max_tx_bytes=max_data_bytes,
            txs=txs,
            local_last_commit=_extended_commit_info(last_extended_commit, state.last_validators),
            misbehavior=_abci_misbehavior(evidence),
            height=height,
            time=block_time or cmttime.now(),
            next_validators_hash=state.next_validators.hash(),
            proposer_address=proposer_addr,
        )
        resp = await self.app_conn.prepare_proposal(req)
        block = state.make_block(
            height, resp.txs, commit, evidence, proposer_addr, block_time=req.time
        )
        return block

    async def process_proposal(self, block: Block, state: State) -> bool:
        """execution.go:169-209."""
        req = abci.RequestProcessProposal(
            txs=block.data.txs,
            proposed_last_commit=_abci_commit_info(block, state.last_validators),
            misbehavior=_abci_misbehavior(block.evidence.evidence),
            hash=block.hash(),
            height=block.header.height,
            time=block.header.time,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )
        resp = await self.app_conn.process_proposal(req)
        if resp.status == abci.ProposalStatus.UNKNOWN:
            raise ErrProposalRejected("ProcessProposal responded with status UNKNOWN")
        return resp.is_accepted()

    async def verify_vote_extension(self, vote) -> None:
        """execution.go:349-366 VerifyVoteExtension — consult the app on
        every peer precommit extension. Raises ErrVoteExtensionRejected when
        the app answers anything but ACCEPT (the reference panics on an
        unknown status; a rejected extension just drops the vote)."""
        req = abci.RequestVerifyVoteExtension(
            hash=vote.block_id.hash,
            validator_address=vote.validator_address,
            height=vote.height,
            vote_extension=vote.extension,
        )
        resp = await self.app_conn.verify_vote_extension(req)
        if resp.status != abci.VerifyStatus.ACCEPT:
            raise ErrVoteExtensionRejected(
                f"app rejected vote extension (status={resp.status}) from "
                f"{vote.validator_address.hex()[:12]} at height {vote.height}"
            )

    # ----------------------------------------------------------- validate

    def validate_block(self, state: State, block: Block,
                       last_commit_verified: bool = False) -> None:
        """state/validation.go:15-110 — structural + against-state checks,
        LastCommit verification through the batch boundary.

        last_commit_verified=True skips the signature re-verification: the
        streaming blocksync path has already full-verified this commit on
        the device (types/validation.py stage_verify_commit) — one device
        pass per commit instead of the reference's two
        (blocksync/reactor.go:463 + state/validation.go:92)."""
        block.validate_basic()
        h = block.header
        if h.version.block != 11:
            raise ErrInvalidBlock(f"wrong Block.Header.Version: {h.version.block}")
        if h.chain_id != state.chain_id:
            raise ErrInvalidBlock(f"wrong Block.Header.ChainID: {h.chain_id}")
        expected_height = state.last_block_height + 1 if state.last_block_height else state.initial_height
        if h.height != expected_height:
            raise ErrInvalidBlock(f"wrong Block.Header.Height: want {expected_height}, got {h.height}")
        if h.last_block_id != state.last_block_id:
            raise ErrInvalidBlock("wrong Block.Header.LastBlockID")
        if h.app_hash != state.app_hash:
            raise ErrInvalidBlock("wrong Block.Header.AppHash")
        if h.last_results_hash != state.last_results_hash:
            raise ErrInvalidBlock("wrong Block.Header.LastResultsHash")
        if h.validators_hash != state.validators.hash():
            raise ErrInvalidBlock("wrong Block.Header.ValidatorsHash")
        if h.next_validators_hash != state.next_validators.hash():
            raise ErrInvalidBlock("wrong Block.Header.NextValidatorsHash")
        if h.consensus_hash != state.consensus_params.hash():
            raise ErrInvalidBlock("wrong Block.Header.ConsensusHash")
        if not state.validators.has_address(h.proposer_address):
            raise ErrInvalidBlock("block proposer is not in the validator set")

        if h.height == state.initial_height:
            if block.last_commit is not None and block.last_commit.signatures:
                raise ErrInvalidBlock("initial block can't have LastCommit signatures")
        else:
            if block.last_commit is None:
                raise ErrInvalidBlock("nil LastCommit")
            if len(block.last_commit.signatures) != len(state.last_validators):
                raise ErrInvalidBlock(
                    f"invalid block commit size: {len(block.last_commit.signatures)} vs "
                    f"{len(state.last_validators)} validators"
                )
            if not last_commit_verified:
                # THE hot call: batched signature verification (validation.go:92)
                validation.verify_commit(
                    state.chain_id,
                    state.last_validators,
                    state.last_block_id,
                    h.height - 1,
                    block.last_commit,
                )

        # evidence in the proposed block must verify (validation.go:15 ->
        # evpool.CheckEvidence, state/validation.go end)
        if self.evidence_pool is not None and block.evidence.evidence:
            self.evidence_pool.check_evidence(block.evidence.evidence)

    # -------------------------------------------------------------- apply

    async def apply_block(
        self, state: State, block_id: BlockID, block: Block,
        last_commit_verified: bool = False, validated: bool = False,
    ) -> State:
        """execution.go:211-330 + Commit at 380-419. Returns the new state.
        The mempool is locked across FinalizeBlock->Commit->Update by the
        caller's single-threaded consensus task (asyncio serialization).
        validated=True skips validate_block entirely (the blocksync apply
        loop runs it pre-pop so a bad block can still be redone)."""
        if not validated:
            self.validate_block(state, block, last_commit_verified=last_commit_verified)
        req = abci.RequestFinalizeBlock(
            txs=block.data.txs,
            decided_last_commit=_abci_commit_info(block, state.last_validators),
            misbehavior=_abci_misbehavior(block.evidence.evidence),
            hash=block.hash(),
            height=block.header.height,
            time=block.header.time,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )
        resp = await self.app_conn.finalize_block(req)
        if len(resp.tx_results) != len(block.data.txs):
            raise ErrInvalidBlock(
                f"app returned {len(resp.tx_results)} tx results for {len(block.data.txs)} txs"
            )
        self.state_store.save_finalize_block_response(block.header.height, resp)
        fail.fail_point("state.finalize")  # execution.go:251 (legacy index 3)

        new_state = self._update_state(state, block_id, block, resp)
        self.state_store.save(new_state)
        fail.fail_point("state.save")  # execution.go:258 (legacy index 4)

        # Commit: app state persistence + mempool maintenance
        commit_resp = await self.app_conn.commit(abci.RequestCommit())
        # app and node state now agree on the height; only the mempool
        # rebuild and event fan-out remain (recovered by re-check)
        fail.fail_point("app.commit")
        await self.mempool.update(block.header.height, block.data.txs, resp.tx_results)

        if self.evidence_pool is not None:
            self.evidence_pool.update(new_state, block.evidence.evidence)

        if self.event_bus is not None:
            await self._fire_events(block, block_id, resp)

        new_state.retain_height = getattr(commit_resp, "retain_height", 0)
        if self.pruner is not None and new_state.retain_height > 0:
            # execution.go:305: hand the app's retain height to the pruner
            # service; actual deletion happens on its own cadence
            try:
                self.pruner.set_application_block_retain_height(
                    new_state.retain_height)
            except ValueError as e:
                self.logger.error("app retain height rejected", err=str(e))
        return new_state

    def _update_state(
        self, state: State, block_id: BlockID, block: Block, resp: abci.ResponseFinalizeBlock
    ) -> State:
        """execution.go:587-657 updateState."""
        n_val_set = state.next_validators.copy()
        last_height_vals_changed = state.last_height_validators_changed
        if resp.validator_updates:
            n_val_set.update_with_change_set(_validator_updates_to_vals(resp.validator_updates))
            last_height_vals_changed = block.header.height + 1 + 1
        n_val_set.increment_proposer_priority(1)

        params = state.consensus_params
        last_height_params_changed = state.last_height_consensus_params_changed
        if resp.consensus_param_updates is not None:
            params = params.update(resp.consensus_param_updates)
            params.validate_basic()
            last_height_params_changed = block.header.height + 1

        new = State(
            chain_id=state.chain_id,
            initial_height=state.initial_height,
            last_block_height=block.header.height,
            last_block_id=block_id,
            last_block_time=block.header.time,
            validators=state.next_validators.copy(),
            next_validators=n_val_set,
            last_validators=state.validators.copy(),
            last_height_validators_changed=last_height_vals_changed,
            consensus_params=params,
            last_height_consensus_params_changed=last_height_params_changed,
            last_results_hash=results_hash(resp.tx_results),
            app_hash=resp.app_hash,
            app_version=params.version.app,
        )
        return new

    async def _fire_events(self, block: Block, block_id: BlockID, resp) -> None:
        """execution.go:659-720 fireEvents -> event bus."""
        await self.event_bus.publish_event_new_block(block, block_id, resp)
        for i, tx in enumerate(block.data.txs):
            await self.event_bus.publish_event_tx(
                block.header.height, tx, i, resp.tx_results[i]
            )
        if resp.validator_updates:
            await self.event_bus.publish_event_validator_set_updates(resp.validator_updates)
