"""State & execution (reference: state/).

State is the engine's snapshot of the replicated app at the latest committed
height (valsets for H/H+1/H-1, consensus params, app hash, last results);
BlockExecutor drives ABCI to produce/validate/apply blocks.
"""

from cometbft_tpu.state.state import State  # noqa: F401
from cometbft_tpu.state.store import StateStore  # noqa: F401
from cometbft_tpu.state.execution import BlockExecutor  # noqa: F401
