"""Transaction + block indexing from the event bus.

Reference: state/txindex/kv/kv.go (tx indexer), state/indexer/block/kv/
(block indexer), state/txindex/indexer_service.go (the service pumping the
EventBus into both).

KV layout (same idea as the reference):
  TX:<hash>                        -> json(TxResult)
  TXE:<key>/<value>/<height>/<idx> -> hash      (event-attr secondary index)
  TXH:<height>/<idx>               -> hash      (reserved tx.height index)
  BLE:<key>/<value>/<height>       -> height    (block event index)
  BLH:<height>                     -> 1         (block indexed marker)

Search supports the pubsub query grammar (libs/pubsub.Query), matching the
reference's tx_search/block_search surface: equality and CONTAINS hit the
secondary indexes; ranged numeric conditions scan the height index.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field

from cometbft_tpu.libs import pubsub
from cometbft_tpu.libs.service import BaseService, TaskRunner
from cometbft_tpu.store.db import KVStore
from cometbft_tpu.types import event_bus as eb
from cometbft_tpu.types.block import tx_hash


@dataclass
class TxResult:
    """abci/types TxResult: a tx + where it landed + how it executed."""

    height: int
    index: int
    tx: bytes
    result: object  # abci.ExecTxResult

    def to_json(self) -> bytes:
        from cometbft_tpu.abci import codec

        return json.dumps({
            "height": self.height, "index": self.index,
            "tx": self.tx.hex(), "result": codec._to_jsonable(self.result),
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "TxResult":
        from cometbft_tpu.abci import codec
        from cometbft_tpu.abci.types import ExecTxResult

        d = json.loads(raw)
        return cls(
            height=d["height"], index=d["index"], tx=bytes.fromhex(d["tx"]),
            result=codec._from_jsonable(ExecTxResult, d["result"]),
        )


def _esc(s: str) -> str:
    return s.replace("/", "%2F")


class TxIndexer:
    """state/txindex/kv/kv.go KV tx indexer."""

    def __init__(self, db: KVStore):
        self.db = db

    def index(self, res: TxResult) -> None:
        h = tx_hash(res.tx)
        pairs: list[tuple[bytes, bytes | None]] = [(b"TX:" + h, res.to_json())]
        pairs.append((
            f"TXH:{res.height:020d}/{res.index:06d}".encode(), h))
        for ev in getattr(res.result, "events", []) or []:
            if not ev.type_:
                continue
            for attr in ev.attributes:
                if not attr.key or not attr.index:
                    continue
                key = f"TXE:{_esc(ev.type_)}.{_esc(attr.key)}/{_esc(attr.value)}/{res.height:020d}/{res.index:06d}"
                pairs.append((key.encode(), h))
        self.db.batch_set(pairs)

    def get(self, hash_: bytes) -> TxResult | None:
        raw = self.db.get(b"TX:" + hash_)
        return TxResult.from_json(raw) if raw is not None else None

    def prune(self, retain_height: int) -> int:
        """Delete index rows for txs below retain_height (the pruner
        service's analog of kv.go pruning). The TXE event rows embed the
        height two path segments from the end; a full-prefix scan per pass
        is acceptable at the pruner's cadence."""
        pairs: list[tuple[bytes, bytes | None]] = []
        pruned = 0
        end = f"TXH:{retain_height:020d}".encode()
        for k, v in list(self.db.iterate(b"TXH:", end)):
            pairs.append((k, None))
            pairs.append((b"TX:" + v, None))
            pruned += 1
        for k, _ in list(self.db.iterate(b"TXE:", b"TXE;")):
            try:
                h = int(k.decode().rsplit("/", 2)[-2])
            except (ValueError, IndexError):
                continue
            if h < retain_height:
                pairs.append((k, None))
        self.db.batch_set(pairs)
        return pruned

    def search(self, query: str | pubsub.Query, limit: int = 100) -> list[TxResult]:
        """kv.go Search: intersect per-condition hash sets; tx.hash short-
        circuits; ranged height conditions scan the TXH index."""
        q = query if isinstance(query, pubsub.Query) else pubsub.Query(query)
        result_sets: list[set[bytes]] = []
        post_filters: list[pubsub.Condition] = []
        for c in q.conditions:
            if c.key == eb.TX_HASH_KEY and c.op == "=":
                h = bytes.fromhex(str(c.operand))
                return [r for r in [self.get(h)] if r is not None]
            if c.key == eb.EVENT_TYPE_KEY:
                continue  # every indexed tx is a Tx event
            if c.key == eb.TX_HEIGHT_KEY:
                result_sets.append(self._scan_heights(c))
            elif c.op in ("=", "CONTAINS", "EXISTS"):
                result_sets.append(self._scan_events(c))
            else:
                # ranged op over an arbitrary event key: scan + post-filter
                result_sets.append(self._scan_events(
                    pubsub.Condition(c.key, "EXISTS")))
                post_filters.append(c)
        if not result_sets:
            hashes = {v for _, v in self.db.iterate(b"TXH:", b"TXH;")}
        else:
            hashes = set.intersection(*result_sets) if result_sets else set()
        out = []
        for h in hashes:
            r = self.get(h)
            if r is None:
                continue
            if post_filters and not all(
                f.matches(_attr_values(r.result, f.key)) for f in post_filters
            ):
                continue
            out.append(r)
        out.sort(key=lambda r: (r.height, r.index))
        return out[:limit]

    def _scan_heights(self, c: pubsub.Condition) -> set[bytes]:
        out = set()
        for k, v in self.db.iterate(b"TXH:", b"TXH;"):
            height = int(k.decode().split(":")[1].split("/")[0])
            if c.matches([str(height)]):
                out.add(v)
        return out

    def _scan_events(self, c: pubsub.Condition) -> set[bytes]:
        prefix = f"TXE:{_esc(c.key)}/".encode()
        out = set()
        for k, v in self.db.iterate(prefix, prefix[:-1] + b"0"):
            value = k.decode().split("/", 1)[1].rsplit("/", 2)[0]
            if c.matches([value.replace("%2F", "/")]):
                out.add(v)
        return out


def _attr_values(result, key: str) -> list[str]:
    out = []
    for ev in getattr(result, "events", []) or []:
        for attr in ev.attributes:
            if f"{ev.type_}.{attr.key}" == key:
                out.append(attr.value)
    return out


class BlockIndexer:
    """state/indexer/block/kv: FinalizeBlock events by height."""

    def __init__(self, db: KVStore):
        self.db = db

    def index(self, height: int, events) -> None:
        pairs: list[tuple[bytes, bytes | None]] = [
            (f"BLH:{height:020d}".encode(), b"1")]
        for ev in events or []:
            if not ev.type_:
                continue
            for attr in ev.attributes:
                if not attr.key or not attr.index:
                    continue
                key = f"BLE:{_esc(ev.type_)}.{_esc(attr.key)}/{_esc(attr.value)}/{height:020d}"
                pairs.append((key.encode(), str(height).encode()))
        self.db.batch_set(pairs)

    def has(self, height: int) -> bool:
        return self.db.has(f"BLH:{height:020d}".encode())

    def prune(self, retain_height: int) -> int:
        """Delete block-event index rows below retain_height."""
        pairs: list[tuple[bytes, bytes | None]] = []
        pruned = 0
        end = f"BLH:{retain_height:020d}".encode()
        for k, _ in list(self.db.iterate(b"BLH:", end)):
            pairs.append((k, None))
            pruned += 1
        for k, v in list(self.db.iterate(b"BLE:", b"BLE;")):
            try:
                if int(v) < retain_height:
                    pairs.append((k, None))
            except ValueError:
                continue
        self.db.batch_set(pairs)
        return pruned

    def search(self, query: str | pubsub.Query, limit: int = 100) -> list[int]:
        q = query if isinstance(query, pubsub.Query) else pubsub.Query(query)
        sets: list[set[int]] = []
        for c in q.conditions:
            if c.key == eb.EVENT_TYPE_KEY:
                continue
            if c.key == "block.height":
                heights = set()
                for k, _ in self.db.iterate(b"BLH:", b"BLH;"):
                    h = int(k.decode().split(":")[1])
                    if c.matches([str(h)]):
                        heights.add(h)
                sets.append(heights)
                continue
            prefix = f"BLE:{_esc(c.key)}/".encode()
            heights = set()
            for k, v in self.db.iterate(prefix, prefix[:-1] + b"0"):
                value = k.decode().split("/", 1)[1].rsplit("/", 1)[0]
                if c.matches([value.replace("%2F", "/")]):
                    heights.add(int(v))
            sets.append(heights)
        if not sets:
            return []
        return sorted(set.intersection(*sets))[:limit]


class NullTxIndexer:
    """config tx_index.indexer = "null"."""

    def index(self, res) -> None:
        pass

    def get(self, hash_: bytes) -> None:
        return None

    def search(self, query, limit: int = 100) -> list:
        return []

    def prune(self, retain_height: int) -> int:
        return 0


class IndexerService(BaseService):
    """state/txindex/indexer_service.go: subscribes to the event bus and
    feeds both indexers."""

    def __init__(self, tx_indexer, block_indexer, event_bus, logger=None,
                 sql_sink=None):
        super().__init__("IndexerService", logger)
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self.sql_sink = sql_sink  # state.indexer_sql.SQLEventSink | None
        self._tasks = TaskRunner("indexer")

    async def on_start(self) -> None:
        # capacity=0: unbounded (SubscribeUnbuffered, indexer_service.go:43)
        # — the indexer must never be dropped for falling behind, or every
        # later tx would silently go unindexed
        block_sub = self.event_bus.subscribe("indexer", eb.QUERY_NEW_BLOCK, capacity=0)
        tx_sub = self.event_bus.subscribe("indexer", eb.QUERY_TX, capacity=0)
        self._tasks.spawn(self._run(block_sub, tx_sub), name="indexer-run")

    async def on_stop(self) -> None:
        await self._tasks.cancel_all()
        try:
            self.event_bus.unsubscribe_all("indexer")
        except Exception:  # noqa: BLE001
            pass

    async def _run(self, block_sub, tx_sub) -> None:
        async def pump_blocks():
            while True:
                msg = await block_sub.out.get()
                if msg is None:
                    return
                d = msg.data
                events = getattr(d.result_finalize_block, "events", [])
                if self.block_indexer is not None:
                    self.block_indexer.index(d.block.header.height, events)
                if self.sql_sink is not None:
                    self.sql_sink.index_block_events(d.block.header.height, events)

        async def pump_txs():
            while True:
                msg = await tx_sub.out.get()
                if msg is None:
                    return
                d = msg.data
                res = TxResult(d.height, d.index, d.tx, d.result)
                self.tx_indexer.index(res)
                if self.sql_sink is not None:
                    self.sql_sink.index_tx_events([res])

        await asyncio.gather(pump_blocks(), pump_txs())
