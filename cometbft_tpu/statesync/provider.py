"""StateProvider: trusted bootstrap data via the light client.

Reference: statesync/stateprovider.go:29-200. AppHash/Commit/State come
from light-client-VERIFIED light blocks (every hop device-batch-verified);
the snapshot's claimed app hash is never trusted from the wire.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from cometbft_tpu.state.state import State


class StateProvider(ABC):
    """stateprovider.go:29-37."""

    @abstractmethod
    async def app_hash(self, height: int) -> bytes: ...

    @abstractmethod
    async def commit(self, height: int): ...

    @abstractmethod
    async def state(self, height: int) -> State: ...


class LightClientStateProvider(StateProvider):
    """stateprovider.go:40-200 over light.Client."""

    def __init__(self, light_client, initial_height: int = 1,
                 consensus_params=None):
        self.lc = light_client
        self.initial_height = initial_height or 1
        self._consensus_params = consensus_params
        self._initialized = False

    async def _ensure_init(self) -> None:
        """Subjective initialization happens on first use — at node boot
        the trust root's providers may not be reachable yet."""
        if not self._initialized:
            await self.lc.initialize()
            self._initialized = True

    async def app_hash(self, height: int) -> bytes:
        """The app hash AFTER `height` commits lives in header height+1;
        also probe height+2 so State() can't fail later
        (stateprovider.go:88-110)."""
        await self._ensure_init()
        lb = await self.lc.verify_light_block_at_height(height + 1)
        await self.lc.verify_light_block_at_height(height + 2)
        return lb.header.app_hash

    async def commit(self, height: int):
        await self._ensure_init()
        lb = await self.lc.verify_light_block_at_height(height)
        return lb.commit

    async def state(self, height: int) -> State:
        """stateprovider.go:124-186: snapshot height h -> last block h,
        current h+1, next h+2 (valset changes at h land at h+2)."""
        await self._ensure_init()
        last = await self.lc.verify_light_block_at_height(height)
        current = await self.lc.verify_light_block_at_height(height + 1)
        next_ = await self.lc.verify_light_block_at_height(height + 2)
        state = State(
            chain_id=self.lc.chain_id,
            initial_height=self.initial_height,
            last_block_height=last.height,
            last_block_time=last.time,
            last_block_id=last.commit.block_id,
            app_hash=current.header.app_hash,
            last_results_hash=current.header.last_results_hash,
            last_validators=last.validator_set,
            validators=current.validator_set,
            next_validators=next_.validator_set,
            last_height_validators_changed=next_.height,
        )
        if self._consensus_params is not None:
            state.consensus_params = self._consensus_params
        return state
