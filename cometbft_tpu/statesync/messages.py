"""Statesync wire messages (reference: statesync/messages.go,
proto/tendermint/statesync). Channels: snapshot metadata on 0x60, chunk
payloads on 0x61 (reactor.go:33-35)."""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.utils import protobuf as pb

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61


@dataclass
class SnapshotsRequest:
    pass


@dataclass
class SnapshotsResponse:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash_: bytes = b""
    metadata: bytes = b""


@dataclass
class ChunkRequest:
    height: int = 0
    format: int = 0
    index: int = 0


@dataclass
class ChunkResponse:
    height: int = 0
    format: int = 0
    index: int = 0
    chunk: bytes = b""
    missing: bool = False


_TYPES = {
    1: SnapshotsRequest,
    2: SnapshotsResponse,
    3: ChunkRequest,
    4: ChunkResponse,
}
_TAGS = {v: k for k, v in _TYPES.items()}


def encode(msg) -> bytes:
    """oneof Message wrapper."""
    inner = pb.Writer()
    if isinstance(msg, SnapshotsRequest):
        pass
    elif isinstance(msg, SnapshotsResponse):
        inner.uvarint(1, msg.height)
        inner.uvarint(2, msg.format)
        inner.uvarint(3, msg.chunks)
        inner.bytes(4, msg.hash_)
        inner.bytes(5, msg.metadata)
    elif isinstance(msg, ChunkRequest):
        inner.uvarint(1, msg.height)
        inner.uvarint(2, msg.format)
        inner.uvarint(3, msg.index)
    elif isinstance(msg, ChunkResponse):
        inner.uvarint(1, msg.height)
        inner.uvarint(2, msg.format)
        inner.uvarint(3, msg.index)
        inner.bytes(4, msg.chunk)
        if msg.missing:
            inner.uvarint(5, 1)
    else:
        raise ValueError(f"unknown statesync message {type(msg)}")
    w = pb.Writer()
    w.message(_TAGS[type(msg)], inner.output(), always=True)
    return w.output()


def decode(data: bytes):
    r = pb.Reader(data)
    while not r.at_end():
        f, wt = r.read_tag()
        cls = _TYPES.get(f)
        if cls is None:
            r.skip(wt)
            continue
        ir = pb.Reader(r.read_bytes())
        msg = cls()
        while not ir.at_end():
            jf, jw = ir.read_tag()
            if isinstance(msg, SnapshotsResponse):
                if jf == 1:
                    msg.height = ir.read_uvarint()
                elif jf == 2:
                    msg.format = ir.read_uvarint()
                elif jf == 3:
                    msg.chunks = ir.read_uvarint()
                elif jf == 4:
                    msg.hash_ = ir.read_bytes()
                elif jf == 5:
                    msg.metadata = ir.read_bytes()
                else:
                    ir.skip(jw)
            elif isinstance(msg, ChunkRequest):
                if jf == 1:
                    msg.height = ir.read_uvarint()
                elif jf == 2:
                    msg.format = ir.read_uvarint()
                elif jf == 3:
                    msg.index = ir.read_uvarint()
                else:
                    ir.skip(jw)
            elif isinstance(msg, ChunkResponse):
                if jf == 1:
                    msg.height = ir.read_uvarint()
                elif jf == 2:
                    msg.format = ir.read_uvarint()
                elif jf == 3:
                    msg.index = ir.read_uvarint()
                elif jf == 4:
                    msg.chunk = ir.read_bytes()
                elif jf == 5:
                    msg.missing = bool(ir.read_uvarint())
                else:
                    ir.skip(jw)
            else:
                ir.skip(jw)
        return msg
    raise ValueError("empty statesync message")
