"""Chunk queue (reference: statesync/chunks.go).

Ordered delivery of snapshot chunks to the applier with out-of-order
arrival, retry, and per-chunk sender tracking. The reference spools chunks
to temp files (they can be large); this keeps them in memory with the same
interface — a disk spill belongs at the node layer once snapshots exceed
RAM."""

from __future__ import annotations

import asyncio
from typing import Optional


class ErrQueueClosed(Exception):
    pass


class ChunkQueue:
    """chunks.go:24-260, asyncio-shaped: allocate() hands out the next
    chunk index to a fetcher; add() stores an arrived chunk and wakes the
    applier; next_chunk() yields chunks strictly in order."""

    def __init__(self, num_chunks: int):
        self.num_chunks = num_chunks
        self._chunks: dict[int, bytes] = {}
        self._senders: dict[int, str] = {}
        self._allocated: set[int] = set()
        self._returned: set[int] = set()
        self._next = 0
        self._closed = False
        self._cond = asyncio.Condition()

    async def allocate(self) -> Optional[int]:
        """Next never-allocated (or retry-returned) index; None when all
        are allocated (fetchers then idle until retry or close)."""
        async with self._cond:
            if self._closed:
                raise ErrQueueClosed
            for i in range(self.num_chunks):
                if i in self._returned:
                    self._returned.discard(i)
                    return i
                if i not in self._allocated and i not in self._chunks:
                    self._allocated.add(i)
                    return i
            return None

    async def add(self, index: int, chunk: bytes, sender: str = "") -> bool:
        """Store an arrived chunk. Returns False for dupes/out-of-range."""
        async with self._cond:
            if self._closed:
                return False
            if not 0 <= index < self.num_chunks or index in self._chunks:
                return False
            self._chunks[index] = chunk
            self._senders[index] = sender
            self._allocated.discard(index)
            self._cond.notify_all()
            return True

    async def next_chunk(self, timeout: float = 60.0) -> tuple[int, bytes]:
        """Block until the next in-order chunk is present."""
        async with self._cond:
            want = self._next

            def ready():
                return self._closed or want in self._chunks

            try:
                await asyncio.wait_for(
                    self._cond.wait_for(ready), timeout)
            except asyncio.TimeoutError:
                raise TimeoutError(f"timed out waiting for chunk {want}") from None
            if self._closed:
                raise ErrQueueClosed
            self._next += 1
            return want, self._chunks[want]

    def sender_of(self, index: int) -> str:
        return self._senders.get(index, "")

    async def retry(self, index: int) -> None:
        """chunks.go Retry: discard + refetch a chunk (app asked)."""
        async with self._cond:
            self._chunks.pop(index, None)
            self._allocated.discard(index)
            self._returned.add(index)
            self._next = min(self._next, index)
            self._cond.notify_all()

    async def retry_all(self) -> None:
        async with self._cond:
            self._chunks.clear()
            self._allocated.clear()
            self._returned = set(range(self.num_chunks))
            self._next = 0
            self._cond.notify_all()

    async def close(self) -> None:
        async with self._cond:
            self._closed = True
            self._cond.notify_all()

    def done(self) -> bool:
        return self._next >= self.num_chunks
