"""The state-sync engine (reference: statesync/syncer.go).

SyncAny loop: pick the best offered snapshot, anchor its app hash in
light-client-verified headers, OfferSnapshot to the app, fetch chunks in
parallel, apply them in order, then verify the restored app (Info) against
the trusted app hash. Error taxonomy mirrors the reference:

  ErrAbort          — app said abort: give up state sync entirely
  ErrRetrySnapshot  — refetch every chunk of the same snapshot
  ErrRejectSnapshot — discard this snapshot, try the next
  ErrRejectFormat   — discard every snapshot of this format
  ErrRejectSender   — ban this snapshot's senders
  ErrNoSnapshots    — nothing (left) to try
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.statesync.chunks import ChunkQueue, ErrQueueClosed
from cometbft_tpu.statesync.provider import StateProvider
from cometbft_tpu.statesync.snapshots import Snapshot, SnapshotPool

CHUNK_FETCHERS = 4  # config statesync.chunk_fetchers
CHUNK_TIMEOUT = 15.0


class ErrAbort(Exception):
    pass


class ErrRetrySnapshot(Exception):
    pass


class ErrRejectSnapshot(Exception):
    pass


class ErrRejectFormat(Exception):
    pass


class ErrRejectSender(Exception):
    pass


class ErrNoSnapshots(Exception):
    pass


class Syncer:
    """syncer.go:40-520."""

    def __init__(
        self,
        state_provider: StateProvider,
        snapshot_conn,  # abci client (proxy snapshot connection)
        request_chunk: Callable[[str, Snapshot, int], "asyncio.Future | None"],
        logger: cmtlog.Logger | None = None,
        chunk_fetchers: int = CHUNK_FETCHERS,
        chunk_timeout: float = CHUNK_TIMEOUT,
    ):
        self.state_provider = state_provider
        self.conn = snapshot_conn
        self.request_chunk = request_chunk  # (peer_id, snapshot, index) -> None
        self.logger = logger or cmtlog.nop()
        self.pool = SnapshotPool()
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        self._chunks: Optional[ChunkQueue] = None
        self._snapshot: Optional[Snapshot] = None

    # ------------------------------------------------------------- intake

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        return self.pool.add(peer_id, snapshot)

    async def add_chunk(self, index: int, chunk: bytes, sender: str) -> bool:
        if self._chunks is None:
            return False
        return await self._chunks.add(index, chunk, sender)

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # --------------------------------------------------------------- sync

    async def sync_any(self, discovery_time: float = 0.0,
                       retry_hook: Callable[[], None] | None = None):
        """syncer.go:145-238: -> (state, commit)."""
        if discovery_time:
            await asyncio.sleep(discovery_time)
        snapshot: Optional[Snapshot] = None
        chunks: Optional[ChunkQueue] = None
        while True:
            if snapshot is None:
                snapshot = self.pool.best()
                chunks = None
            if snapshot is None:
                if not discovery_time:
                    raise ErrNoSnapshots
                if retry_hook is not None:
                    retry_hook()
                await asyncio.sleep(discovery_time)
                continue
            if chunks is None:
                chunks = ChunkQueue(snapshot.chunks)
            try:
                return await self.sync(snapshot, chunks)
            except ErrAbort:
                raise
            except ErrRetrySnapshot:
                await chunks.retry_all()
                self.logger.info("retrying snapshot", height=snapshot.height)
                continue
            except TimeoutError:
                self.pool.reject(snapshot)
                self.logger.error("timed out waiting for chunks; snapshot rejected",
                                  height=snapshot.height)
            except ErrRejectSnapshot:
                self.pool.reject(snapshot)
                self.logger.info("snapshot rejected", height=snapshot.height)
            except ErrRejectFormat:
                self.pool.reject_format(snapshot.format)
                self.logger.info("snapshot format rejected", format=snapshot.format)
            except ErrRejectSender:
                self.logger.info("snapshot senders rejected", height=snapshot.height)
                for pid in self.pool.peers_of(snapshot):
                    self.pool.reject_peer(pid)
            await chunks.close()
            snapshot = None
            chunks = None

    async def sync(self, snapshot: Snapshot, chunks: ChunkQueue):
        """syncer.go:241-320."""
        if self._chunks is not None:
            raise RuntimeError("a state sync is already in progress")
        self._chunks = chunks
        self._snapshot = snapshot
        fetchers: list[asyncio.Task] = []
        try:
            # anchor the app hash in light-client-verified headers BEFORE
            # offering anything to the app
            try:
                trusted_app_hash = await self.state_provider.app_hash(snapshot.height)
            except Exception as e:  # noqa: BLE001 - unverifiable: reject
                self.logger.info("failed to fetch and verify app hash", err=str(e))
                raise ErrRejectSnapshot from e

            await self._offer_snapshot(snapshot, trusted_app_hash)

            for _ in range(self.chunk_fetchers):
                fetchers.append(asyncio.create_task(
                    self._fetch_chunks(snapshot, chunks)))

            state = await self.state_provider.state(snapshot.height)
            commit = await self.state_provider.commit(snapshot.height)

            await self._apply_chunks(chunks)
            await self._verify_app(snapshot, trusted_app_hash, state.app_version)
            self.logger.info("snapshot restored", height=snapshot.height)
            return state, commit
        finally:
            for t in fetchers:
                t.cancel()
            self._chunks = None
            self._snapshot = None

    async def _offer_snapshot(self, snapshot: Snapshot, app_hash: bytes) -> None:
        """syncer.go:322-355."""
        resp = await self.conn.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(
                height=snapshot.height, format_=snapshot.format,
                chunks=snapshot.chunks, hash=snapshot.hash_,
                metadata=snapshot.metadata,
            ),
            app_hash=app_hash,
        ))
        r = resp.result
        if r == abci.OfferSnapshotResult.ACCEPT:
            return
        if r == abci.OfferSnapshotResult.ABORT:
            raise ErrAbort("app aborted state sync")
        if r == abci.OfferSnapshotResult.REJECT:
            raise ErrRejectSnapshot
        if r == abci.OfferSnapshotResult.REJECT_FORMAT:
            raise ErrRejectFormat
        if r == abci.OfferSnapshotResult.REJECT_SENDER:
            raise ErrRejectSender
        raise ErrRejectSnapshot(f"unknown OfferSnapshot result {r}")

    async def _fetch_chunks(self, snapshot: Snapshot, chunks: ChunkQueue) -> None:
        """syncer.go:415-463: one fetcher loop."""
        rr = 0
        while True:
            try:
                index = await chunks.allocate()
            except ErrQueueClosed:
                return
            if index is None:
                if chunks.done():
                    return
                await asyncio.sleep(0.1)
                continue
            peers = self.pool.peers_of(snapshot)
            if peers:
                peer = peers[rr % len(peers)]
                rr += 1
                try:
                    self.request_chunk(peer, snapshot, index)
                except Exception as e:  # noqa: BLE001
                    self.logger.error("chunk request failed", index=index, err=str(e))
            await asyncio.sleep(0)

    async def _apply_chunks(self, chunks: ChunkQueue) -> None:
        """syncer.go:358-413."""
        while not chunks.done():
            index, chunk = await chunks.next_chunk(timeout=self.chunk_timeout)
            resp = await self.conn.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=index, chunk=chunk, sender=chunks.sender_of(index)))
            for i in resp.refetch_chunks:
                await chunks.retry(i)
            for pid in resp.reject_senders:
                self.pool.reject_peer(pid)
            r = resp.result
            if r == abci.ApplySnapshotChunkResult.ACCEPT:
                continue
            if r == abci.ApplySnapshotChunkResult.ABORT:
                raise ErrAbort("app aborted during chunk apply")
            if r == abci.ApplySnapshotChunkResult.RETRY:
                await chunks.retry(index)
            elif r == abci.ApplySnapshotChunkResult.RETRY_SNAPSHOT:
                raise ErrRetrySnapshot
            elif r == abci.ApplySnapshotChunkResult.REJECT_SNAPSHOT:
                raise ErrRejectSnapshot
            else:
                raise ErrRejectSnapshot(f"unknown ApplySnapshotChunk result {r}")

    async def _verify_app(self, snapshot: Snapshot, trusted_app_hash: bytes,
                          app_version: int) -> None:
        """syncer.go:485-520: the restored app must report the trusted hash
        at the snapshot height."""
        resp = await self.conn.info(abci.RequestInfo())
        if resp.last_block_app_hash != trusted_app_hash:
            raise ErrRejectSnapshot(
                f"app hash mismatch after restore: got "
                f"{resp.last_block_app_hash.hex()}, want {trusted_app_hash.hex()}"
            )
        if resp.last_block_height != snapshot.height:
            raise ErrRejectSnapshot(
                f"app height mismatch after restore: got {resp.last_block_height}, "
                f"want {snapshot.height}"
            )
