"""State sync (reference: statesync/).

snapshots — peer-offered snapshot pool with ranking + rejection memory
chunks    — ordered chunk queue with retry/refetch semantics
syncer    — the offer/fetch/apply loop against the ABCI app, anchored to
            light-client-verified state
provider  — StateProvider: trusted AppHash/Commit/State via the light client
reactor   — p2p plumbing: snapshot/chunk channels, serving + requesting
"""

from cometbft_tpu.statesync.chunks import ChunkQueue
from cometbft_tpu.statesync.provider import LightClientStateProvider, StateProvider
from cometbft_tpu.statesync.reactor import StatesyncReactor
from cometbft_tpu.statesync.snapshots import Snapshot, SnapshotPool
from cometbft_tpu.statesync.syncer import (
    ErrAbort,
    ErrNoSnapshots,
    ErrRejectSnapshot,
    ErrRetrySnapshot,
    Syncer,
)

__all__ = [
    "ChunkQueue", "LightClientStateProvider", "StateProvider",
    "StatesyncReactor", "Snapshot", "SnapshotPool", "Syncer",
    "ErrAbort", "ErrNoSnapshots", "ErrRejectSnapshot", "ErrRetrySnapshot",
]
