"""Statesync reactor (reference: statesync/reactor.go).

Serves local snapshots to catching-up peers (ListSnapshots /
LoadSnapshotChunk via the app's snapshot connection) and feeds incoming
offers/chunks into the Syncer. Sync() drives the whole bootstrap and hands
(state, commit) to the node, which persists them and switches to blocksync
(node.go fast-sync handoff)."""

from __future__ import annotations

import asyncio
from typing import Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import log as cmtlog
from cometbft_tpu.p2p.base_reactor import Envelope, Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.statesync import messages as sm
from cometbft_tpu.statesync.snapshots import Snapshot
from cometbft_tpu.statesync.syncer import Syncer

RECENT_SNAPSHOTS = 10  # reactor.go:30


class StatesyncReactor(Reactor):
    """reactor.go:38-280."""

    def __init__(self, snapshot_conn, state_provider=None,
                 logger: cmtlog.Logger | None = None,
                 chunk_timeout: float = 15.0):
        super().__init__("StatesyncReactor", logger)
        self.conn = snapshot_conn
        self.syncer: Optional[Syncer] = None
        if state_provider is not None:
            self.syncer = Syncer(
                state_provider, snapshot_conn, self._request_chunk,
                logger=self.logger, chunk_timeout=chunk_timeout,
            )

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(id=sm.SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(id=sm.CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=16),
        ]

    # ---------------------------------------------------------- lifecycle

    async def add_peer(self, peer) -> None:
        """reactor.go:103-110: ask every new peer for its snapshots while
        we are syncing."""
        if self.syncer is not None:
            await peer.send(sm.SNAPSHOT_CHANNEL, sm.encode(sm.SnapshotsRequest()))

    async def remove_peer(self, peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    # ------------------------------------------------------------ receive

    async def receive(self, e: Envelope) -> None:
        try:
            msg = sm.decode(e.message)
        except Exception as err:  # noqa: BLE001
            self.logger.error("bad statesync message", err=str(err))
            return
        if isinstance(msg, sm.SnapshotsRequest):
            await self._serve_snapshots(e.src)
        elif isinstance(msg, sm.SnapshotsResponse):
            if self.syncer is not None:
                self.syncer.add_snapshot(
                    e.src.id,
                    Snapshot(height=msg.height, format=msg.format,
                             chunks=msg.chunks, hash_=msg.hash_,
                             metadata=msg.metadata),
                )
        elif isinstance(msg, sm.ChunkRequest):
            await self._serve_chunk(e.src, msg)
        elif isinstance(msg, sm.ChunkResponse):
            if self.syncer is not None and not msg.missing:
                await self.syncer.add_chunk(msg.index, msg.chunk, e.src.id)

    async def _serve_snapshots(self, peer) -> None:
        """reactor.go:121-146: up to the 10 newest local snapshots."""
        resp = await self.conn.list_snapshots(abci.RequestListSnapshots())
        snaps = sorted(resp.snapshots, key=lambda s: (s.height, s.format_),
                       reverse=True)[:RECENT_SNAPSHOTS]
        for s in snaps:
            await peer.send(sm.SNAPSHOT_CHANNEL, sm.encode(sm.SnapshotsResponse(
                height=s.height, format=s.format_, chunks=s.chunks,
                hash_=s.hash, metadata=s.metadata)))

    async def _serve_chunk(self, peer, msg: sm.ChunkRequest) -> None:
        """reactor.go:148-175."""
        resp = await self.conn.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            height=msg.height, format_=msg.format, chunk=msg.index))
        await peer.send(sm.CHUNK_CHANNEL, sm.encode(sm.ChunkResponse(
            height=msg.height, format=msg.format, index=msg.index,
            chunk=resp.chunk, missing=not resp.chunk)))

    # ------------------------------------------------------------- egress

    def _request_chunk(self, peer_id: str, snapshot, index: int) -> None:
        """Syncer callback: fire a chunk request at a specific peer."""
        if self.switch is None:
            return
        peer = self.switch.peers.get(peer_id)
        if peer is None:
            return
        asyncio.get_running_loop().create_task(
            peer.send(sm.CHUNK_CHANNEL, sm.encode(sm.ChunkRequest(
                height=snapshot.height, format=snapshot.format, index=index))))

    # --------------------------------------------------------------- sync

    async def sync(self, discovery_time: float = 3.0):
        """Drive a full state sync; returns (state, commit) for the node
        to bootstrap from (node.go stateSync handoff)."""
        if self.syncer is None:
            raise RuntimeError("statesync reactor has no state provider")
        return await self.syncer.sync_any(discovery_time)
