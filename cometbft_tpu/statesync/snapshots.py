"""Snapshot pool (reference: statesync/snapshots.go).

Tracks snapshots offered by peers, keyed by (height, format, chunks, hash);
ranks candidates best-first (newest height, then newest format, then most
peers); remembers rejections of snapshots, formats, and peers so a bad
offer is never retried."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Snapshot:
    """snapshots.go:22-45."""

    height: int
    format: int
    chunks: int
    hash_: bytes
    metadata: bytes = b""

    def key(self) -> bytes:
        """snapshots.go:48-60: identity over all fields."""
        h = hashlib.sha256()
        h.update(self.height.to_bytes(8, "big"))
        h.update(self.format.to_bytes(4, "big"))
        h.update(self.chunks.to_bytes(4, "big"))
        h.update(self.hash_)
        h.update(self.metadata)
        return h.digest()[:16]


@dataclass
class _Entry:
    snapshot: Snapshot
    peers: set[str] = field(default_factory=set)
    trusted_app_hash: bytes = b""


class SnapshotPool:
    """snapshots.go:63-260."""

    def __init__(self):
        self._entries: dict[bytes, _Entry] = {}
        self._rejected: set[bytes] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this (snapshot, any-peer) pair is new."""
        if (
            snapshot.format in self._rejected_formats
            or peer_id in self._rejected_peers
        ):
            return False
        key = snapshot.key()
        if key in self._rejected:
            return False
        entry = self._entries.get(key)
        if entry is None:
            self._entries[key] = _Entry(snapshot, {peer_id})
            return True
        added = peer_id not in entry.peers
        entry.peers.add(peer_id)
        return added

    def best(self) -> Snapshot | None:
        """snapshots.go:166-185 Best: height desc, format desc, peers desc."""
        ranked = sorted(
            self._entries.values(),
            key=lambda e: (e.snapshot.height, e.snapshot.format, len(e.peers)),
            reverse=True,
        )
        return ranked[0].snapshot if ranked else None

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        entry = self._entries.get(snapshot.key())
        return sorted(entry.peers) if entry else []

    def reject(self, snapshot: Snapshot) -> None:
        key = snapshot.key()
        self._rejected.add(key)
        self._entries.pop(key, None)

    def reject_format(self, format_: int) -> None:
        self._rejected_formats.add(format_)
        for key, e in list(self._entries.items()):
            if e.snapshot.format == format_:
                self._entries.pop(key)

    def reject_peer(self, peer_id: str) -> None:
        self._rejected_peers.add(peer_id)
        self.remove_peer(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        for key, e in list(self._entries.items()):
            e.peers.discard(peer_id)
            if not e.peers:
                self._entries.pop(key)

    def __len__(self) -> int:
        return len(self._entries)
