"""Device microbenchmarks for the Ed25519 kernel components.

Dev tool, not part of the node runtime: isolates where the Pallas ladder's
device time goes (field mul, carry rounds, table selects, point ops) so
kernel-optimization rounds are driven by measurement instead of vreg-count
guesses. All timings are slope-based: each probe runs its body I and 2*I
times inside one fused kernel and reports (t(2I) - t(I)) / I, which cancels
dispatch, transfer, and fixed per-kernel overhead — tunnel-proof by
construction.

Usage:  python -m cometbft_tpu.ops.microbench [probe ...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cometbft_tpu.ops import curve
from cometbft_tpu.ops import field as F
from cometbft_tpu.ops import pallas_verify as PV
from cometbft_tpu.ops import unpack as U

LANES = 128


def _time(fn, *args) -> float:
    """Median-of-5 wall time of fn(*args) fully materialized, seconds."""
    jax.tree_util.tree_map(lambda x: x.block_until_ready(), fn(*args))
    out = []
    for _ in range(5):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.tree_util.tree_map(lambda x: x.block_until_ready(), r)
        out.append(time.perf_counter() - t0)
    return sorted(out)[2]


def _loop_kernel_factory(body, n_state: int, iters: int):
    """Pallas kernel: state = body(state) run `iters` times. body maps a
    tuple of n_state (20, LANES) arrays to the same. Constants enter as in
    pallas_verify (module-constant swap)."""

    def kernel(*refs):
        consts = refs[: PV._N_CONSTS]
        ins = refs[PV._N_CONSTS : PV._N_CONSTS + n_state]
        outs = refs[PV._N_CONSTS + n_state :]
        saved_f = {n: getattr(F, n) for n in PV._FIELD_CONST_NAMES}
        saved_table = curve._BASE_TABLE17
        try:
            for n, ref in zip(PV._FIELD_CONST_NAMES, consts):
                setattr(F, n, ref[:])
            curve._BASE_TABLE17 = tuple(
                r[:] for r in consts[len(PV._FIELD_CONST_NAMES) :]
            )
            state = tuple(r[:] for r in ins)
            state = jax.lax.fori_loop(
                0, iters, lambda _, s: body(s), state
            )
            for o, s in zip(outs, state):
                o[:, :] = s
        finally:
            for n, v in saved_f.items():
                setattr(F, n, v)
            curve._BASE_TABLE17 = saved_table

    @jax.jit
    def run(*arrs):
        spec = pl.BlockSpec(
            (F.NLIMBS, LANES), lambda: (0, 0), memory_space=pltpu.VMEM
        )
        const_specs = [
            pl.BlockSpec((F.NLIMBS, LANES), lambda: (0, 0), memory_space=pltpu.VMEM)
        ] * len(PV._FIELD_CONST_NAMES) + [
            pl.BlockSpec(
                (curve.TABLE17, F.NLIMBS, LANES),
                lambda: (0, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ] * 4
        return pl.pallas_call(
            kernel,
            in_specs=const_specs + [spec] * n_state,
            out_specs=tuple([spec] * n_state),
            out_shape=tuple(
                jax.ShapeDtypeStruct((F.NLIMBS, LANES), jnp.int32)
                for _ in range(n_state)
            ),
        )(*PV._const_args(), *arrs)

    return run


def probe_loop(name: str, body, n_state: int, base_iters: int) -> float:
    """Per-iteration device time (us) of body via the I vs 2I slope."""
    rng = np.random.default_rng(0)
    arrs = [
        jnp.asarray(
            rng.integers(0, 8000, size=(F.NLIMBS, LANES)), dtype=jnp.int32
        )
        for _ in range(n_state)
    ]
    t1 = _time(_loop_kernel_factory(body, n_state, base_iters), *arrs)
    t2 = _time(_loop_kernel_factory(body, n_state, 2 * base_iters), *arrs)
    per = (t2 - t1) / base_iters * 1e6
    print(f"  {name:<32} {per:9.3f} us/iter  (I={base_iters}, t1={t1*1e3:.1f}ms t2={t2*1e3:.1f}ms)")
    return per


def _verify_reps_timer(batch: int, n_windows: int = 0, stages: str = "full"):
    rng = np.random.default_rng(1)
    # random valid-shaped inputs: timing only, validity irrelevant
    a = rng.integers(0, 8000, size=(4, F.NLIMBS, batch)).astype(np.int32)
    w = rng.integers(0, 2**32, size=(3, 8, batch), dtype=np.uint64).astype(np.uint32)
    args = [jnp.asarray(x) for x in (*a, *w)]

    @functools.partial(jax.jit, static_argnums=(7,))
    def reps(ax, ay, az, at, rw, sw, kw, n):
        def body(_, acc):
            m, _ok = PV._verify_pallas_bench(
                ax, ay, az, at, rw, sw, kw,
                n_windows=n_windows, stages=stages,
            )
            return acc + m.astype(jnp.int32)

        return jax.lax.fori_loop(0, n, body, jnp.zeros((batch,), jnp.int32))

    t1 = _time(reps, *args, 4)
    t2 = _time(reps, *args, 12)
    return (t2 - t1) / 8


def probe_full_verify(batch: int = 10240) -> None:
    """End-to-end verify_pallas device time, slope-based via rep loop."""
    per = _verify_reps_timer(batch)
    print(f"  verify_pallas[{batch}]            {per*1e3:9.2f} ms/batch  "
          f"({batch/per:,.0f} sigs/s)")


def probe_bisect(batch: int = 10240) -> None:
    """In-context stage costs: truncate the ladder / skip decompression and
    difference the slopes."""
    full = _verify_reps_timer(batch)
    half = _verify_reps_timer(batch, n_windows=26)
    nodec = _verify_reps_timer(batch, stages="nodecomp")
    per_win = (full - half) / 25
    blocks = batch // LANES
    print(f"  full                  {full*1e3:8.2f} ms")
    print(f"  26-window ladder      {half*1e3:8.2f} ms")
    print(f"  no R-decompress       {nodec*1e3:8.2f} ms")
    print(f"  => per-window         {per_win*1e6/blocks:8.3f} us/block")
    print(f"  => decompress         {(full-nodec)*1e6/blocks:8.3f} us/block")
    print(f"  => fixed (non-ladder) {(half - 26/51*(full-half+half))*1e3:8.2f} ms-ish")


# --------------------------------------------------------------------------
# Experimental variants (measured here before being promoted into field.py).
# --------------------------------------------------------------------------


_NCONV = 2 * F.NLIMBS


def _carry_round40(x: jnp.ndarray) -> jnp.ndarray:
    """Historical 40-column carry round (replaced in field.py by the split
    lo/hi reduce); kept here so the variant probes remain comparable."""
    c = x >> F.RADIX
    r = x & F.MASK
    shifted = jnp.concatenate(
        [
            jnp.zeros_like(c[:1]),
            c[: F.NLIMBS - 1],
            c[F.NLIMBS - 1 : F.NLIMBS] + c[_NCONV - 1 :] * F.FOLD,
            c[F.NLIMBS : _NCONV - 1],
        ],
        axis=0,
    )
    return r + shifted


def _reduce_v2(conv: jnp.ndarray) -> jnp.ndarray:
    """2x carry40 + fold + 3x carry20 (the pre-split reduce shape)."""
    for _ in range(2):
        conv = _carry_round40(conv)
    folded = conv[: F.NLIMBS] + F.FOLD * conv[F.NLIMBS :]
    for _ in range(3):
        folded = F._carry_round20(folded)
    return folded


def _mul_v2(a, b):
    return _reduce_v2(F._conv(a, b))


def _add_1round(a, b):
    return F._carry_round20(a + b)


def _conv_roll(a, b):
    """Pre-rolled 40-col conv: no jnp.pad, rows accumulate via sublane roll
    of the zero-extended b."""
    bz = jnp.concatenate([b, jnp.zeros_like(b)], axis=0)  # (40, B)
    acc = a[0:1] * bz
    for i in range(1, F.NLIMBS):
        acc = acc + a[i : i + 1] * jnp.roll(bz, i, axis=0)
    return acc


def _mul_roll(a, b):
    return _reduce_v2(_conv_roll(a, b))


def _conv_split(a, b):
    """Cyclic 20-col conv split into (lo, hi): lo = sum of products with
    i+j < 20 at col i+j, hi = products with i+j >= 20 at col i+j-20."""
    cyc = a[0:1] * b
    hi = jnp.zeros_like(b)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, b.shape, 0)
    for i in range(1, F.NLIMBS):
        prod = a[i : i + 1] * jnp.roll(b, i, axis=0)
        cyc = cyc + prod
        hi = hi + jnp.where(row_idx < i, prod, 0)
    return cyc - hi, hi


def _reduce_split(lo, hi):
    """Reduce (lo, hi) 20-col accumulators: carry hi twice, twist by
    2^260 mod p = 608, add, carry lo."""
    for _ in range(2):
        hi = F._carry_round20(hi)
    x = lo + F.FOLD * hi
    for _ in range(4):
        x = F._carry_round20(x)
    return x


def _mul_split(a, b):
    return _reduce_split(*_conv_split(a, b))


def _conv_stacked(a, b):
    """Conv on stacked coords (4, 20, B): axis-1 rolls. Probes whether
    filling sublane tiles exactly (80 = 10 vregs, no 20->24 padding) beats
    4 separate (20, B) convs."""
    pad = jnp.zeros_like(b)
    bz = jnp.concatenate([b, pad], axis=1)  # (4, 40, B)
    acc = a[:, 0:1] * bz
    for i in range(1, F.NLIMBS):
        acc = acc + a[:, i : i + 1] * jnp.roll(bz, i, axis=1)
    return acc


def probe_stacked() -> None:
    print("stacked-coord conv (4x (20,128) jointly):")
    rng = np.random.default_rng(0)
    arrs4 = [
        jnp.asarray(rng.integers(0, 8000, size=(4, F.NLIMBS, LANES)), dtype=jnp.int32)
        for _ in range(2)
    ]

    def factory(iters):
        def kernel(a_ref, b_ref, o_ref):
            a, b = a_ref[:], b_ref[:]

            def body(_, s):
                c = _conv_stacked(s, b)
                return c[:, : F.NLIMBS] & 0x1FFF  # cheap feedback, shape-stable

            o_ref[:] = jax.lax.fori_loop(0, iters, body, a)

        spec = pl.BlockSpec((4, F.NLIMBS, LANES), lambda: (0, 0, 0), memory_space=pltpu.VMEM)
        return jax.jit(
            lambda a, b: pl.pallas_call(
                kernel,
                in_specs=[spec, spec],
                out_specs=spec,
                out_shape=jax.ShapeDtypeStruct((4, F.NLIMBS, LANES), jnp.int32),
            )(a, b)
        )

    t1 = _time(factory(100_000), *arrs4)
    t2 = _time(factory(200_000), *arrs4)
    per = (t2 - t1) / 100_000 * 1e6
    print(f"  4-stacked conv                   {per:9.3f} us/iter  (= {per/4:.3f} us per conv)  t1={t1*1e3:.1f}ms t2={t2*1e3:.1f}ms")


def _select17_int16(table16, digit):
    """Experimental: where-tree over int16 tables, upcast after select."""
    neg_mask = (digit < 0)[None, :]
    mag = jnp.abs(digit).astype(jnp.int16)
    coords = [c[:16] for c in table16]
    for level in (3, 2, 1, 0):
        bit = ((mag >> level) & 1)[None, None, :] == 1
        half = coords[0].shape[0] // 2
        coords = [jnp.where(bit, c[half:], c[:half]) for c in coords]
    is16 = (mag == 16)[None, :]
    out = [jnp.where(is16, t[16], c[0]).astype(jnp.int32)
           for t, c in zip(table16, coords)]
    x, y, z, t = out
    x = jnp.where(neg_mask, F.neg(x), x)
    t = jnp.where(neg_mask, F.neg(t), t)
    return curve.Point(x, y, z, t)


def probe_select16() -> None:
    print("select int16 experiment (per 128-lane block):")
    probe_loop(
        "select17 int32 (current)",
        lambda s: (
            curve._select17_signed(curve._BASE_TABLE17, s[0][0]).x,
            s[0], s[1], s[2],
        ),
        4, 200_000,
    )

    table16 = tuple(
        jnp.broadcast_to(c, (curve.TABLE17, F.NLIMBS, LANES)).astype(jnp.int16)
        for c in curve._BASE_TABLE17
    )

    def probe16(s):
        p = _select17_int16(table16, s[0][0])
        return (p.x, s[0], s[1], s[2])

    # note: table16 closes over device constants — run via XLA-level loop
    # instead of the pallas harness for a comparable slope
    import functools

    arrs = [jnp.asarray(np.random.default_rng(0).integers(
        -16, 16, size=(F.NLIMBS, LANES)), dtype=jnp.int32) for _ in range(4)]

    @functools.partial(jax.jit, static_argnums=(4,))
    def loop16(a, b, c, d, iters):
        def body(_, s):
            return probe16(s)

        return jax.lax.fori_loop(0, iters, body, (a, b, c, d))

    @functools.partial(jax.jit, static_argnums=(4,))
    def loop32(a, b, c, d, iters):
        def body(_, s):
            return (curve._select17_signed(curve._BASE_TABLE17, s[0][0]).x,
                    s[0], s[1], s[2])

        return jax.lax.fori_loop(0, iters, body, (a, b, c, d))

    for name, fn in (("xla select int16", loop16), ("xla select int32", loop32)):
        t1 = _time(fn, *arrs, 100_000)
        t2 = _time(fn, *arrs, 200_000)
        print(f"  {name:<32} {(t2-t1)/100_000*1e6:9.3f} us/iter")


def probe_variants2() -> None:
    print("variants2 (per 128-lane block):")
    probe_loop("split-conv mul", lambda s: (_mul_split(s[0], s[1]), s[0]), 2, 300_000)
    probe_loop(
        "conv_split only",
        lambda s: (_conv_split(s[0], s[1])[0], s[0]),
        2,
        300_000,
    )
    probe_loop(
        "current field.mul", lambda s: (F.mul(s[0], s[1]), s[0]), 2, 300_000
    )
    probe_loop(
        "current field.sub", lambda s: (F.sub(s[0], s[1]), s[0]), 2, 1_000_000
    )


def probe_variants() -> None:
    print("variants (per 128-lane block):")
    probe_loop("loop overhead (s+1)", lambda s: (s[0] + 1,), 1, 2_000_000)
    probe_loop("reduce_v2 mul", lambda s: (_mul_v2(s[0], s[1]), s[0]), 2, 300_000)
    probe_loop("roll-conv mul", lambda s: (_mul_roll(s[0], s[1]), s[0]), 2, 300_000)
    probe_loop(
        "conv_roll only", lambda s: (_conv_roll(s[0], s[1])[:20], s[0]), 2, 300_000
    )
    probe_loop("add 1-round", lambda s: (_add_1round(s[0], s[1]), s[0]), 2, 1_000_000)


def probe_staging(n: int = 10240, mlen: int = 110) -> None:
    """Host-staging fast path: serial per-row hashers vs the vectorized
    batch rungs (ops/hashvec + BatchStrobe128), us/row. Pure host work —
    no device involved; this is the 48 ms of BENCH_r05's
    mixed_host_staging_ms decomposed."""
    import hashlib
    import os
    import time

    from cometbft_tpu.crypto import sr25519_math as srm
    from cometbft_tpu.ops import hashvec

    rng = __import__("numpy").random.default_rng(0)
    datas = [rng.bytes(mlen) for _ in range(n)]
    print(f"  hashvec native core: {hashvec.native_available()}")

    t0 = time.perf_counter()
    for d in datas:
        hashlib.sha512(d).digest()
    t_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    hashvec.sha512_many(datas)
    t_vec = time.perf_counter() - t0
    print(f"  sha512      serial {t_serial / n * 1e6:7.2f} us/row | "
          f"vectorized {t_vec / n * 1e6:7.2f} us/row "
          f"({t_serial / t_vec:.1f}x)")

    t0 = time.perf_counter()
    hashvec.sha512_mod_l_words(datas)
    t_pipe = time.perf_counter() - t0
    print(f"  sha512+modL pipeline          | "
          f"vectorized {t_pipe / n * 1e6:7.2f} us/row")

    m = n // 4  # serial strobe is slow; measure a quarter and scale
    pubs = [rng.bytes(32) for _ in range(m)]
    rs = [rng.bytes(32) for _ in range(m)]
    msgs = [rng.bytes(mlen) for _ in range(m)]
    prior = os.environ.get("CBFT_HASHVEC")
    os.environ["CBFT_HASHVEC"] = "serial"
    try:
        t0 = time.perf_counter()
        srm.batch_compute_challenges(pubs, rs, msgs)
        t_serial = time.perf_counter() - t0
    finally:
        if prior is None:
            del os.environ["CBFT_HASHVEC"]
        else:
            os.environ["CBFT_HASHVEC"] = prior
    t0 = time.perf_counter()
    srm.batch_compute_challenges(pubs, rs, msgs)
    t_vec = time.perf_counter() - t0
    print(f"  sr challenge serial {t_serial / m * 1e6:6.2f} us/row | "
          f"batch STROBE {t_vec / m * 1e6:5.2f} us/row "
          f"({t_serial / t_vec:.1f}x)")


def main(argv: list[str]) -> None:
    probes = set(argv) or {"all"}
    print(f"backend={jax.default_backend()} device={jax.devices()[0]}")

    if probes & {"all", "staging"}:
        print("host staging (serial vs vectorized hashers):")
        probe_staging()

    if probes & {"all", "verify"}:
        print("full verify:")
        probe_full_verify()

    if probes & {"bisect"}:
        print("stage bisection:")
        probe_bisect()

    if probes & {"all", "field"}:
        print("field ops (per 128-lane block):")
        probe_loop("mul", lambda s: (F.mul(s[0], s[1]), s[0]), 2, 300_000)
        probe_loop("sq", lambda s: (F.sq(s[0]),), 1, 300_000)
        probe_loop("add(3-round carry)", lambda s: (F.add(s[0], s[1]), s[0]), 2, 1_000_000)
        probe_loop("sub(3-round carry)", lambda s: (F.sub(s[0], s[1]), s[0]), 2, 1_000_000)
        probe_loop("raw add (no carry)", lambda s: ((s[0] + s[1]) & 0x1FFF, s[0]), 2, 2_000_000)
        probe_loop("carry_round20", lambda s: (F._carry_round20(s[0]),), 1, 2_000_000)
        probe_loop(
            "conv only (no reduce)",
            lambda s: (F._conv(s[0], s[1])[:20], s[0]),
            2,
            300_000,
        )

    if probes & {"all", "variants"}:
        probe_variants()

    if probes & {"all", "variants2"}:
        probe_variants2()

    if probes & {"all", "stacked"}:
        probe_stacked()

    if probes & {"select16"}:
        probe_select16()

    if probes & {"all", "window"}:
        print("ladder window (per 128-lane block):")

        def win(s):
            p = curve.Point(s[0], s[1], s[2], s[3])
            table_a = (s[0][None] + curve._BASE_TABLE17[0],) * 4
            ds = s[0][0] & 15
            p = curve.window_step(p, ds, ds, curve._BASE_TABLE17, table_a, out_t=False)
            return tuple(p)

        probe_loop("window_step(out_t=False)", win, 4, 20_000)

        def dbl5(s):
            p = curve.Point(s[0], s[1], s[2], s[3])
            for _ in range(4):
                p = curve.double_no_t(p)
            p = curve.double(p)
            return tuple(p)

        probe_loop("5 doublings only", dbl5, 4, 20_000)

    if probes & {"all", "curve"}:
        print("curve ops (per 128-lane block):")
        probe_loop(
            "double_no_t",
            lambda s: tuple(curve.double_no_t(curve.Point(*s)))[:4],
            4,
            40_000,
        )
        probe_loop(
            "double",
            lambda s: tuple(curve.double(curve.Point(*s))),
            4,
            40_000,
        )
        probe_loop(
            "madd_pre",
            lambda s: tuple(
                curve.madd_pre(
                    curve.Point(*s), curve._select17_signed(curve._BASE_TABLE17, s[0][0])
                )
            ),
            4,
            40_000,
        )
        probe_loop(
            "select17 only",
            lambda s: (
                curve._select17_signed(curve._BASE_TABLE17, s[0][0]).x,
                s[0],
                s[1],
                s[2],
            ),
            4,
            100_000,
        )


if __name__ == "__main__":
    main(sys.argv[1:])
