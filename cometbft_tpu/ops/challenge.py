"""Device-side ed25519 challenge derivation: k = SHA-512(R||A||M) mod L
computed on the chip, so only signature material crosses the wire.

Of the ~98 B/sig the PR 10 reduced-send steady state shipped, 32 B was
the challenge scalar k — host-computed from bytes the device already has
(A is resident in the PR 10 validator tables, M's prefix is shared per
(height,round,chain) vote flush). This module is the device twin of the
host challenge pipeline in ops/hashvec.py:

  lane-parallel SHA-512     32-bit lane-pair message schedule and
                            compression over the batch axis (the VPU is
                            int32-native; every 64-bit word lives as an
                            (hi, lo) uint32 pair, carries recovered from
                            the wrapped low sum)
  device Barrett mod L      base-2^16 limbs in uint32 (16x16 products
                            are exact in 32 bits), HAC 14.42 with the
                            same mu/L limb tables as the numpy rung,
                            emitting the packed (8, N) challenge words
                            the verify grid consumes
  prefix/tail table         a 256-row device-resident table of
                            prefix||tail byte rows, content-keyed and
                            delta-synced like the residency key tables,
                            so a vote lane's message descriptor is a
                            2-byte (flag|prefix-id) plus only the
                            ~10-24 variable suffix bytes

Both cores are oracled bit-for-bit against hashvec.sha512_rows /
reduce512_mod_l (tests/test_challenge.py fuzzes every rung); the wire
integration lives in ops/ed25519_kernel.py behind
`crypto.wire_device_challenge`, with a degradation ladder (table miss,
ragged/oversize message, non-resident A, chaos/breaker) that falls back
per-lane or per-batch to the host-computed k — never a verdict change.

Wire layout (one flat uint32 block, ed25519_kernel stages it):

  words[0      : 8b ]   R encoding words, (8, b) word-major
  words[8b     : 16b]   s scalar words, (8, b) word-major
  words[16b    : W  ]   descriptor stream: 2*b bytes of per-lane uint16
                        LE descriptors (bit15 = device-derive flag, low
                        15 bits = prefix-table row), then b lanes of
                        `var` variable suffix bytes, lane-contiguous

giving 64 + 2 + var wire bytes per signature (plus the 2-byte residency
index) — ~66-82 B/sig against the 98 of host-computed challenges.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

from cometbft_tpu.ops import hashvec as _hv

# chaos/supervisor site for the derive seam (libs/chaos.py,
# ops/dispatch.py): failures here degrade to the host-challenge path
# under this site's own breaker — the main "device" breaker never trips
# on a challenge-plane fault
SITE = "ed25519.challenge"

TABLE_ROWS = 256  # prefix/tail rows resident per put_key
PREFIX_CAP = 160  # prefix+tail bytes per row (vote prefixes are ~105)
MAX_VAR = 24      # variable suffix bytes shipped per lane; 2 + var must
                  # stay under the 32 B of k it replaces for a wire win
MAX_MLEN = 192    # message bytes (prefix+var+tail): 64+192 pads to <= 3
                  # SHA-512 blocks, the static compile ladder's ceiling
MIN_LANES = 4     # below this the classic path's fixed cost wins
MIN_ELIGIBLE_FRAC = 0.5  # mostly-fallback batches take the classic path

# ------------------------------------------------------------------ config

_cfg = {"enabled": True}


def configure(enabled: bool | None = None) -> None:
    if enabled is not None:
        _cfg["enabled"] = bool(enabled)


def enabled() -> bool:
    return _cfg["enabled"]


# ------------------------------------------------------------------- stats

_stats_lock = threading.Lock()
_stats: dict[str, int] = {}


def count(key: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[key] = _stats.get(key, 0) + n


def stats() -> dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        _stats.clear()


# ------------------------------------------------- 64-bit lane-pair helpers
#
# The TPU VPU has no int64 lanes: every SHA-512 word is an (hi, lo)
# uint32 pair. Shift amounts are static Python ints so the rotations
# trace to plain vector shifts (no shift-by-32 hazards, no dtype
# promotion — Python scalars stay weakly typed against uint32).


def _add64(ah, al, bh, bl):
    import jax.numpy as jnp

    s = al + bl  # uint32 wraps; wrapped sum below an addend flags carry
    carry = (s < al).astype(jnp.uint32)
    return ah + bh + carry, s


def _rotr64(h, l, n: int):  # noqa: E741 - l is the low word
    if n == 32:
        return l, h
    if n < 32:
        return ((h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n)))
    m = n - 32
    return ((l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m)))


def _shr64(h, l, n: int):  # noqa: E741 - n < 32 only (sigma shifts 6, 7)
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(p, q, r):
    return p[0] ^ q[0] ^ r[0], p[1] ^ q[1] ^ r[1]


# --------------------------------------------------------- SHA-512 (device)

_K_HI_NP = (_hv._SHA_K >> np.uint64(32)).astype(np.uint32)
_K_LO_NP = (_hv._SHA_K & np.uint64(0xFFFFFFFF)).astype(np.uint32)
_H0_HI = tuple(int(x) >> 32 for x in _hv._SHA_H0)
_H0_LO = tuple(int(x) & 0xFFFFFFFF for x in _hv._SHA_H0)


def _pairs_from_be_bytes(buf):
    """(N, nb*128) uint8 padded buffer -> ((N, nb, 16), (N, nb, 16))
    uint32 big-endian message word pairs."""
    import jax.numpy as jnp

    b = buf.reshape(buf.shape[0], -1, 16, 8).astype(jnp.uint32)
    hi = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    lo = (b[..., 4] << 24) | (b[..., 5] << 16) | (b[..., 6] << 8) | b[..., 7]
    return hi, lo


def _compress_pairs(whi, wlo):
    """(N, nb, 16) uint32 BE word pairs -> 16-tuple of (N,) uint32 state
    arrays [h0hi, h0lo, ..., h7hi, h7lo] — FIPS 180-4 compression, all N
    lanes through each round together (the device twin of
    hashvec._sha512_blocks_numpy)."""
    import jax
    import jax.numpy as jnp

    n, nb, _ = whi.shape
    khi = jnp.asarray(_K_HI_NP)
    klo = jnp.asarray(_K_LO_NP)
    state = []
    for i in range(8):
        state.append(jnp.full((n,), _H0_HI[i], dtype=jnp.uint32))
        state.append(jnp.full((n,), _H0_LO[i], dtype=jnp.uint32))
    for bi in range(nb):  # nb is static (<= 3): the block loop unrolls
        wh = jnp.zeros((80, n), dtype=jnp.uint32).at[:16].set(whi[:, bi, :].T)
        wl = jnp.zeros((80, n), dtype=jnp.uint32).at[:16].set(wlo[:, bi, :].T)

        def _sched(t, wp):
            wh, wl = wp
            w15 = (wh[t - 15], wl[t - 15])
            w2 = (wh[t - 2], wl[t - 2])
            s0 = _xor3(_rotr64(*w15, 1), _rotr64(*w15, 8), _shr64(*w15, 7))
            s1 = _xor3(_rotr64(*w2, 19), _rotr64(*w2, 61), _shr64(*w2, 6))
            ah, al = _add64(wh[t - 16], wl[t - 16], *s0)
            ah, al = _add64(ah, al, wh[t - 7], wl[t - 7])
            ah, al = _add64(ah, al, *s1)
            return wh.at[t].set(ah), wl.at[t].set(al)

        wh, wl = jax.lax.fori_loop(16, 80, _sched, (wh, wl))

        def _round(t, st):
            (ah, al, bh, bl, ch, cl, dh, dl,
             eh, el, fh, fl, gh, gl, hh, hl) = st
            s1 = _xor3(_rotr64(eh, el, 14), _rotr64(eh, el, 18),
                       _rotr64(eh, el, 41))
            chh = gh ^ (eh & (fh ^ gh))
            chl = gl ^ (el & (fl ^ gl))
            t1h, t1l = _add64(hh, hl, *s1)
            t1h, t1l = _add64(t1h, t1l, chh, chl)
            t1h, t1l = _add64(t1h, t1l, khi[t], klo[t])
            t1h, t1l = _add64(t1h, t1l, wh[t], wl[t])
            s0 = _xor3(_rotr64(ah, al, 28), _rotr64(ah, al, 34),
                       _rotr64(ah, al, 39))
            mjh = (ah & (bh | ch)) | (bh & ch)
            mjl = (al & (bl | cl)) | (bl & cl)
            t2h, t2l = _add64(*s0, mjh, mjl)
            neh, nel = _add64(dh, dl, t1h, t1l)
            nah, nal = _add64(t1h, t1l, t2h, t2l)
            return (nah, nal, ah, al, bh, bl, ch, cl,
                    neh, nel, eh, el, fh, fl, gh, gl)

        st = jax.lax.fori_loop(0, 80, _round, tuple(state))
        nxt = []
        for i in range(8):
            sh, sl = _add64(state[2 * i], state[2 * i + 1],
                            st[2 * i], st[2 * i + 1])
            nxt.append(sh)
            nxt.append(sl)
        state = nxt
    return tuple(state)


# ----------------------------------------- Barrett reduction mod L (device)
#
# Same HAC 14.42 shape as hashvec._reduce512_mod_l_numpy, re-limbed for
# uint32 lanes: base-2^16 limbs so every 16x16 product is exact in 32
# bits, split into (lo, hi) contributions whose accumulators stay under
# 2^22 before one carry sweep. Borrows ride the uint32 sign bit (every
# operand is < 2^16, so a wrapped difference always sets bit 31).

_MU17_PY = tuple(int(x) for x in _hv._MU17)
_L17_PY = tuple(int(x) for x in _hv._L17)


def _bswap32(x):
    return (((x >> 24) & 0xFF) | ((x >> 8) & 0xFF00)
            | ((x << 8) & 0xFF0000) | (x << 24))


def _state_to_limbs(state):
    """16-tuple of (N,) uint32 BE state pairs -> 32 (N,) uint32 base-2^16
    limbs of the little-endian 512-bit digest value (the digest byte
    stream is the BE serialization of the eight 64-bit state words)."""
    limbs = []
    for i in range(8):
        wh = _bswap32(state[2 * i])
        wl = _bswap32(state[2 * i + 1])
        limbs += [wh & 0xFFFF, wh >> 16, wl & 0xFFFF, wl >> 16]
    return limbs


def _carry16(acc):
    """One base-2^16 carry sweep along a list of (N,) uint32 limb
    accumulators (values < 2^22 on entry; canonical limbs on exit;
    overflow off the top limb dropped — mod b^len semantics)."""
    out = []
    c = None
    for a in acc:
        t = a if c is None else a + c
        out.append(t & 0xFFFF)
        c = t >> 16
    return out


def _barrett_mod_l(x):
    """32 (N,) uint32 base-2^16 limbs -> 16 limbs of (x mod L), the
    bit-for-bit device twin of hashvec._reduce512_mod_l_numpy."""
    import jax.numpy as jnp

    zeros = jnp.zeros_like(x[0])
    q1 = x[15:]  # floor(x / b^15): 17 limbs
    q2 = [zeros] * 34
    for i in range(17):
        mu = _MU17_PY[i]
        if mu == 0:
            continue
        for j in range(17):
            p = q1[j] * mu  # < 2^32: exact
            q2[i + j] = q2[i + j] + (p & 0xFFFF)
            q2[i + j + 1] = q2[i + j + 1] + (p >> 16)
    q2 = _carry16(q2)
    q3 = q2[17:]  # floor(q2 / b^17): 17 limbs
    r2 = [zeros] * 17  # q3*L mod b^17
    for i in range(17):
        li = _L17_PY[i]
        if li == 0:
            continue
        for j in range(17 - i):
            p = q3[j] * li
            r2[i + j] = r2[i + j] + (p & 0xFFFF)
            if i + j + 1 < 17:
                r2[i + j + 1] = r2[i + j + 1] + (p >> 16)
    r2 = _carry16(r2)
    r = []
    borrow = zeros
    for j in range(17):
        t = x[j] - r2[j] - borrow
        r.append(t & 0xFFFF)
        borrow = t >> 31
    # Barrett guarantees r < 3L: at most two conditional subtractions
    for _ in range(2):
        d = []
        borrow = zeros
        for j in range(17):
            t = r[j] - _L17_PY[j] - borrow
            d.append(t & 0xFFFF)
            borrow = t >> 31
        ge = borrow == 0  # no final borrow: r >= L, take the difference
        r = [jnp.where(ge, d[j], r[j]) for j in range(17)]
    return r[:16]


def _limbs_to_words(r):
    """16 (N,) uint32 base-2^16 limbs -> (8, N) uint32 packed LE words
    (the k layout the verify grid consumes, batch-minor)."""
    import jax.numpy as jnp

    return jnp.stack([r[2 * w] | (r[2 * w + 1] << 16) for w in range(8)])


# -------------------------------------------------- test oracle entry points
#
# Standalone device pipelines over host arrays — what
# tests/test_challenge.py fuzzes bit-for-bit against the hashvec twins.
# The production path (the derive program below) never leaves the device.


@functools.lru_cache(maxsize=8)
def _digest_fn(nb: int):
    import jax
    import jax.numpy as jnp

    def f(buf):
        st = _compress_pairs(*_pairs_from_be_bytes(buf))
        return jnp.stack(st, axis=1)  # (N, 16): h0hi, h0lo, ...

    return jax.jit(f)


def sha512_rows_device(rows: np.ndarray) -> np.ndarray:
    """(N, L) uint8 same-length rows -> (N, 64) uint8 digests via the
    device lane-pair compression — bit-for-bit hashvec.sha512_rows."""
    n = rows.shape[0]
    if n == 0:
        return np.zeros((0, 64), dtype=np.uint8)
    buf, nb = _hv._sha512_pad(np.ascontiguousarray(rows))
    st = np.asarray(_digest_fn(nb)(buf))  # (N, 16) uint32
    return np.ascontiguousarray(st).astype(">u4").view(np.uint8).reshape(n, 64)


@functools.lru_cache(maxsize=2)
def _reduce_fn():
    import jax
    import jax.numpy as jnp

    def f(w):  # (N, 16) uint32 LE digest words
        limbs = []
        for i in range(16):
            limbs += [w[:, i] & 0xFFFF, w[:, i] >> 16]
        return jnp.transpose(_limbs_to_words(_barrett_mod_l(limbs)))

    return jax.jit(f)


def reduce512_mod_l_device(digests: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 little-endian digests -> (N, 8) uint32 words of
    (value mod L) via the device Barrett rung — bit-for-bit
    hashvec.reduce512_mod_l."""
    n = digests.shape[0]
    if n == 0:
        return np.zeros((0, 8), dtype=np.uint32)
    w = np.ascontiguousarray(digests).view("<u4").reshape(n, 16)
    return np.asarray(_reduce_fn()(w))


# ------------------------------------------------------ prefix/tail table
#
# The device-resident message dictionary: each row is prefix||tail bytes
# (a vote flush's shared sign-bytes prefix plus the batch-common suffix
# tail — chain-id trailer etc.), content-keyed host-side, LRU-evicted,
# delta-synced to the device with the same checksummed-scatter contract
# as the residency key tables. plan_batch captures the device snapshot
# AT PLAN TIME: scatters are functional, so in-flight batches keep their
# immutable table even if later plans evict their rows.

_CHK_MULT = np.uint32(2654435761)  # Knuth multiplicative; position-weighted


def _host_tab_chk(idx: np.ndarray, vals: np.ndarray) -> int:
    w = (np.arange(vals.size, dtype=np.uint32) * _CHK_MULT
         + np.uint32(1))
    chk = np.sum(vals.reshape(-1).astype(np.uint32) * w, dtype=np.uint32)
    chk += np.sum(idx.astype(np.uint32), dtype=np.uint32)
    return int(chk)


@functools.lru_cache(maxsize=8)
def _tab_scatter_fn(db: int):
    import jax
    import jax.numpy as jnp

    def f(tab, idx, vals):
        new = tab.at[idx].set(vals)
        w = (jnp.arange(vals.size, dtype=jnp.uint32) * _CHK_MULT
             + jnp.uint32(1))
        chk = jnp.sum(vals.reshape(-1).astype(jnp.uint32) * w,
                      dtype=jnp.uint32)
        chk = chk + jnp.sum(idx.astype(jnp.uint32), dtype=jnp.uint32)
        return new, chk

    return jax.jit(f)


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class PrefixTable:
    """One put_key's device prefix/tail dictionary: TABLE_ROWS rows of
    PREFIX_CAP bytes, host mirror + dirty-row scatter sync."""

    def __init__(self, put_key: str = "", device=None) -> None:
        self.put_key = put_key
        self._device = device
        self._lock = threading.Lock()
        self._rows: dict[tuple[bytes, bytes], int] = {}  # content -> row
        self._row_key: dict[int, tuple[bytes, bytes]] = {}
        self._lru: dict[tuple[bytes, bytes], None] = {}  # dict order = LRU
        self._host = np.zeros((TABLE_ROWS, PREFIX_CAP), dtype=np.uint8)
        self._dirty: set[int] = set()
        self._tab = None  # device snapshot after last successful sync
        self.version = 0
        self.counters = {"inserts": 0, "hits": 0, "evictions": 0,
                         "upload_failures": 0, "syncs": 0}

    def ensure(self, prefix: bytes, tail: bytes,
               protect: set[int] | None = None) -> int | None:
        """Row index for (prefix, tail), inserting (and evicting LRU) as
        needed. None when the content cannot be resident: over CAP, or
        every evictable row is protected by the in-flight plan."""
        if len(prefix) + len(tail) > PREFIX_CAP:
            return None
        key = (bytes(prefix), bytes(tail))
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                self.counters["hits"] += 1
                self._lru.pop(key, None)
                self._lru[key] = None  # refresh recency
                return row
            if len(self._rows) < TABLE_ROWS:
                row = len(self._rows)
            else:
                victim = None
                for k in self._lru:  # oldest first
                    r = self._rows[k]
                    if protect is None or r not in protect:
                        victim = k
                        break
                if victim is None:
                    return None
                row = self._rows.pop(victim)
                self._lru.pop(victim, None)
                self._row_key.pop(row, None)
                self.counters["evictions"] += 1
            self._rows[key] = row
            self._row_key[row] = key
            self._lru[key] = None
            self._host[row] = 0
            body = key[0] + key[1]
            self._host[row, :len(body)] = np.frombuffer(body, dtype=np.uint8)
            self._dirty.add(row)
            self.version += 1
            self.counters["inserts"] += 1
            return row

    def sync(self):
        """Upload dirty rows (checksummed scatter, one retry) and return
        the device table snapshot, or None when the upload cannot be
        trusted (rows stay dirty; the batch takes the host path)."""
        import jax.numpy as jnp

        with self._lock:
            dirty = sorted(self._dirty)
            if not dirty and self._tab is not None:
                return self._tab
            if not dirty:  # empty table, first use
                self._tab = jnp.zeros((TABLE_ROWS, PREFIX_CAP),
                                      dtype=jnp.uint8)
                return self._tab
            db = _pow2(len(dirty))
            idx = np.full(db, dirty[-1], dtype=np.int32)
            idx[:len(dirty)] = dirty
            vals = self._host[idx]  # padding repeats the last row: idempotent
            base = self._tab
            if base is None:
                base = jnp.zeros((TABLE_ROWS, PREFIX_CAP), dtype=jnp.uint8)
            want = _host_tab_chk(idx, vals)
            fn = _tab_scatter_fn(db)
            from cometbft_tpu.ops import residency as _residency

            for _ in range(2):  # one retry on checksum mismatch
                new, chk = fn(base, idx, vals)
                _residency.record_send("delta", vals.nbytes + idx.nbytes)
                if int(chk) == want:
                    self._tab = new
                    self._dirty.clear()
                    self.counters["syncs"] += 1
                    return self._tab
            self.counters["upload_failures"] += 1
            return None

    def stats(self) -> dict:
        with self._lock:
            return dict(self.counters, rows=len(self._rows),
                        capacity=TABLE_ROWS, version=self.version,
                        dirty=len(self._dirty))


_tables_lock = threading.Lock()
_tables: dict[str, PrefixTable] = {}


def table(put_key: str = "", device=None) -> PrefixTable:
    with _tables_lock:
        t = _tables.get(put_key)
        if t is None:
            t = PrefixTable(put_key, device=device)
            _tables[put_key] = t
        return t


def table_stats() -> dict:
    with _tables_lock:
        return {k or "default": t.stats() for k, t in _tables.items()}


def reset() -> None:
    """Forget every table and counter (tests)."""
    with _tables_lock:
        _tables.clear()
    reset_stats()


# ------------------------------------------------------------ batch planning


class Plan:
    """One batch's device-challenge shape, frozen at plan time: the
    static message geometry the derive program compiles against, the
    per-lane descriptor assignment, and the immutable device table
    snapshot the in-flight batch gathers from."""

    __slots__ = ("plen", "tlen", "var", "slen", "pids", "eligible",
                 "vbytes", "dev_tab", "n", "n_eligible", "n_fallback",
                 "put_key")

    def __init__(self, *, plen, tlen, var, slen, pids, eligible, vbytes,
                 dev_tab, n, n_eligible, n_fallback, put_key):
        self.plen = plen
        self.tlen = tlen
        self.var = var
        self.slen = slen
        self.pids = pids
        self.eligible = eligible
        self.vbytes = vbytes
        self.dev_tab = dev_tab
        self.n = n
        self.n_eligible = n_eligible
        self.n_fallback = n_fallback
        self.put_key = put_key


def plan_batch(msgs, pre_ok, put_key: str = "", device=None) -> Plan | None:
    """Decide the degradation rung for one batch: a Plan when device
    challenge derivation wins (dominant (prefix-len, suffix-len) combo
    covers most live lanes, messages fit the static compile ladder, the
    challenge breaker admits, the table syncs), else None — the caller
    stays on the bit-identical host-challenge path. Lanes outside the
    dominant combo or missing a table row become per-lane host
    fallbacks inside the Plan, never verdict changes."""
    n = len(msgs)
    if not _cfg["enabled"]:
        count("plan_disabled")
        return None
    if n < MIN_LANES:
        count("plan_small")
        return None
    from cometbft_tpu.ops import dispatch as _dispatch

    if not _dispatch.supervisor(SITE).breaker.peek():
        count("plan_breaker_open")
        return None
    from cometbft_tpu.libs.prefixrows import PrefixedMsg

    pre_ok = np.asarray(pre_ok, dtype=bool)
    prefixes: list = [None] * n
    suffixes: list = [None] * n
    combos: dict[tuple[int, int], int] = {}
    for i, m in enumerate(msgs):
        if not pre_ok[i]:
            continue
        if isinstance(m, PrefixedMsg):
            p, s = m.prefix, m.suffix
        else:
            p, s = b"", bytes(m)
        prefixes[i] = p
        suffixes[i] = s
        combos[(len(p), len(s))] = combos.get((len(p), len(s)), 0) + 1
    if not combos:
        count("plan_no_ok_lanes")
        return None
    (plen, slen), nc = max(combos.items(), key=lambda kv: kv[1])
    n_ok = int(pre_ok.sum())
    if plen + slen > MAX_MLEN or plen > PREFIX_CAP:
        count("plan_oversize")
        return None
    if nc < MIN_LANES or nc < MIN_ELIGIBLE_FRAC * n_ok:
        count("plan_low_eligibility")
        return None
    conf = np.zeros(n, dtype=bool)
    for i in range(n):
        conf[i] = (prefixes[i] is not None and len(prefixes[i]) == plen
                   and len(suffixes[i]) == slen)
    cidx = np.flatnonzero(conf)
    if slen:
        sfx = np.frombuffer(
            b"".join(suffixes[i] for i in cidx),
            dtype=np.uint8).reshape(len(cidx), slen)
        # the batch-common trailing run (vote rows: the chain-id trailer
        # after the per-lane timestamp) rides the table row, not the wire
        eqcols = (sfx == sfx[0]).all(axis=0)
        tlen = 0
        for j in range(slen - 1, -1, -1):
            if not eqcols[j]:
                break
            tlen += 1
    else:
        sfx = np.zeros((len(cidx), 0), dtype=np.uint8)
        tlen = 0
    tlen = min(tlen, PREFIX_CAP - plen)
    var = slen - tlen
    if var > MAX_VAR:
        count("plan_oversize_var")
        return None
    tail = sfx[0, slen - tlen:].tobytes() if tlen else b""
    tab = table(put_key, device=device)
    pids = np.full(n, -1, dtype=np.int32)
    protect: set[int] = set()
    misses = 0
    for i in cidx:
        pid = tab.ensure(prefixes[i], tail, protect=protect)
        if pid is None:
            misses += 1
            continue
        protect.add(pid)
        pids[i] = pid
    if misses:
        count("lane_table_miss", misses)
    eligible = pids >= 0
    ne = int(eligible.sum())
    if ne < MIN_LANES or ne < MIN_ELIGIBLE_FRAC * n_ok:
        count("plan_low_eligibility")
        return None
    dev_tab = tab.sync()
    if dev_tab is None:
        count("plan_upload_failed")
        return None
    vbytes = np.zeros((n, var), dtype=np.uint8)
    if var:
        vbytes[cidx] = sfx[:, :var]
    count("plans")
    count("lanes_device", ne)
    count("lanes_host_fallback", n_ok - ne)
    return Plan(plen=plen, tlen=tlen, var=var, slen=slen, pids=pids,
                eligible=eligible, vbytes=vbytes, dev_tab=dev_tab, n=n,
                n_eligible=ne, n_fallback=n_ok - ne, put_key=put_key)


# ------------------------------------------------------------- wire packing


def stream_words(bucket: int, var: int) -> int:
    """uint32 words of descriptor stream for a bucket: 2 descriptor
    bytes per lane plus `var` lane-contiguous suffix bytes per lane."""
    return (2 * bucket + var * bucket + 3) // 4


def block_words(bucket: int, var: int) -> int:
    """Total uint32 words of one flat device-challenge staging block:
    R words, s words, descriptor stream."""
    return 16 * bucket + stream_words(bucket, var)


def fill_stream(block: np.ndarray, bucket: int, plan: Plan) -> None:
    """Pack the descriptor stream of a leased flat block in place:
    per-lane uint16 LE descriptors (bit15 = derive-on-device, low 15
    bits = prefix-table row; 0 for padding/fallback lanes), then the
    lane-contiguous variable suffix bytes."""
    sw = stream_words(bucket, plan.var)
    sb = block[16 * bucket:16 * bucket + sw].view(np.uint8)
    sb[:] = 0
    n = plan.n
    desc = sb[:2 * bucket].view("<u2")
    vals = np.zeros(n, dtype=np.uint16)
    el = plan.eligible
    vals[el] = (0x8000 | plan.pids[el]).astype(np.uint16)
    desc[:n] = vals
    if plan.var:
        v = sb[2 * bucket:2 * bucket + bucket * plan.var]
        v.reshape(bucket, plan.var)[:n] = plan.vbytes


# ----------------------------------------------------- the derive program


def _words_to_bytes(w):
    """(8, B) uint32 LE words -> (B, 32) uint8 encodings (the inverse of
    limbs.bytes_to_words, on device)."""
    import jax.numpy as jnp

    wt = jnp.transpose(w)  # (B, 8)
    parts = jnp.stack([(wt >> (8 * k)) & 0xFF for k in range(4)], axis=-1)
    return parts.reshape(wt.shape[0], 32).astype(jnp.uint8)


@functools.lru_cache(maxsize=32)
def derive_fn(bucket: int, var: int, plen: int, tlen: int, fb: int,
              donate: bool):
    """Compiled derive program for one batch geometry. Signature:

      run(flat, aw, ptab[, fkw, fidx]) -> (flat, kw)

    flat   (block_words,) uint32 — the staged wire block (R words, s
           words, descriptor stream). Returned unchanged as output 0 so
           TPU donation aliases the h2d buffer straight through to the
           verify dispatch (donate=False on CPU, where jit donation is
           unsupported and warns).
      aw   (8, bucket) uint32 — resident pubkey-encoding words for the
           batch's lanes (the residency enc plane; device-resident, not
           this batch's wire).
    ptab   (TABLE_ROWS, PREFIX_CAP) uint8 — the Plan's table snapshot.
     fkw   (8, fb) uint32 host-computed challenge words for fallback
           lanes, fidx (fb,) int32 their lane indices (padded with a
           repeated real index — the scatter is idempotent). fb == 0
           omits both.

    kw is zero for padding/fallback/ineligible lanes before the fkw
    scatter: padded lanes carry identity R / s=0 / k=0, which the verify
    grid accepts — preserving the all-ok happy-path header."""
    import jax
    import jax.numpy as jnp

    tot = 64 + plen + var + tlen
    nb = (tot + 17 + 127) // 128
    padlen = nb * 128 - tot
    pad_np = np.zeros(padlen, dtype=np.uint8)
    pad_np[0] = 0x80
    pad_np[-16:] = np.frombuffer((tot * 8).to_bytes(16, "big"),
                                 dtype=np.uint8)
    sw = stream_words(bucket, var)

    def f(flat, aw, ptab, *fk):
        stream = flat[16 * bucket:16 * bucket + sw]
        sb = jnp.stack([(stream >> (8 * k)) & 0xFF for k in range(4)],
                       axis=-1).reshape(-1).astype(jnp.uint8)
        dlo = sb[0:2 * bucket:2].astype(jnp.uint32)
        dhi = sb[1:2 * bucket:2].astype(jnp.uint32)
        desc = dlo | (dhi << 8)
        use_dev = (desc >> 15).astype(jnp.uint32)
        pid = (desc & 0x7FFF).astype(jnp.int32)
        parts = [_words_to_bytes(flat[:8 * bucket].reshape(8, bucket)),
                 _words_to_bytes(aw)]
        if plen or tlen:
            row = ptab[pid]  # (bucket, PREFIX_CAP) gather off the snapshot
        if plen:
            parts.append(row[:, :plen])
        if var:
            offs = (2 * bucket
                    + jnp.arange(bucket, dtype=jnp.int32)[:, None] * var
                    + jnp.arange(var, dtype=jnp.int32)[None, :])
            parts.append(sb[offs])
        if tlen:
            parts.append(row[:, plen:plen + tlen])
        if padlen:
            parts.append(jnp.broadcast_to(jnp.asarray(pad_np),
                                          (bucket, padlen)))
        msg = jnp.concatenate(parts, axis=1)  # (bucket, nb*128)
        st = _compress_pairs(*_pairs_from_be_bytes(msg))
        kw = _limbs_to_words(_barrett_mod_l(_state_to_limbs(st)))
        kw = kw * use_dev
        if fb:
            fkw, fidx = fk
            kw = kw.at[:, fidx].set(fkw)
        return flat, kw

    if donate:
        return jax.jit(f, donate_argnums=(0,))
    return jax.jit(f)
