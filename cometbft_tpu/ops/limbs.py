"""Host-side numpy packing between wire bytes and device limb arrays.

Field elements travel to the device as (B, 20) int32 arrays of radix-2^13
limbs (little-endian); scalars travel as (B, 253) int32 bit arrays consumed
by the Straus ladder. Packing is vectorized numpy so a 10k-signature commit
stages in well under a millisecond of host time.
"""

from __future__ import annotations

import threading

import numpy as np

RADIX = 13
NLIMBS = 20  # 20 * 13 = 260 bits >= 255
MASK = (1 << RADIX) - 1
SCALAR_BITS = 253  # ZIP-215 enforces s < L < 2^253; k = H mod L < 2^253

_POW2 = (1 << np.arange(RADIX, dtype=np.int64)).astype(np.int64)


def int_to_limbs(x: int) -> np.ndarray:
    """Single Python int -> (20,) int32 limb array."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= RADIX
    assert x == 0, "value exceeds 260 bits"
    return out


def limbs_to_int(limbs: np.ndarray) -> int:
    """(..., 20) limb array -> Python int (single element only)."""
    acc = 0
    for i in reversed(range(NLIMBS)):
        acc = (acc << RADIX) + int(limbs[..., i])
    return acc


def bytes32_to_bits(data: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 -> (B, 256) uint8 bits, little-endian bit order."""
    return np.unpackbits(data, axis=-1, bitorder="little")


def bits_to_limbs(bits: np.ndarray) -> np.ndarray:
    """(B, <=260) bit array -> (B, 20) int32 limbs."""
    b = bits.shape[0]
    padded = np.zeros((b, NLIMBS * RADIX), dtype=np.int64)
    padded[:, : bits.shape[1]] = bits
    return (padded.reshape(b, NLIMBS, RADIX) * _POW2).sum(axis=-1).astype(np.int32)


def encodings_to_point_inputs(enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(B, 32) uint8 compressed-point encodings -> (y_limbs (B,20) int32,
    sign (B,) int32). The y candidate is the low 255 bits, NOT reduced — the
    device field ops are mod-p semantically, so non-canonical y (ZIP-215)
    needs no host handling."""
    bits = bytes32_to_bits(enc)
    sign = bits[:, 255].astype(np.int32)
    y_limbs = bits_to_limbs(bits[:, :255])
    return y_limbs, sign


def scalars_to_bits(scalars: list[int]) -> np.ndarray:
    """List of B ints (< 2^253) -> (B, 253) int32 bit array."""
    raw = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), dtype=np.uint8
    ).reshape(len(scalars), 32)
    return bytes32_to_bits(raw)[:, :SCALAR_BITS].astype(np.int32)


def bytes_to_words(raw: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 -> (B, 8) uint32 little-endian words — the packed
    host->device wire layout consumed by ops.unpack on device."""
    return np.ascontiguousarray(raw).view("<u4").reshape(raw.shape[0], 8)


def scalars_to_words(scalars) -> np.ndarray:
    """B scalars (< 2^256) -> (B, 8) uint32 word array. Accepts a list of
    ints, a bytes blob of B concatenated little-endian 32-byte values, or
    a (B, 32) uint8 array — the bytes/array forms are the staging fast
    path: no per-row int round trip, one view."""
    if isinstance(scalars, (bytes, bytearray, memoryview)):
        raw = np.frombuffer(bytes(scalars), dtype=np.uint8).reshape(-1, 32)
        return bytes_to_words(raw)
    if isinstance(scalars, np.ndarray):
        assert scalars.dtype == np.uint8 and scalars.shape[1] == 32
        return bytes_to_words(scalars)
    raw = np.frombuffer(
        b"".join(s.to_bytes(32, "little") for s in scalars), dtype=np.uint8
    ).reshape(len(scalars), 32)
    return bytes_to_words(raw)


class StagingPool:
    """Per-bucket pool of (3, 8, bucket) uint32 staging blocks — the r/s/k
    word arrays of one device batch, batch-minor, preallocated. The
    stagers (ed25519_kernel.stage_batch / sr25519_kernel.stage_rows_sr)
    pack rows in place into a leased block instead of allocating, joining
    and transposing fresh arrays per batch; the verify thunk releases the
    block once its batch resolves. A block that is never released (error
    paths, bench callers that keep the arrays) is simply garbage-collected
    — the pool is a bounded free list, not a ledger. Leased blocks are
    dirty: stagers overwrite every word, padding lanes included.

    Double-buffer contract (reduced-send protocol): a block is ONE
    contiguous array, so the whole r/s/k payload crosses the tunnel as a
    single transfer (`jnp.asarray(block)` in the dispatch closures), and
    a block stays leased for its batch's full flight — so the steady
    state holds two blocks per bucket (batch N in transfer/compute while
    batch N+1 stages), which is why warm() preallocates pairs and
    MAX_FREE_PER_SHAPE is sized above 2. The dispatch-side half of the
    contract is ops/dispatch.DoubleBuffer: two in-flight slots per fault
    domain, so batch N's h2d overlaps batch N-1's compute.

    The free list is keyed by full block shape: the classic path leases
    (3, 8, bucket) r/s/k planes, the device-challenge path
    (ops/challenge.py) leases flat 1-D word blocks via lease_flat —
    release() routes either kind home by its shape."""

    MAX_FREE_PER_SHAPE = 4

    def __init__(self) -> None:
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self.leases = 0
        self.reuses = 0

    def _lease_shape(self, shape: tuple) -> np.ndarray:
        with self._lock:
            self.leases += 1
            free = self._free.get(shape)
            if free:
                self.reuses += 1
                return free.pop()
        return np.empty(shape, dtype=np.uint32)

    def lease(self, bucket: int) -> np.ndarray:
        return self._lease_shape((3, 8, bucket))

    def lease_flat(self, nwords: int) -> np.ndarray:
        """A flat (nwords,) uint32 block — the device-challenge wire
        layout (R words, s words, descriptor stream)."""
        return self._lease_shape((nwords,))

    def release(self, block: np.ndarray | None) -> None:
        if block is None:
            return
        with self._lock:
            free = self._free.setdefault(block.shape, [])
            if len(free) < self.MAX_FREE_PER_SHAPE:
                free.append(block)

    def _warm_shape(self, shape: tuple, pairs: int) -> None:
        with self._lock:
            free = self._free.setdefault(shape, [])
            while len(free) < min(pairs, self.MAX_FREE_PER_SHAPE):
                free.append(np.empty(shape, dtype=np.uint32))

    def warm(self, bucket: int, pairs: int = 2) -> None:
        """Preallocate `pairs` blocks for a bucket so the first flushes
        of the double-buffered steady state never allocate on the hot
        path (scheduler warmup calls this along the bucket ladder)."""
        self._warm_shape((3, 8, bucket), pairs)

    def warm_flat(self, nwords: int, pairs: int = 2) -> None:
        """warm() for the device-challenge flat blocks."""
        self._warm_shape((nwords,), pairs)

    def stats(self) -> dict:
        with self._lock:
            return {"leases": self.leases, "reuses": self.reuses,
                    "free_blocks": sum(len(v) for v in self._free.values())}


POOL = StagingPool()
