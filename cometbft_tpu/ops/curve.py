"""edwards25519 point operations on TPU vector lanes.

A point is a tuple (X, Y, Z, T) of extended homogeneous coordinates, each a
(20, B) carried limb array (field.py, limb-axis first); one lane = one
point. All formulas
are complete/unified (add-2008-hwcd-3 for a=-1, dbl-2008-hwcd) — branch-free
by construction, exactly what lockstep SIMD lanes need: no special-casing of
identity or equal points, so adversarial inputs (small-order points,
non-canonical encodings; ZIP-215 territory) take the same instruction path
as honest ones.

Semantics mirror the Python oracle (crypto/ed25519_math.py), which mirrors
curve25519-voi's ZIP-215 mode used by the reference
(crypto/ed25519/ed25519.go:37-42).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import ed25519_math as oracle
from cometbft_tpu.ops import field as F


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


# Base point as limb constants, shape (20, 1), broadcastable over batches.
B_X = F._const(oracle.B_POINT[0])
B_Y = F._const(oracle.B_POINT[1])
B_T = F._const(oracle.B_POINT[3])


def identity(shape: tuple[int, ...]) -> Point:
    """(0 : 1 : 1 : 0) broadcast to (20,) + batch shape."""
    zero = jnp.zeros((F.NLIMBS,) + shape, dtype=jnp.int32)
    one = jnp.broadcast_to(F.ONE, (F.NLIMBS,) + shape).astype(jnp.int32)
    return Point(zero, one, one, zero)


def base_point(shape: tuple[int, ...]) -> Point:
    bx = jnp.broadcast_to(B_X, (F.NLIMBS,) + shape).astype(jnp.int32)
    by = jnp.broadcast_to(B_Y, (F.NLIMBS,) + shape).astype(jnp.int32)
    bt = jnp.broadcast_to(B_T, (F.NLIMBS,) + shape).astype(jnp.int32)
    one = jnp.broadcast_to(F.ONE, (F.NLIMBS,) + shape).astype(jnp.int32)
    return Point(bx, by, one, bt)


def add(p: Point, q: Point) -> Point:
    """add-2008-hwcd-3 (unified, a=-1). ~9 field muls."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, F.D2), q.t)
    zz = F.mul(p.z, q.z)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double(p: Point) -> Point:
    """dbl-2008-hwcd. 4 squarings + 4 muls."""
    a = F.sq(p.x)
    b = F.sq(p.y)
    zz = F.sq(p.z)
    c = F.add(zz, zz)
    h = F.add(a, b)
    e = F.sub(h, F.sq(F.add(p.x, p.y)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def neg(p: Point) -> Point:
    return Point(F.neg(p.x), p.y, p.z, F.neg(p.t))


def mul_by_cofactor(p: Point) -> Point:
    return double(double(double(p)))


def is_identity(p: Point) -> jnp.ndarray:
    """(...,) bool: projective identity — X == 0 and Y == Z mod p."""
    return F.is_zero(p.x) & F.is_zero(F.sub(p.y, p.z))


def decompress_zip215(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> tuple[jnp.ndarray, Point]:
    """ZIP-215 decompression: y taken mod p (non-canonical encodings
    accepted — the field ops are redundant mod p so no explicit reduction is
    needed), x recovered per RFC 8032 5.1.3. Returns (ok mask, point); on
    ok == False the point coords are garbage and the caller must mask.
    Oracle: ed25519_math.point_decompress_zip215."""
    y = y_limbs
    yy = F.sq(y)
    one = jnp.broadcast_to(F.ONE, yy.shape).astype(jnp.int32)
    u = F.sub(yy, one)
    v = F.add(F.mul(F.D, yy), one)
    v3 = F.mul(F.sq(v), v)
    v7 = F.mul(F.sq(v3), v)
    x = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    vxx = F.mul(v, F.sq(x))
    root1 = F.is_zero(F.sub(vxx, u))       # v*x^2 == u
    root2 = F.is_zero(F.add(vxx, u))       # v*x^2 == -u -> x *= sqrt(-1)
    x = jnp.where(root1[None], x, F.mul(x, F.SQRT_M1))
    ok = root1 | root2
    xc = F.canonicalize(x)
    x_zero = jnp.all(xc == 0, axis=0)
    ok = ok & ~(x_zero & (sign == 1))      # x=0 with sign bit set: reject
    flip = (xc[0] & 1) != sign
    x = jnp.where(flip[None], F.neg(x), x)
    return ok, Point(x, y, jnp.broadcast_to(F.ONE, y.shape).astype(jnp.int32), F.mul(x, y))


def straus_base_and_point(
    s_bits: jnp.ndarray, k_bits: jnp.ndarray, a: Point
) -> Point:
    """[s]B + [k]A by interleaved (Straus) double-scalar multiplication with
    the shared 4-entry table {O, B, A, B+A} — the same shape as the oracle's
    double_scalar_mult, vectorized: every lane runs the same 253 iterations
    (scalars < 2^253: s < L enforced host-side, k = H mod L), selecting its
    table entry branch-free per bit pair.

    s_bits/k_bits: (253, B) int32 in {0,1}, little-endian bit order along
    axis 0 (bit axis leading, batch on lanes like everything else).
    """
    batch_shape = s_bits.shape[1:]
    nbits = s_bits.shape[0]
    t0 = identity(batch_shape)
    t1 = base_point(batch_shape)
    t2 = a
    t3 = add(t1, a)

    def select(b_s: jnp.ndarray, b_k: jnp.ndarray) -> Point:
        bs = b_s[None]
        bk = b_k[None]
        coords = []
        for c0, c1, c2, c3 in zip(t0, t1, t2, t3):
            lo = jnp.where(bs == 1, c1, c0)
            hi = jnp.where(bs == 1, c3, c2)
            coords.append(jnp.where(bk == 1, hi, lo))
        return Point(*coords)

    def body(it: jnp.ndarray, acc: Point) -> Point:
        i = nbits - 1 - it
        acc = double(acc)
        b_s = jax.lax.dynamic_index_in_dim(s_bits, i, axis=0, keepdims=False)
        b_k = jax.lax.dynamic_index_in_dim(k_bits, i, axis=0, keepdims=False)
        return add(acc, select(b_s, b_k))

    # Derive the identity init from an input so its sharding "varying-ness"
    # matches the loop body under shard_map (a replicated-constant carry
    # would trip the manual-axes vma check).
    zero = jnp.zeros_like(a.x)
    one = zero + F.ONE
    init = Point(zero, one, one, zero)
    return jax.lax.fori_loop(0, nbits, body, init)


# ---------------------------------------------------------------------------
# 4-bit windowed double-scalar multiplication: 64 iterations of 4 doublings
# + 2 table adds, vs the bitwise ladder's 253 x (double + add). The [d]B
# table is a compile-time constant (B is fixed); the [d]A table is built
# per batch (7 doubles + 7 adds). ~23% fewer field muls and a 4x shorter
# loop than straus_base_and_point — shorter dependent chains compile to
# much better TPU code than the 253-iteration dynamic-index loop.
# ---------------------------------------------------------------------------

def _base_table_consts() -> tuple[jnp.ndarray, ...]:
    """[d]B for d in 0..15 as canonical affine-extended limb constants,
    each coord (16, 20, 1) for broadcast over the lane axis."""
    import numpy as np

    from cometbft_tpu.ops import limbs as L

    coords = np.zeros((4, 16, L.NLIMBS), dtype=np.int32)
    pt = oracle.B_POINT
    acc = (0, 1, 1, 0)
    for d in range(16):
        if d:
            acc = oracle.point_add(acc, pt)
        zinv = pow(acc[2], oracle.P - 2, oracle.P)
        x = acc[0] * zinv % oracle.P
        y = acc[1] * zinv % oracle.P
        for ci, v in enumerate((x, y, 1, x * y % oracle.P)):
            coords[ci, d] = L.int_to_limbs(v)
    return tuple(jnp.asarray(coords[ci])[:, :, None] for ci in range(4))


_BASE_TABLE = _base_table_consts()


def build_point_table(a: Point) -> tuple[jnp.ndarray, ...]:
    """{[0]A..[15]A} per lane: each coord stacked (16, 20, B). 7 doubles +
    7 adds, shared across the whole 64-iteration window loop."""
    zero = jnp.zeros_like(a.x)
    one = zero + F.ONE
    t = [Point(zero, one, one, zero), a]
    for d in range(2, 16):
        t.append(double(t[d // 2]) if d % 2 == 0 else add(t[d - 1], a))
    return tuple(jnp.stack([p[ci] for p in t], axis=0) for ci in range(4))


def _select(table: tuple[jnp.ndarray, ...], digit: jnp.ndarray) -> Point:
    """Branch-free table lookup: 4-level binary where-tree over the 16
    entries. table coords (16, 20, B|1), digit (B,) in 0..15 -> Point of
    (20, B). A where-tree beats a gather on TPU: no dynamic indexing, pure
    vector selects."""
    coords = list(table)
    for level in (3, 2, 1, 0):
        bit = ((digit >> level) & 1)[None, None, :] == 1
        half = coords[0].shape[0] // 2
        coords = [jnp.where(bit, c[half:], c[:half]) for c in coords]
    return Point(*(c[0] for c in coords))


def windowed_double_scalar(
    s_digits: jnp.ndarray, k_digits: jnp.ndarray, a: Point
) -> Point:
    """[s]B + [k]A with 4-bit windows. s_digits/k_digits: (64, B) int32
    little-endian window digits (ops.unpack.words_to_digits4). Scalars are
    < 2^253 < 16^64. Complete addition formulas make zero digits (identity
    entries) branch-free no-ops."""
    table_a = build_point_table(a)
    bx = jnp.zeros_like(a.x)
    table_b = tuple(c + bx[None] for c in _BASE_TABLE)  # broadcast to lanes

    # most-significant digit first
    sd = s_digits[::-1]
    kd = k_digits[::-1]

    def body(acc: Point, digs):
        ds, dk = digs
        acc = double(double(double(double(acc))))
        acc = add(acc, _select(table_a, dk))
        acc = add(acc, _select(table_b, ds))
        return acc, None

    zero = jnp.zeros_like(a.x)
    one = zero + F.ONE
    init = Point(zero, one, one, zero)
    acc, _ = jax.lax.scan(body, init, (sd, kd))
    return acc
