"""edwards25519 point operations on TPU vector lanes.

A point is a tuple (X, Y, Z, T) of extended homogeneous coordinates, each a
(20, B) carried limb array (field.py, limb-axis first); one lane = one
point. All formulas
are complete/unified (add-2008-hwcd-3 for a=-1, dbl-2008-hwcd) — branch-free
by construction, exactly what lockstep SIMD lanes need: no special-casing of
identity or equal points, so adversarial inputs (small-order points,
non-canonical encodings; ZIP-215 territory) take the same instruction path
as honest ones.

Semantics mirror the Python oracle (crypto/ed25519_math.py), which mirrors
curve25519-voi's ZIP-215 mode used by the reference
(crypto/ed25519/ed25519.go:37-42).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import ed25519_math as oracle
from cometbft_tpu.ops import field as F


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


# Base point as limb constants, shape (20, 1), broadcastable over batches.
B_X = F._const(oracle.B_POINT[0])
B_Y = F._const(oracle.B_POINT[1])
B_T = F._const(oracle.B_POINT[3])


def identity(shape: tuple[int, ...]) -> Point:
    """(0 : 1 : 1 : 0) broadcast to (20,) + batch shape."""
    zero = jnp.zeros((F.NLIMBS,) + shape, dtype=jnp.int32)
    one = jnp.broadcast_to(F.ONE, (F.NLIMBS,) + shape).astype(jnp.int32)
    return Point(zero, one, one, zero)


def base_point(shape: tuple[int, ...]) -> Point:
    bx = jnp.broadcast_to(B_X, (F.NLIMBS,) + shape).astype(jnp.int32)
    by = jnp.broadcast_to(B_Y, (F.NLIMBS,) + shape).astype(jnp.int32)
    bt = jnp.broadcast_to(B_T, (F.NLIMBS,) + shape).astype(jnp.int32)
    one = jnp.broadcast_to(F.ONE, (F.NLIMBS,) + shape).astype(jnp.int32)
    return Point(bx, by, one, bt)


def add(p: Point, q: Point) -> Point:
    """add-2008-hwcd-3 (unified, a=-1). ~9 field muls."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q.y, q.x))
    b = F.mul(F.add(p.y, p.x), F.add(q.y, q.x))
    c = F.mul(F.mul(p.t, F.D2), q.t)
    zz = F.mul(p.z, q.z)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double(p: Point) -> Point:
    """dbl-2008-hwcd. 4 squarings + 4 muls. Never reads p.t."""
    a = F.sq(p.x)
    b = F.sq(p.y)
    zz = F.sq(p.z)
    c = F.add(zz, zz)
    h = F.add(a, b)
    e = F.sub(h, F.sq(F.add(p.x, p.y)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def double_no_t(p: Point) -> Point:
    """double without materializing T (4 sq + 3 muls): doubling never reads
    its input's T, so runs of doublings only need T on the last one — 3 of
    every 5 ladder muls saved. The returned T is zeros and MUST NOT feed an
    add."""
    a = F.sq(p.x)
    b = F.sq(p.y)
    zz = F.sq(p.z)
    c = F.add(zz, zz)
    h = F.add(a, b)
    e = F.sub(h, F.sq(F.add(p.x, p.y)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), jnp.zeros_like(p.x))


def neg(p: Point) -> Point:
    return Point(F.neg(p.x), p.y, p.z, F.neg(p.t))


# --------------------------------------------------------------------------
# Premultiplied-T adds: table entries store t' = D2*t, turning the addition
# formula's c = (t1*D2)*t2 two-mul chain into one mul. Build tables with
# true T (chained construction needs it), premultiply once at the end.
# --------------------------------------------------------------------------


def add_pre(p: Point, q_pre: Point, out_t: bool = True) -> Point:
    """add-2008-hwcd-3 where q.t is premultiplied by D2: 8 muls, 7 without
    the output T. p.t is the TRUE extended coordinate."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q_pre.y, q_pre.x))
    b = F.mul(F.add(p.y, p.x), F.add(q_pre.y, q_pre.x))
    c = F.mul(p.t, q_pre.t)
    zz = F.mul(p.z, q_pre.z)
    d = F.add(zz, zz)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    t = F.mul(e, h) if out_t else jnp.zeros_like(p.x)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), t)


def madd_pre(p: Point, q_pre: Point, out_t: bool = True) -> Point:
    """Mixed add: q is affine (Z=1) with premultiplied T — 7 muls, 6
    without the output T."""
    a = F.mul(F.sub(p.y, p.x), F.sub(q_pre.y, q_pre.x))
    b = F.mul(F.add(p.y, p.x), F.add(q_pre.y, q_pre.x))
    c = F.mul(p.t, q_pre.t)
    d = F.add(p.z, p.z)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    t = F.mul(e, h) if out_t else jnp.zeros_like(p.x)
    return Point(F.mul(e, f), F.mul(g, h), F.mul(f, g), t)


def mul_by_cofactor(p: Point) -> Point:
    return double(double(double(p)))


def is_identity(p: Point) -> jnp.ndarray:
    """(...,) bool: projective identity — X == 0 and Y == Z mod p."""
    return F.is_zero(p.x) & F.is_zero(F.sub(p.y, p.z))


def decompress_zip215(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> tuple[jnp.ndarray, Point]:
    """ZIP-215 decompression: y taken mod p (non-canonical encodings
    accepted — the field ops are redundant mod p so no explicit reduction is
    needed), x recovered per RFC 8032 5.1.3. Returns (ok mask, point); on
    ok == False the point coords are garbage and the caller must mask.
    Oracle: ed25519_math.point_decompress_zip215."""
    y = y_limbs
    yy = F.sq(y)
    one = jnp.broadcast_to(F.ONE, yy.shape).astype(jnp.int32)
    u = F.sub(yy, one)
    v = F.add(F.mul(F.D, yy), one)
    v3 = F.mul(F.sq(v), v)
    v7 = F.mul(F.sq(v3), v)
    x = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    vxx = F.mul(v, F.sq(x))
    root1 = F.is_zero(F.sub(vxx, u))       # v*x^2 == u
    root2 = F.is_zero(F.add(vxx, u))       # v*x^2 == -u -> x *= sqrt(-1)
    x = jnp.where(root1[None], x, F.mul(x, F.SQRT_M1))
    ok = root1 | root2
    xc = F.canonicalize(x)
    x_zero = jnp.all(xc == 0, axis=0)
    ok = ok & ~(x_zero & (sign == 1))      # x=0 with sign bit set: reject
    flip = (xc[0] & 1) != sign
    x = jnp.where(flip[None], F.neg(x), x)
    return ok, Point(x, y, jnp.broadcast_to(F.ONE, y.shape).astype(jnp.int32), F.mul(x, y))


# ---------------------------------------------------------------------------
# Signed 5-bit ladder: 51 windows x (5 doublings + 2 adds) with digits in
# [-16, 15] (ops.unpack.words_to_digits5_signed). vs the 4-bit ladder's
# 64 x (4 dbl + 2 add):
#   - 255 doublings, 4 of every 5 skipping the T mul (double_no_t)
#   - 102 adds, the base half mixed (madd: Z=1) and all adds one mul
#     cheaper via premultiplied table T (add_pre/madd_pre); the A-add skips
#     its T output on every window but the last (only the final add(-R)
#     reads it)
# Negative digits select the negated entry lane-locally (x, t sign flip) —
# table stays 17 entries, so VMEM footprint is ~equal to the 16-entry
# unsigned table.
# ---------------------------------------------------------------------------

TABLE17 = 17  # entries 0..16


def _base_table17_consts() -> tuple[jnp.ndarray, ...]:
    """[d]B for d in 0..16, affine with premultiplied T: coords (17, 20, 1)
    (x, y, z=1, t*2d)."""
    import numpy as np

    from cometbft_tpu.ops import limbs as L

    coords = np.zeros((4, TABLE17, L.NLIMBS), dtype=np.int32)
    pt = oracle.B_POINT
    acc = (0, 1, 1, 0)
    d2 = F._D_INT * 2 % oracle.P
    for d in range(TABLE17):
        if d:
            acc = oracle.point_add(acc, pt)
        zinv = pow(acc[2], oracle.P - 2, oracle.P)
        x = acc[0] * zinv % oracle.P
        y = acc[1] * zinv % oracle.P
        for ci, v in enumerate((x, y, 1, x * y % oracle.P * d2 % oracle.P)):
            coords[ci, d] = L.int_to_limbs(v)
    return tuple(jnp.asarray(coords[ci])[:, :, None] for ci in range(4))


_BASE_TABLE17 = _base_table17_consts()


def build_point_table17(a: Point) -> tuple[jnp.ndarray, ...]:
    """{[0]A..[16]A} per lane with premultiplied T: coords (17, 20, B).
    15 point ops + one T-premul pass."""
    zero = jnp.zeros_like(a.x)
    one = zero + F.ONE
    t = [Point(zero, one, one, zero), a]
    for d in range(2, TABLE17):
        t.append(double(t[d // 2]) if d % 2 == 0 else add(t[d - 1], a))
    d2 = jnp.broadcast_to(F.D2, a.x.shape).astype(jnp.int32)
    t = [Point(p.x, p.y, p.z, F.mul(p.t, d2)) for p in t]
    return tuple(jnp.stack([p[ci] for p in t], axis=0) for ci in range(4))


def _select17_signed(table: tuple[jnp.ndarray, ...], digit: jnp.ndarray) -> Point:
    """Branch-free signed lookup: |d| via 4-level where-tree over entries
    0..15 plus one fixup where for entry 16, then lane-local negation (x, t
    sign flip — valid for premultiplied t too) where d < 0."""
    neg_mask = (digit < 0)[None, :]
    mag = jnp.abs(digit)
    coords = [c[:16] for c in table]
    for level in (3, 2, 1, 0):
        bit = ((mag >> level) & 1)[None, None, :] == 1
        half = coords[0].shape[0] // 2
        coords = [jnp.where(bit, c[half:], c[:half]) for c in coords]
    is16 = (mag == 16)[None, :]
    x, y, z, t = (jnp.where(is16, table[ci][16], coords[ci][0]) for ci in range(4))
    x = jnp.where(neg_mask, F.neg(x), x)
    t = jnp.where(neg_mask, F.neg(t), t)
    return Point(x, y, z, t)


def window_step(
    acc: Point, ds: jnp.ndarray, dk: jnp.ndarray, table_b, table_a,
    out_t: bool,
) -> Point:
    """One ladder window: 5 doublings (4 skipping T) + base madd + A add.
    The base add goes first (mixed, produces the T the A add consumes);
    out_t=False elides the A-add's T mul — legal on every window except the
    last, because the next window re-derives T in its final double()."""
    for _ in range(4):
        acc = double_no_t(acc)
    acc = double(acc)
    acc = madd_pre(acc, _select17_signed(table_b, ds), out_t=True)
    return add_pre(acc, _select17_signed(table_a, dk), out_t=out_t)


def windowed_double_scalar_signed(
    s_digits: jnp.ndarray, k_digits: jnp.ndarray, a: Point
) -> Point:
    """[s]B + [k]A, signed 5-bit windows. s_digits/k_digits: (51, B) int32
    in [-16, 15], little-endian (ops.unpack.words_to_digits5_signed)."""
    table_a = build_point_table17(a)
    bx = jnp.zeros_like(a.x)
    table_b = tuple(c + bx[None] for c in _BASE_TABLE17)

    sd = s_digits[::-1][:-1]  # MSB-first, final (LSB) window handled below
    kd = k_digits[::-1][:-1]

    def body(acc: Point, digs):
        ds, dk = digs
        return window_step(acc, ds, dk, table_b, table_a, out_t=False), None

    zero = jnp.zeros_like(a.x)
    one = zero + F.ONE
    init = Point(zero, one, one, zero)
    acc, _ = jax.lax.scan(body, init, (sd, kd))
    return window_step(
        acc, s_digits[0], k_digits[0], table_b, table_a, out_t=True
    )
