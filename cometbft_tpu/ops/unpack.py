"""Device-side unpacking of wire-format bytes into limbs / window digits.

Signatures, scalars and point encodings travel host->device as packed
uint32 words — (8, B) per 32-byte item, batch on the minor (lane) axis —
and are expanded to radix-2^13 limbs or 4-bit window digits ON DEVICE with
shift/mask ops. Rationale: the host link is the bottleneck (the axon tunnel
moves ~22 MB/s with an ~89 ms round-trip floor); shipping a 10k-signature
commit as bit-arrays was 25 MB, as packed words it is ~1 MB. The reference
has no analog (its verifier consumes Go byte slices in-core,
crypto/ed25519/ed25519.go:208-241); this is the TPU-native wire layout.

Host-side packing counterpart: limbs.bytes_to_words.
"""

from __future__ import annotations

import jax.numpy as jnp

from cometbft_tpu.ops import limbs as L

WORDS = 8  # 32 bytes = 8 little-endian uint32 words


def words_to_y_limbs(w: jnp.ndarray) -> jnp.ndarray:
    """(8, B) uint32 point encodings -> (20, B) int32 y limbs (low 255
    bits, canonical 13-bit limbs; limb 19 is 8 bits). The sign bit (bit
    255) is excluded — see words_sign."""
    out = []
    for i in range(L.NLIMBS):
        bit = L.RADIX * i
        wi, off = bit // 32, bit % 32
        v = w[wi] >> off if off else w[wi]
        if off > 32 - L.RADIX and wi + 1 < WORDS:
            v = v | (w[wi + 1] << (32 - off))
        mask = 0xFF if i == L.NLIMBS - 1 else L.MASK  # limb 19: bits 247..254
        out.append((v & mask).astype(jnp.int32))
    return jnp.stack(out, axis=0)


def words_sign(w: jnp.ndarray) -> jnp.ndarray:
    """(8, B) uint32 -> (B,) int32 sign bit (bit 255)."""
    return (w[WORDS - 1] >> 31).astype(jnp.int32)


# Scalars are < L < 2^253: digit 50 covers bits 250..254, of which bits
# 253/254 are always zero, so its raw value is <= 7 and even with a ripple
# carry (+1) stays < 16 — the signed recoding never carries out of digit 50.
# Hence 51 digits, not ceil(256/5) + 1 = 53: each digit trimmed deletes a
# full ladder window (5 doublings + 2 adds = ~51 field muls per signature).
NDIGITS5 = 51


def words_to_digits5_signed(w: jnp.ndarray) -> jnp.ndarray:
    """(8, B) uint32 scalar words -> (51, B) int32 SIGNED 5-bit window
    digits in [-16, 15], little-endian: scalar = sum d_j * 32^j. Standard
    signed recoding (d >= 16 -> d - 32, carry 1 up) shortens the ladder to
    51 windows of 5 doublings and, because -d selects as a lane-local
    negation, keeps the table at 17 entries. The carry ripple is a 51-step
    scan over (B,) rows — noise next to one field mul."""
    raw = []
    for j in range(NDIGITS5):
        bit = 5 * j
        wi, off = bit // 32, bit % 32
        if wi >= WORDS:
            v = jnp.zeros_like(w[0])
        else:
            v = w[wi] >> off if off else w[wi]
            if off > 27 and wi + 1 < WORDS:
                v = v | (w[wi + 1] << (32 - off))
        raw.append((v & 31).astype(jnp.int32))
    digits = jnp.stack(raw, axis=0)  # (51, B) in [0, 31]

    import jax

    # The carry ripple c_{j+1} = (v_j + c_j >= 16) is a generate/propagate
    # chain (generate: v_j >= 16; propagate the incoming carry: v_j == 15),
    # exactly an adder carry-lookahead — solved with a log-depth
    # associative scan (6 levels for 51 digits) instead of a 51-step
    # sequential lax.scan.
    g = (digits >= 16)
    p = (digits == 15)

    def op(a, b):
        ga, pa = a
        gb, pb = b
        return ga & pb | gb, pa & pb

    gacc, _ = jax.lax.associative_scan(op, (g, p), axis=0)
    carry_in = jnp.concatenate(
        [jnp.zeros_like(gacc[:1]), gacc[:-1]], axis=0).astype(jnp.int32)
    d = digits + carry_in
    signed = d - 32 * (d >= 16).astype(jnp.int32)
    # the carry out of the top digit is provably zero for scalars < 2^253
    # (see the NDIGITS5 comment: digit 50's post-carry value is <= 8 < 16);
    # callers enforce s, k < L < 2^253 host-side (ed25519_kernel.stage_batch
    # rejects s >= L, k is reduced mod L).
    return signed
