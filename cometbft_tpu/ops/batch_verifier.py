"""crypto.BatchVerifier backed by the TPU kernel (the `tpu` backend that
crypto/batch registers — reference seam: crypto/batch/batch.go:11-32,
crypto/ed25519/ed25519.go:208-241)."""

from __future__ import annotations

from cometbft_tpu import crypto
from cometbft_tpu.ops import ed25519_kernel

SIGNATURE_SIZE = 64
PUB_KEY_SIZE = 32


class TPUBatchVerifier(crypto.BatchVerifier):
    """add() stages host-side (cheap); verify() is the device sync point.
    Returns (all_valid, per-lane mask) — mask is the kernel's lane output,
    not a serial re-check."""

    def __init__(self, cache: ed25519_kernel.PubKeyCache | None = None):
        self._pubs: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []
        self._cache = cache

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type_() != "ed25519":
            raise crypto.ErrInvalidKey("tpu batch verifier requires ed25519 keys")
        if len(sig) != SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._pubs.append(pub_key.bytes_())
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(sig))

    def verify(self) -> tuple[bool, list[bool]]:
        return ed25519_kernel.verify_batch(
            self._pubs, self._msgs, self._sigs, cache=self._cache
        )

    def verify_async(self):
        """Dispatch without blocking; resolve via
        ed25519_kernel.resolve_batches (MixedBatchVerifier coalesces the
        fetch across schemes)."""
        return ed25519_kernel.verify_batch_async(
            self._pubs, self._msgs, self._sigs, cache=self._cache
        )

    def count(self) -> int:
        return len(self._sigs)


class SrTPUBatchVerifier(crypto.BatchVerifier):
    """sr25519 on the device: same ladder kernel family, ristretto decode +
    cofactor-4 coset equality (ops/sr25519_kernel.py; reference seam
    crypto/sr25519/batch.go:45-78)."""

    def __init__(self):
        self._pubs: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type_() != "sr25519":
            raise crypto.ErrInvalidKey("sr25519 tpu batch verifier requires sr25519 keys")
        if len(sig) != SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._pubs.append(pub_key.bytes_())
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(sig))

    def verify(self) -> tuple[bool, list[bool]]:
        from cometbft_tpu.ops import sr25519_kernel

        return sr25519_kernel.verify_batch(self._pubs, self._msgs, self._sigs)

    def verify_async(self):
        from cometbft_tpu.ops import sr25519_kernel

        return sr25519_kernel.verify_batch_async(
            self._pubs, self._msgs, self._sigs)

    def count(self) -> int:
        return len(self._sigs)


class BlsTPUBatchVerifier(crypto.BatchVerifier):
    """BLS12-381 batched single-verify on the device (ops/bls_kernel.py:
    one 2B-wide Miller loop + vectorized final exponentiations). 96-byte
    G2 signatures; the aggregate commit path lives in
    bls_kernel.aggregate_verify, not behind this per-lane seam."""

    SIGNATURE_SIZE = 96

    def __init__(self):
        self._pubs: list[bytes] = []
        self._msgs: list[bytes] = []
        self._sigs: list[bytes] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type_() != "bls12381":
            raise crypto.ErrInvalidKey(
                "bls12381 tpu batch verifier requires bls12381 keys")
        if len(sig) != self.SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._pubs.append(pub_key.bytes_())
        self._msgs.append(bytes(msg))
        self._sigs.append(bytes(sig))

    def verify(self) -> tuple[bool, list[bool]]:
        from cometbft_tpu.ops import bls_kernel

        return bls_kernel.verify_batch(self._pubs, self._msgs, self._sigs)

    def verify_async(self):
        from cometbft_tpu.ops import bls_kernel

        return bls_kernel.verify_batch_async(
            self._pubs, self._msgs, self._sigs)

    def count(self) -> int:
        return len(self._sigs)
